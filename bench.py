"""Round benchmark: EC encode+decode GB/s at k=8,m=4 on the attached TPU.

Mirrors the reference's benchmark semantics
(src/test/erasure-code/ceph_erasure_code_benchmark.cc:151-190 encode,
:255-328 decode: GB/s = iterations x object_size / seconds, decode
pre-encodes once then reconstructs erased chunks and verifies equality)
for the BASELINE.md headline config: isa-equivalent RS k=8 m=4, 1 MiB
chunks.  The baseline divisor is the native C++ GF(2^8) scalar oracle
(csrc/gf256.cc) measured on this host's CPU, standing in for the
reference's table-based plugins (ISA-L itself is x86-asm and absent).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""

import json
import sys
import time

import numpy as np


def _bench(fn, warmup=2, iters=10):
    for _ in range(warmup):
        out = fn()
    _block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _block(out)
    return (time.perf_counter() - t0) / iters


def _block(out):
    if hasattr(out, "block_until_ready"):
        out.block_until_ready()


def main():
    import jax

    from ceph_tpu import _native
    from ceph_tpu.ec import matrices
    from ceph_tpu.ops import gf2_matmul

    k, m = 8, 4
    n = 1 << 20  # 1 MiB chunks -> 8 MiB object per encode
    rng = np.random.default_rng(0)
    coding = matrices.isa_cauchy(k, m)
    mbits = gf2_matmul.prepare_bitmatrix(coding)
    x = rng.integers(0, 256, size=(k, n), dtype=np.uint8)

    backend = jax.default_backend()
    xd = jax.device_put(x)
    md = jax.device_put(mbits)

    def encode():
        return gf2_matmul.gf2_matmul_bytes(md, xd)

    # correctness pin vs the native oracle before timing anything
    native_coding = _native.rs_encode(coding.astype(np.uint8), x[:, :4096])
    got = np.asarray(encode())[:, :4096]
    assert np.array_equal(got, native_coding), "TPU encode != native oracle"

    enc_dt = _bench(encode)
    enc_gbps = k * n / enc_dt / 1e9

    # decode: erase m chunks (2 data + 2 coding), rebuild data rows from
    # the k survivors via the cached recovery matrix (one bit-matmul)
    from ceph_tpu.ec.codec import RSMatrixCodec

    codec = RSMatrixCodec(k, m, coding)
    coding_rows = np.asarray(encode())
    survivors = [0, 1, 2, 3, 4, 5, 8, 9]  # lost data 6,7 and coding 10,11
    _, rec_bits = codec.recovery_matrix(survivors)
    stacked = np.concatenate([x[:6], coding_rows[:2]])
    sd = jax.device_put(stacked)
    rd = jax.device_put(rec_bits)

    def decode():
        return gf2_matmul.gf2_matmul_bytes(rd, sd)

    dec = np.asarray(decode())
    assert np.array_equal(dec, x), "TPU decode != original data"
    dec_dt = _bench(decode)
    dec_gbps = k * n / dec_dt / 1e9

    # CPU baseline: the same encode through the scalar native oracle
    base_n = 1 << 22  # 4 MiB total is plenty for a stable scalar rate
    xb = x[:, : base_n // k]
    cm = coding.astype(np.uint8)
    base_dt = _bench(lambda: _native.rs_encode(cm, xb), warmup=1, iters=3)
    base_gbps = xb.size / base_dt / 1e9

    value = 2 * k * n / (enc_dt + dec_dt) / 1e9  # combined encode+decode
    print(
        json.dumps(
            {
                "metric": f"EC encode+decode GB/s (RS k={k},m={m}, 1MiB chunks, {backend})",
                "value": round(value, 3),
                "unit": "GB/s",
                "vs_baseline": round(value / base_gbps, 3),
                "encode_gbps": round(enc_gbps, 3),
                "decode_gbps": round(dec_gbps, 3),
                "baseline_cpu_native_gbps": round(base_gbps, 3),
                "backend": backend,
            }
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # one line, always
        print(json.dumps({"metric": "bench-error", "value": 0, "unit": "GB/s",
                          "vs_baseline": 0, "error": repr(e)}))
        sys.exit(1)
