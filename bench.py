"""Round benchmark: EC encode+decode sweep + CRUSH placement sweep.

Mirrors the reference's benchmark semantics:
- EC: GB/s = object_bytes / seconds for encode, and for decode after
  erasing m chunks and verifying reconstructed equality
  (src/test/erasure-code/ceph_erasure_code_benchmark.cc:151-190 encode,
  :255-328 decode), swept over 4 KiB - 4 MiB objects like
  qa/workunits/erasure-code/bench.sh:103-145.
- CRUSH: placements/sec for a full-cluster sweep of object ids over a
  1024-OSD straw2 map (BASELINE metric 6; the CrushTester/psim loop,
  src/crush/CrushTester.cc:472, src/tools/psim.cc:64), measured against
  the REFERENCE's own C crush_do_rule batch rate (libcrush_ref.so,
  compiled from /root/reference/src/crush/).

Engines under test: the packed SWAR GF(2^8) xor network
(ceph_tpu/ops/gf256_swar.py) and the vmapped straw2 interpreter
(ceph_tpu/crush/mapper.py).  CPU baseline for EC is the native scalar
C++ oracle (csrc/gf256.cc).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""

import json
import sys
import time

import numpy as np

K, M = 8, 4
HBM_PEAK_GBPS = 819.0  # v5e


def _block(out):
    if hasattr(out, "block_until_ready"):
        out.block_until_ready()


def _bench(fn, warmup=2, iters=10):
    out = None
    for _ in range(warmup):
        out = fn()
    _block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _block(out)
    return (time.perf_counter() - t0) / iters


def ec_sweep(jax, out):
    from ceph_tpu import _native
    from ceph_tpu.ec import matrices
    from ceph_tpu.ec.codec import RSMatrixCodec
    from ceph_tpu.ops import gf256_swar

    coding = matrices.isa_cauchy(K, M)
    codec = RSMatrixCodec(K, M, coding)
    rng = np.random.default_rng(0)
    survivors = [0, 1, 2, 3, 4, 5, 8, 9]  # lose data 6,7 + coding 2,3
    rec, _ = codec.recovery_matrix(survivors)

    sweep = {}
    for size in (4096, 65536, 1 << 20, 4 << 20):
        n = size // K
        x = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
        xd = jax.device_put(x)

        enc = lambda: gf256_swar.gf_matmul_bytes(coding, xd)  # noqa: E731
        coded = np.asarray(enc())
        # correctness pin vs the native oracle before timing anything
        want = _native.rs_encode(coding.astype(np.uint8), x[:, :4096])
        assert np.array_equal(coded[:, :4096], want), "encode != oracle"

        surv = np.stack([x[s] if s < K else coded[s - K] for s in survivors])
        sd = jax.device_put(surv)
        dec = lambda: gf256_swar.gf_matmul_bytes(rec, sd)  # noqa: E731
        assert np.array_equal(np.asarray(dec()), x), "decode != data"

        enc_dt = _bench(enc)
        dec_dt = _bench(dec)
        sweep[str(size)] = {
            "encode_gbps": round(size / enc_dt / 1e9, 3),
            "decode_gbps": round(size / dec_dt / 1e9, 3),
        }

    # headline at 1 MiB
    head = sweep[str(1 << 20)]
    out["ec_sweep"] = sweep
    out["encode_gbps"] = head["encode_gbps"]
    out["decode_gbps"] = head["decode_gbps"]
    # roofline: encode moves (k+m)/k x the object bytes over HBM
    out["encode_hbm_frac"] = round(
        head["encode_gbps"] * (K + M) / K / HBM_PEAK_GBPS, 3)

    # CPU baseline: the same encode through the scalar native oracle
    n = (1 << 20) // K
    xb = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
    cm = coding.astype(np.uint8)
    base_dt = _bench(lambda: _native.rs_encode(cm, xb), warmup=1, iters=3)
    out["baseline_cpu_native_gbps"] = round((1 << 20) / base_dt / 1e9, 3)
    return head, out["baseline_cpu_native_gbps"]


def crush_sweep(jax, out):
    from ceph_tpu import _crush_ref
    from ceph_tpu.crush import map as cmap
    from ceph_tpu.crush import mapper

    n_osds, n_hosts, nrep = 1024, 64, 3
    m, root = cmap.build_flat_cluster(n_osds, hosts=n_hosts)
    steps = [(cmap.OP_TAKE, root, 0),
             (cmap.OP_CHOOSELEAF_FIRSTN, nrep, 1),
             (cmap.OP_EMIT, 0, 0)]
    flat = m.flatten()
    dev_w = np.full(n_osds, 0x10000, dtype=np.uint32)
    fn = mapper.compile_rule(flat, steps, nrep)

    # BASELINE metric 6 is 10M ids; a CPU-backend run (sanity only)
    # scales down or the sweep itself takes minutes
    n_x = 10_000_000 if jax.default_backend() != "cpu" else 200_000
    xs = np.arange(n_x, dtype=np.int32)
    xs_d = jax.device_put(xs)
    w_d = jax.device_put(dev_w)
    dt = _bench(lambda: fn(xs_d, w_d), warmup=1, iters=3)
    out["crush_mplacements_per_s"] = round(n_x / dt / 1e6, 2)

    # reference C rate, extrapolated from 200k ids
    if _crush_ref.available():
        m.add_rule(cmap.Rule("bench", steps))
        ref = _crush_ref.RefCrushMap(m)
        sub = xs[:200_000]
        t0 = time.perf_counter()
        ref_out = ref.do_rule(ref.rulenos[-1], sub, nrep, dev_w)
        ref_dt = time.perf_counter() - t0
        out["crush_ref_c_mplacements_per_s"] = round(
            len(sub) / ref_dt / 1e6, 2)
        out["crush_vs_ref_c"] = round(
            out["crush_mplacements_per_s"]
            / out["crush_ref_c_mplacements_per_s"], 2)
        # spot conformance on the first ids
        got = np.asarray(fn(xs_d[:1000], w_d))
        assert np.array_equal(got, ref_out[:1000]), "sweep != reference C"


def main():
    import jax

    out = {"backend": jax.default_backend()}
    head, base = ec_sweep(jax, out)
    crush_sweep(jax, out)

    value = round(
        2 / (1 / head["encode_gbps"] + 1 / head["decode_gbps"]), 3)
    out.update({
        "metric": (f"EC encode+decode GB/s (RS k={K},m={M}, 1MiB object, "
                   f"{out['backend']}) + CRUSH 10M-id sweep"),
        "value": value,
        "unit": "GB/s",
        "vs_baseline": round(value / base, 2),
    })
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # one line, always
        print(json.dumps({"metric": "bench-error", "value": 0, "unit": "GB/s",
                          "vs_baseline": 0, "error": repr(e)}))
        sys.exit(1)
