"""Round benchmark: EC encode+decode sweep + CRUSH placement sweep.

Mirrors the reference's benchmark semantics:
- EC: GB/s = object_bytes / seconds for encode, and for decode after
  erasing m chunks and verifying reconstructed equality
  (src/test/erasure-code/ceph_erasure_code_benchmark.cc:151-190 encode,
  :255-328 decode), swept over 4 KiB - 4 MiB objects like
  qa/workunits/erasure-code/bench.sh:103-145.
- CRUSH: placements/sec for a full-cluster sweep of object ids over a
  1024-OSD straw2 map (BASELINE metric 6; the CrushTester/psim loop,
  src/crush/CrushTester.cc:472, src/tools/psim.cc:64), measured against
  the REFERENCE's own C crush_do_rule batch rate (libcrush_ref.so,
  compiled from /root/reference/src/crush/).

Engines under test: the packed SWAR GF(2^8) xor network
(ceph_tpu/ops/gf256_swar.py) and the vmapped straw2 interpreter
(ceph_tpu/crush/mapper.py).  CPU baseline for EC is the native scalar
C++ oracle (csrc/gf256.cc) — NOTE: that is a scalar C++ loop, NOT
ISA-L; real ISA-L does multiple GB/s/core with AVX.

Fault isolation: every section appends into one result dict and catches
its own exceptions (recorded under "errors"), so a late CRUSH failure
can never discard the EC numbers (the round-2 artifact failure mode).
Exactly ONE JSON line is always printed:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""

import json
import sys
import time
import traceback

import numpy as np

K, M = 8, 4
HBM_PEAK_GBPS = 819.0  # v5e
CRUSH_IDS = 10_000_000  # BASELINE metric 6
CRUSH_CHUNK = 1 << 19  # ids per device dispatch: bounds live HBM temps


def _block(out):
    if hasattr(out, "block_until_ready"):
        out.block_until_ready()


def _bench(fn, warmup=2, iters=10):
    out = None
    for _ in range(warmup):
        out = fn()
    _block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _block(out)
    return (time.perf_counter() - t0) / iters


def _suspect(gbps, bytes_moved_per_byte=1.0):
    """Roofline sanity: effective HBM traffic above peak is impossible —
    flag it rather than report it as a win (round-2 Weak #5)."""
    return bool(gbps * bytes_moved_per_byte > HBM_PEAK_GBPS)


def ec_sweep(jax, out):
    from ceph_tpu import _native
    from ceph_tpu.ec import matrices
    from ceph_tpu.ec.codec import RSMatrixCodec
    from ceph_tpu.ops import gf256_swar

    coding = matrices.isa_cauchy(K, M)
    codec = RSMatrixCodec(K, M, coding)
    rng = np.random.default_rng(0)
    survivors = [0, 1, 2, 3, 4, 5, 8, 9]  # lose data 6,7 + coding 2,3
    rec, _ = codec.recovery_matrix(survivors)

    sweep = {}
    on_cpu = jax.default_backend() == "cpu"
    for size in (4096, 65536, 1 << 20, 4 << 20):
        n = size // K
        x = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
        # TPU: pre-staged device arrays (HBM-resident pipeline); CPU:
        # host arrays so the engine's host-view fast path engages —
        # each backend measured the way the product drives it
        xd = x if on_cpu else jax.device_put(x)

        enc = lambda: gf256_swar.gf_matmul_bytes(coding, xd)  # noqa: E731
        coded = np.asarray(enc())
        # correctness pin vs the native oracle before timing anything
        want = _native.rs_encode(coding.astype(np.uint8), x[:, :4096])
        assert np.array_equal(coded[:, :4096], want), "encode != oracle"

        surv = np.stack([x[s] if s < K else coded[s - K] for s in survivors])
        sd = surv if on_cpu else jax.device_put(surv)
        dec = lambda: gf256_swar.gf_matmul_bytes(rec, sd)  # noqa: E731
        assert np.array_equal(np.asarray(dec()), x), "decode != data"

        enc_dt = _bench(enc)
        dec_dt = _bench(dec)
        # encode reads k/(k+m) and writes m/(k+m) of (k+m)/k*size bytes:
        # HBM traffic ≈ size * (k+m)/k relative to the reported object GB/s
        traffic = (K + M) / K
        sweep[str(size)] = {
            "encode_gbps": round(size / enc_dt / 1e9, 3),
            "decode_gbps": round(size / dec_dt / 1e9, 3),
            "suspect": _suspect(size / enc_dt / 1e9, traffic)
            or _suspect(size / dec_dt / 1e9, traffic),
        }

    # headline at 1 MiB
    head = sweep[str(1 << 20)]
    out["ec_sweep"] = sweep
    out["encode_gbps"] = head["encode_gbps"]
    out["decode_gbps"] = head["decode_gbps"]
    # roofline: encode moves (k+m)/k x the object bytes over HBM
    out["encode_hbm_frac"] = round(
        head["encode_gbps"] * (K + M) / K / HBM_PEAK_GBPS, 3)

    # CPU baseline: the same encode through the scalar native oracle
    # (scalar C++, not ISA-L — see module docstring)
    n = (1 << 20) // K
    xb = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
    cm = coding.astype(np.uint8)
    base_dt = _bench(lambda: _native.rs_encode(cm, xb), warmup=1, iters=3)
    out["baseline_cpu_native_gbps"] = round((1 << 20) / base_dt / 1e9, 3)
    out["baseline_is_isal"] = False

    # honest VECTORIZED CPU baseline (VERDICT r3 weak #3): the native
    # AVX2 split-nibble PSHUFB kernel (csrc/gf256_simd.cc) — the same
    # technique ISA-L's asm uses, measured on THIS bench host (the
    # isa-l submodule is empty in the reference checkout, so this is
    # the strongest comparator buildable here).  vs_baseline reports
    # against the BEST cpu number.
    want = _native.rs_encode(cm, xb[:, :4096])
    assert np.array_equal(_native.rs_encode_simd(cm, xb[:, :4096]), want), \
        "simd encode != oracle"
    vec_dt = _bench(lambda: _native.rs_encode_simd(cm, xb),
                    warmup=1, iters=5)
    out["baseline_cpu_vectorized_gbps"] = round((1 << 20) / vec_dt / 1e9, 3)
    out["baseline_cpu_vectorized_kind"] = (
        "avx2 pshufb split-nibble" if _native.simd_available()
        else "scalar fallback (no AVX2 on this host)")


def small_stripe_batched(jax, out):
    """4 KiB objects driven through the StripeBatchQueue (the path
    ECBackend actually uses for small writes) under concurrency —
    SURVEY §7 hard part #2 (reference bench sweep:
    qa/workunits/erasure-code/bench.sh:103-145)."""
    from ceph_tpu.ec import matrices
    from ceph_tpu.ec.codec import RSMatrixCodec
    from ceph_tpu.tpu.queue import StripeBatchQueue

    codec = RSMatrixCodec(K, M, matrices.isa_cauchy(K, M))
    q = StripeBatchQueue()
    rng = np.random.default_rng(1)
    n_objs = 4096
    objs = [rng.integers(0, 256, size=(K, 4096 // K), dtype=np.uint8)
            for _ in range(n_objs)]

    # warmup (compiles the power-of-two batch shapes)
    for f in [q.encode_async(codec, o) for o in objs[:512]]:
        f.result()

    t0 = time.perf_counter()
    for f in [q.encode_async(codec, o) for o in objs]:
        f.result()
    dt = time.perf_counter() - t0
    q.stop()
    gbps = n_objs * 4096 / dt / 1e9
    out["small_stripe_4k_batched_gbps"] = round(gbps, 3)
    out["small_stripe_stats"] = {"batches": q.batches, "jobs": q.jobs}


def clay_repair(jax, out):
    """Clay repair-decode GB/s (BASELINE metric 3): single-node repair
    should read ~(d/(d-k+1))/k of the RS repair bytes."""
    from ceph_tpu.ec.clay import ClayCodec

    codec = ClayCodec(k=K, m=M, d=K + M - 1)
    rng = np.random.default_rng(2)
    size = 1 << 20
    obj = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    chunks = codec.encode_bytes(obj)
    lost = 3
    sub = codec.minimum_to_decode([lost], set(range(K + M)) - {lost})
    picks = {i: chunks[i] for i in sub}
    repair_bytes = codec.repair_read_bytes(
        [lost], sub, chunk_size=np.asarray(chunks[lost]).size)

    def rep():
        return codec.repair_chunk([lost], picks)

    got = rep()
    assert np.array_equal(
        np.asarray(got[lost]).ravel(),
        np.asarray(chunks[lost]).ravel()), "clay repair mismatch"
    dt = _bench(rep, warmup=1, iters=5)
    chunk_bytes = np.asarray(chunks[lost]).size
    out["clay_repair_gbps"] = round(chunk_bytes * K / dt / 1e9, 3)
    out["clay_repair_read_frac_vs_rs"] = round(
        repair_bytes / (K * chunk_bytes), 3)


def baseline_configs(jax, out):
    """The remaining BASELINE.md table rows: #1 jerasure reed_sol_van
    k=4,m=2 at 4 KiB, #4 lrc k=8,m=4,l=4 local-repair decode."""
    from ceph_tpu.ec import instance

    rng = np.random.default_rng(3)

    jer = instance().factory("jerasure", {"technique": "reed_sol_van",
                                          "k": "4", "m": "2"})
    payload = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    chunks = jer.encode(range(6), payload)  # warm + correctness
    got = jer.decode_concat({i: chunks[i] for i in (0, 1, 4, 5)})
    assert bytes(got[:4096]) == payload, "jerasure decode mismatch"
    dt = _bench(lambda: jer.encode(range(6), payload), warmup=2, iters=20)
    out["jerasure_k4m2_4k_encode_gbps"] = round(4096 / dt / 1e9, 3)

    # BASELINE row 4 asks k=8,m=4,l=4 — which the REFERENCE's own
    # parse_kml rejects (ErasureCodeLrc.cc parse_kml: k and m must be
    # multiples of (k+m)/l; 8 % 3 != 0).  l=6 is the closest profile
    # both implementations accept (2 local groups, one parity each).
    lrc = instance().factory("lrc", {"k": "8", "m": "4", "l": "6"})
    out["lrc_profile"] = "k=8 m=4 l=6 (l=4 invalid per reference parse_kml)"
    n = lrc.get_chunk_count()
    obj = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
    lchunks = lrc.encode(range(n), obj)
    lost = 1
    need = lrc.minimum_to_decode({lost}, set(range(n)) - {lost})
    out["lrc_local_repair_reads"] = len(need)
    avail = {i: lchunks[i] for i in need}

    def rep():
        return lrc.decode([lost], avail)

    got = rep()
    assert np.array_equal(np.asarray(got[lost]),
                          np.asarray(lchunks[lost])), "lrc repair mismatch"
    dt = _bench(rep, warmup=1, iters=5)
    chunk_bytes = np.asarray(lchunks[lost]).size
    # object-equivalent GB/s (same convention as clay_repair_gbps and
    # BASELINE.md: bytes = chunk * k), so rows compare 1:1
    out["lrc_local_repair_gbps"] = round(
        chunk_bytes * 8 / dt / 1e9, 3)


def crush_sweep(jax, out):
    from ceph_tpu import _crush_ref
    from ceph_tpu.crush import map as cmap
    from ceph_tpu.crush import mapper

    n_osds, n_hosts, nrep = 1024, 64, 3
    m, root = cmap.build_flat_cluster(n_osds, hosts=n_hosts)
    steps = [(cmap.OP_TAKE, root, 0),
             (cmap.OP_CHOOSELEAF_FIRSTN, nrep, 1),
             (cmap.OP_EMIT, 0, 0)]
    flat = m.flatten()
    dev_w = np.full(n_osds, 0x10000, dtype=np.uint32)

    # BASELINE metric 6: the FULL 10M-id, 1024-OSD sweep through the
    # two-stage program (one-shot fast pass + full-retry re-run of the
    # ~5% unclean lanes — mapper.sweep), chunked so live HBM temps
    # stay bounded (the round-2 one-shot OOM'd)
    n_x = CRUSH_IDS
    xs = np.arange(n_x, dtype=np.int32)
    # warm both traces at the chunk shape — two different chunks so the
    # slow pass's pow2(bad-count) shape is cached too (~5% unclean of a
    # fixed chunk rounds to the same power of two on essentially every
    # chunk)
    mapper.sweep(flat, steps, nrep, xs[:CRUSH_CHUNK], dev_w,
                 chunk=CRUSH_CHUNK)
    mapper.sweep(flat, steps, nrep, xs[CRUSH_CHUNK:2 * CRUSH_CHUNK],
                 dev_w, chunk=CRUSH_CHUNK)
    # time-budgeted: measure one chunk, run as many as fit, extrapolate
    t0 = time.perf_counter()
    mapper.sweep(flat, steps, nrep, xs[:CRUSH_CHUNK], dev_w,
                 chunk=CRUSH_CHUNK)
    per_chunk = time.perf_counter() - t0
    budget_s = 180.0
    total_chunks = -(-n_x // CRUSH_CHUNK)
    run_chunks = max(1, min(total_chunks,
                            int(budget_s / max(per_chunk, 1e-9))))
    measured = min(n_x, run_chunks * CRUSH_CHUNK)
    t0 = time.perf_counter()
    res = mapper.sweep(flat, steps, nrep, xs[:measured], dev_w,
                       chunk=CRUSH_CHUNK)
    dt = time.perf_counter() - t0
    out["crush_mplacements_per_s"] = round(measured / dt / 1e6, 2)
    out["crush_ids"] = n_x
    out["crush_ids_measured"] = measured
    out["crush_extrapolated"] = measured < n_x
    out["crush_chunk"] = CRUSH_CHUNK

    # reference C rate (the scalar crush_do_rule loop, single-core —
    # the same work ParallelPGMapper shards over threads)
    if _crush_ref.available():
        m.add_rule(cmap.Rule("bench", steps))
        ref = _crush_ref.RefCrushMap(m)
        sub = np.arange(100_000, dtype=np.int32)
        ref_dt = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            ref_out = ref.do_rule(ref.rulenos[-1], sub, nrep, dev_w)
            ref_dt = min(ref_dt, time.perf_counter() - t0)
        out["crush_ref_c_mplacements_per_s"] = round(
            len(sub) / ref_dt / 1e6, 2)
        out["crush_vs_ref_c"] = round(
            out["crush_mplacements_per_s"]
            / out["crush_ref_c_mplacements_per_s"], 2)
        # conformance: the sweep must be bit-exact vs the reference C
        assert np.array_equal(res[:100_000], ref_out), \
            "sweep != reference C"


SECTIONS = [
    ("ec", ec_sweep),
    ("small_stripe", small_stripe_batched),
    ("clay", clay_repair),
    ("baseline_configs", baseline_configs),
    ("crush", crush_sweep),
]


def _probe_accelerator(timeout_s: float = 240.0) -> bool:
    """True if the attached accelerator answers within the timeout.

    Probed in a SUBPROCESS: a wedged axon tunnel hangs jax.devices()
    indefinitely (round-3 outages), and once jax initializes against a
    broken backend in-process there is no recovery.  On failure the
    bench falls back to CPU so the round artifact still records
    numbers (labeled backend=cpu) instead of nothing.
    """
    import os
    import subprocess

    timeout_s = float(os.environ.get("CEPH_TPU_PROBE_TIMEOUT", timeout_s))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    import os

    if (os.environ.get("CEPH_TPU_BENCH_FALLBACK") != "1"
            and os.environ.get("JAX_PLATFORMS", "") != "cpu"
            # an explicit CPU run is honored as-is (no probe, no
            # re-exec, user env untouched); only accelerator-targeted
            # runs pay the probe (one extra backend bring-up) because a
            # wedged tunnel would otherwise hang the round's artifact
            and not _probe_accelerator()):
        # the axon sitecustomize imports jax at interpreter START, so
        # env mutation in-process is too late — re-exec scrubbed (the
        # same discipline as conftest.py / dryrun_multichip)
        print("bench: accelerator probe failed/timed out -> re-exec "
              "on CPU", file=sys.stderr, flush=True)
        env = {k: v for k, v in os.environ.items()
               if not (k.startswith(("JAX_", "TPU_", "LIBTPU", "XLA_",
                                     "PJRT_", "PALLAS_")))}
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        env["CEPH_TPU_BENCH_FALLBACK"] = "1"
        os.execve(sys.executable, [sys.executable, __file__], env)

    print("bench: importing jax...", file=sys.stderr, flush=True)
    import jax

    print(f"bench: backend={jax.default_backend()} "
          f"devices={jax.devices()}", file=sys.stderr, flush=True)
    out = {"backend": jax.default_backend(), "errors": {}}
    if os.environ.get("CEPH_TPU_BENCH_FALLBACK") == "1":
        # make the artifact self-explanatory: these are CPU numbers
        # because the attached accelerator never answered the probe
        out["accelerator_fallback"] = (
            "attached accelerator unreachable (probe timeout); "
            "numbers are CPU")
    partial_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "BENCH_PARTIAL.json")

    def _flush_partial():
        # wedge-proofing (VERDICT r3 #1): the artifact-so-far hits disk
        # after EVERY section, so a tunnel wedge mid-run keeps every
        # completed section's numbers instead of erasing the round
        try:
            with open(partial_path, "w") as f:
                f.write(json.dumps(out) + "\n")
        except OSError:
            pass

    # watchdog: a tunnel that wedges MID-SECTION hangs that dispatch
    # forever — after section_timeout with no progress, emit the
    # one-line JSON with everything recorded so far and hard-exit.
    # A partial artifact always beats a hung driver (round-3 failure).
    import threading

    section_timeout = float(os.environ.get("CEPH_TPU_SECTION_TIMEOUT",
                                           "900"))
    progress = {"t": time.monotonic(), "name": "startup", "done": False}

    def _watchdog():
        while not progress["done"]:
            time.sleep(5)
            if (not progress["done"]
                    and time.monotonic() - progress["t"] > section_timeout):
                out["errors"][progress["name"]] = (
                    f"section hung > {section_timeout}s "
                    "(accelerator wedged mid-run?)")
                out.setdefault("watchdog_fired", progress["name"])
                _flush_partial()
                _emit(out)
                os._exit(1)

    threading.Thread(target=_watchdog, daemon=True).start()

    for name, fn in SECTIONS:
        # progress to stderr: if the tunnel wedges mid-run, the log
        # shows WHICH section hung (round-3 outage forensics)
        t0 = time.perf_counter()
        progress.update(t=time.monotonic(), name=name)
        print(f"bench: section {name} start", file=sys.stderr, flush=True)
        try:
            fn(jax, out)
            print(f"bench: section {name} done "
                  f"({time.perf_counter() - t0:.1f}s)",
                  file=sys.stderr, flush=True)
        except Exception:
            out["errors"][name] = traceback.format_exc(limit=4)
            print(f"bench: section {name} FAILED "
                  f"({time.perf_counter() - t0:.1f}s)",
                  file=sys.stderr, flush=True)
        _flush_partial()
    progress["done"] = True

    value = _emit(out)
    # rc=0 whenever the headline numbers were recorded, even if an
    # auxiliary section failed — the artifact must carry the wins
    return 0 if value > 0 else 1


def _emit(out) -> float:
    """Finalize + print the ONE-line JSON artifact (also used by the
    hang watchdog to salvage a partial run)."""
    enc = out.get("encode_gbps")
    dec = out.get("decode_gbps")
    # vs_baseline is judged against the BEST cpu number we recorded
    # (vectorized numpy beats the scalar oracle ~10x; using the scalar
    # number would overstate progress — VERDICT r3 weak #3)
    base = max(out.get("baseline_cpu_native_gbps") or 0,
               out.get("baseline_cpu_vectorized_gbps") or 0) or None
    if enc and dec:
        value = round(2 / (1 / enc + 1 / dec), 3)
    else:
        value = 0.0
    out.update({
        "metric": (f"EC encode+decode GB/s (RS k={K},m={M}, 1MiB object, "
                   f"{out['backend']}) + CRUSH {out.get('crush_ids', 0)}-id "
                   "sweep"),
        "value": value,
        "unit": "GB/s",
        # no silent fake ratio: 0 when the baseline didn't record
        "vs_baseline": round(value / base, 2) if (value and base) else 0,
    })
    if not out.get("errors"):
        out.pop("errors", None)
    print(json.dumps(out), flush=True)
    return value


if __name__ == "__main__":
    try:
        rc = main()
    except Exception as e:  # one line, always
        print(json.dumps({"metric": "bench-error", "value": 0, "unit": "GB/s",
                          "vs_baseline": 0, "error": repr(e)}))
        rc = 1
    sys.exit(rc)
