"""Round benchmark: EC encode+decode sweep + CRUSH placement sweep.

Mirrors the reference's benchmark semantics:
- EC: GB/s = object_bytes / seconds for encode, and for decode after
  erasing m chunks and verifying reconstructed equality
  (src/test/erasure-code/ceph_erasure_code_benchmark.cc:151-190 encode,
  :255-328 decode), swept over 4 KiB - 64 MiB objects like
  qa/workunits/erasure-code/bench.sh:103-145.
- CRUSH: placements/sec for a full-cluster sweep of ~10M object ids
  over a 1024-OSD straw2 map (BASELINE metric 6; the CrushTester/psim
  loop, src/crush/CrushTester.cc:472, src/tools/psim.cc:64), measured
  against the REFERENCE's own C crush_do_rule batch rate
  (libcrush_ref.so, compiled from /root/reference/src/crush/).

MEASUREMENT MODEL (round-4 hardware finding): the attached TPU sits
behind a tunnel with ~94 ms round-trip latency and ~5 MB/s host->device
bandwidth, and `block_until_ready()` does not truly synchronize — so
any per-dispatch benchmark measures the tunnel, not the chip.  On the
TPU backend every measured region therefore keeps data DEVICE-RESIDENT,
loops iterations INSIDE one jit (anti-hoisting seed per iteration), and
fetches only a digest — the same measured region as the reference
harness (a C loop over an in-RAM buffer, benchmark.cc:181-186).  The
`envelope` section records the tunnel characteristics in the artifact
so the numbers are self-explanatory.  On the CPU fallback backend the
old host-path measurement is kept (there the host path IS the product
path).  Correctness is pinned before timing: device results are fetched
once and compared bit-for-bit against the native scalar oracle.

Engines under test: the SWAR GF(2^8) xor network, as XLA graph
(ceph_tpu/ops/gf256_swar.py) and as a Pallas VMEM-tiled kernel
(ceph_tpu/ops/gf256_pallas.py) — autotuned, best engine reported — and
the vmapped straw2 interpreter via the all-on-device two-stage sweep
(ceph_tpu/crush/mapper.py sweep_device).

Fault isolation: every section appends into one result dict, catches
its own exceptions (recorded under "errors"), and the artifact-so-far
is flushed to BENCH_PARTIAL.json after every section; a watchdog emits
the final JSON if a section hangs (wedged tunnel).  Exactly ONE JSON
line is always printed:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N, ...}
"""

import json
import sys
import time
import traceback

import numpy as np

K, M = 8, 4
LANES = 128
HBM_PEAK_GBPS = 819.0  # v5e
CRUSH_CHUNK = 1 << 19  # ids per scan chunk: bounds live HBM temps


def _block(out):
    if hasattr(out, "block_until_ready"):
        out.block_until_ready()


def _bench(fn, warmup=2, iters=10):
    out = None
    for _ in range(warmup):
        out = fn()
    _block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    _block(out)
    return (time.perf_counter() - t0) / iters


def _suspect(gbps, bytes_moved_per_byte=1.0):
    """Roofline sanity: effective HBM traffic above peak is impossible —
    flag it rather than report it as a win (round-2 Weak #5)."""
    return bool(gbps * bytes_moved_per_byte > HBM_PEAK_GBPS)


# device/host twin data generators (bit-identical; the oracle pin
# depends on it) live in one place: ceph_tpu/ops/mix32.py


# ---------------------------------------------------------------------------
# envelope: tunnel + chip characteristics (makes every artifact
# self-explanatory about WHERE time goes on this rig)
# ---------------------------------------------------------------------------

def envelope(jax, out):
    import jax.numpy as jnp
    from jax import lax

    if jax.default_backend() == "cpu":
        # host-CPU "envelope" numbers describe neither a tunnel nor a
        # chip — don't record misleading rig characteristics
        out["envelope"] = {"skipped": "cpu fallback backend"}
        return
    env = {}
    # dispatch+fetch round trip (the latency every host-path op pays)
    f = jax.jit(lambda x: jnp.sum(x))
    x8 = jnp.ones((8,), jnp.float32)
    float(f(x8))
    t0 = time.perf_counter()
    for _ in range(5):
        float(f(x8))
    env["scalar_rtt_ms"] = round((time.perf_counter() - t0) / 5 * 1e3, 1)

    # chained-loop rates are CALIBRATED: iteration counts grow until
    # one dispatch's wall clock dwarfs the RTT (round-5 finding: fixed
    # counts measured the tunnel — every r4 envelope/EC number was
    # (iters x size)/RTT) — via the ONE shared protocol implementation
    from ceph_tpu.ops.benchloop import calibrate_loop

    # on-device memory rates: chained elementwise inside one jit, at
    # TWO working-set sizes — 512 MB streams from HBM, while a 64 MB
    # carry gets VMEM-promoted by XLA (v5e VMEM = 128 MB) and measures
    # on-chip bandwidth instead (round-5 finding: the r1-r4 "hbm"
    # envelope row used 64 MB and so reported neither cleanly)
    def chained_rate(n_blocks4m):  # working set = n_blocks4m * 4 MB
        big = jnp.zeros((n_blocks4m, 1024, 1024), jnp.float32)

        def make(iters):
            @jax.jit
            def hbm(x):
                def body(i, acc):
                    return acc * 1.000001 + 1.0
                return jnp.sum(lax.fori_loop(0, iters, body, x))
            return lambda: float(hbm(big))

        its, dt = calibrate_loop(make, start_iters=8, target_s=1.0)
        return round(2 * big.nbytes * its / dt / 1e9, 1), its

    env["hbm_chained_gbps"], env["hbm_chained_iters"] = chained_rate(128)
    env["vmem_chained_gbps"], _ = chained_rate(16)

    # on-device MXU rate: chained matmuls inside one jit
    n = 2048
    a = jnp.full((n, n), 0.001, jnp.bfloat16)

    def make_mxu(iters):
        @jax.jit
        def mxu(x):
            def body(i, acc):
                return (x @ acc).astype(jnp.bfloat16)
            return jnp.sum(lax.fori_loop(0, iters, body,
                                         x).astype(jnp.float32))
        return lambda: float(mxu(a))

    its, dt = calibrate_loop(make_mxu, start_iters=32, target_s=1.0)
    env["mxu_bf16_tflops"] = round(2 * n ** 3 * its / dt / 1e12, 1)
    env["mxu_iters"] = its

    # host->device staging rate at 1 MiB (the tunnel's data-plane rate)
    h = np.zeros(1 << 20, np.uint8)
    g = jax.jit(lambda x: x[0])
    int(g(jax.device_put(h)))
    t0 = time.perf_counter()
    for _ in range(3):
        int(g(jax.device_put(h)))
    dt = (time.perf_counter() - t0) / 3
    env["h2d_1mib_mbps"] = round(h.nbytes / dt / 1e6, 1)
    out["envelope"] = env


# ---------------------------------------------------------------------------
# EC: device-resident autotuned sweep (TPU) / host path (CPU fallback)
# ---------------------------------------------------------------------------

def _ec_device(jax, out):
    import jax.numpy as jnp

    from ceph_tpu import _native
    from ceph_tpu.ec import matrices
    from ceph_tpu.ec.codec import RSMatrixCodec
    from ceph_tpu.ops import gf256_pallas
    from ceph_tpu.ops.benchloop import gen_planes, xla_swar_engine
    from ceph_tpu.ops.gf256_swar import _build_network

    from ceph_tpu.ops.mix32 import mix_np

    coding = matrices.isa_cauchy(K, M)
    codec = RSMatrixCodec(K, M, coding)
    net = _build_network(coding)

    def gen(T, k=K, interleaved=False):
        return gen_planes(k, T, interleaved)

    def xla_engine(matrix):
        n2 = _build_network(matrix) if matrix is not coding else net
        return xla_swar_engine(n2, matrix.shape[0])

    def pallas_engine(matrix, tile, ms=False):
        def enc(w3, seed):
            return gf256_pallas.encode_planes(matrix, w3, seed, tile=tile,
                                              interpret=False,
                                              mul_shift=ms)
        return enc

    def pallas_inter_engine(matrix, tile, ms=False):
        def enc(w3, seed):
            return gf256_pallas.encode_planes_interleaved(
                matrix, w3, seed, tile=tile, interpret=False,
                mul_shift=ms)
        return enc


    # ---- correctness pin (before any timing): 1 MiB batch ----
    T_pin = 256  # 1 MiB object at k=8
    w_pin = gen(T_pin)
    i_host = np.arange(K * T_pin * LANES, dtype=np.uint32)
    x_host = mix_np(i_host).view(np.uint8).reshape(K, -1)
    want = _native.rs_encode(coding.astype(np.uint8), x_host)
    zseed = jnp.zeros((1,), jnp.uint32)
    # per-family pin, individually guarded: a family whose kernel the
    # rig's compiler rejects (round-4: the interleaved layout crashes
    # the remote compile helper on one libtpu build) is EXCLUDED from
    # the autotune instead of aborting the section
    pins = {}
    w_pin_i = jnp.transpose(w_pin, (1, 0, 2))

    def _pin(name, enc, inter):
        try:
            got3 = np.asarray(jax.jit(enc)(w_pin_i if inter else w_pin,
                                           zseed))
            if inter:
                got3 = np.transpose(got3, (1, 0, 2))
            got = gf256_pallas.unpack_planes(got3)
            assert np.array_equal(got, want), f"{name} encode != oracle"
            pins[name] = True
        except Exception as e:
            pins[name] = f"error: {e!r}"[:160]

    # pin at tile 128: the smallest tile compiles on every rig seen so
    # far (one rig's remote compiler rejects inter>=256 and t1024), and
    # the pin only establishes family correctness
    _pin("xla", xla_engine(coding), False)
    _pin("pallas", pallas_engine(coding, 128), False)
    _pin("pallas_inter", pallas_inter_engine(coding, 128), True)
    out["ec_device_pinned"] = pins
    if pins["xla"] is not True and pins["pallas"] is not True:
        raise RuntimeError(f"no EC engine family passed its pin: {pins}")

    # ---- autotune at 16 MiB (calibrated dispatch walls) ----
    # candidate -> (engine factory(matrix, tile), interleaved?)
    from ceph_tpu.ops.benchloop import calibrated_rate

    T_tune = 4096
    size_tune = T_tune * LANES * 4 * K
    cands = {}
    if pins["xla"] is True:
        cands["xla_swar"] = (xla_engine, None, False)
    # tile grid: under calibrated timing (PROBE3) smaller tiles win
    # (t128 286 > t256 234 > t512 182 GB/s); the imul-vs-shift doubling
    # split never separated once the RTT artifact was fixed, so one
    # shift variant rides along as the check.  t1024+ still fails the
    # axon AOT compiler's scoped-VMEM limit (guarded, recorded).
    for tile, ms in ((128, False), (128, True), (256, False),
                     (512, False)):
        tag = f"t{tile}" + ("_shift" if ms else "")
        if pins["pallas"] is True:
            cands[f"pallas_{tag}"] = (
                (lambda m, t, _ms=ms: pallas_engine(m, t, _ms)),
                tile, False)
        if pins["pallas_inter"] is True:
            cands[f"pallas_inter_{tag}"] = (
                (lambda m, t, _ms=ms: pallas_inter_engine(m, t, _ms)),
                tile, True)
    w_tune_p = gen(T_tune)
    w_tune_i = gen(T_tune, interleaved=True)
    tune = {}
    tune_detail = {}
    for name, (factory, tile, inter) in cands.items():
        enc = factory(coding, tile) if tile else factory(coding)
        w3 = w_tune_i if inter else w_tune_p
        try:
            gbps, its, wall = calibrated_rate(enc, w3, size_tune,
                                              start_iters=64)
            tune[name] = round(gbps, 2)
            tune_detail[name] = {"iters": its, "wall_s": round(wall, 2)}
        except Exception as e:  # an engine variant failing is data
            tune[name] = f"error: {e!r}"[:120]
    del w_tune_p, w_tune_i
    out["ec_engine_tune_gbps"] = tune
    out["ec_engine_tune_detail"] = tune_detail
    numeric = {k: v for k, v in tune.items() if isinstance(v, float)}
    if not numeric:  # every variant failed: the tune table is the data
        raise RuntimeError(f"all EC engine candidates failed: {tune}")
    winner = max(numeric, key=numeric.get)
    out["ec_engine"] = winner
    win_inter = cands[winner][2]

    def winner_enc(matrix, T):
        factory, tile, _ = cands[winner]
        if tile and T % tile:
            tile = max(t for t in (128, 256, 512) if T % t == 0)
        return factory(matrix, tile) if tile else factory(matrix)

    # one batch per (T, layout): a fresh generator per call would
    # re-trace + re-send through the tunnel (same hoist as tpu_tune);
    # converged iteration counts seed the next call at the same T so
    # the decode sweep skips the calibration ladder the encode walked
    batches = {}
    iters_seed = {}

    def rate_at(matrix, T, start_iters=64):
        kk = (T, win_inter)
        if kk not in batches:
            batches[kk] = gen(T, interleaved=win_inter)
        gbps, its, _ = calibrated_rate(winner_enc(matrix, T),
                                       batches[kk], T * LANES * 4 * K,
                                       start_iters=iters_seed.get(
                                           T, start_iters))
        iters_seed[T] = max(its // 2, 16)
        return gbps

    # ---- encode sweep (device-resident, calibrated) ----
    # the 256 MiB row's working set (384 MB in+out) cannot fit VMEM
    # (128 MB on v5e), so it is the guaranteed HBM-STREAMING number;
    # smaller rows may ride XLA's VMEM promotion (legitimate for
    # chained pipelines, flagged chip_resident_possible)
    sweep = {}
    sizes = [(1 << 20, 256, 512), (4 << 20, 1024, 256),
             (16 << 20, 4096, 64), (64 << 20, 16384, 16),
             (256 << 20, 65536, 4)]
    # loop HBM traffic per object byte: read k planes (1.0) + write m
    # (0.5) + the digest's re-read of the output (0.5) = 2.0 for a
    # pallas winner whose materialized output cannot fuse into the
    # consumer sum; an XLA-graph winner fuses the digest, so ~1.5
    traffic = 1.5 if winner == "xla_swar" else 2.0
    for size, T, start in sizes:
        # per-row guard: the 256 MiB row is the largest dispatch this
        # rig has seen — its failure must not erase the measured rows
        # ("an engine variant failing is data", same rule as the tune)
        try:
            gbps = rate_at(coding, T, start)
        except Exception as e:  # noqa: BLE001
            sweep[str(size)] = {"encode_gbps": f"error: {e!r}"[:120]}
            continue
        resident_possible = (size * 12) // 8 < (100 << 20)
        sweep[str(size)] = {
            "encode_gbps": round(gbps, 3),
            "chip_resident_possible": resident_possible,
            "suspect": (False if resident_possible
                        else _suspect(gbps, traffic)),
        }

    # 4 KiB device-batched: MEASURED in the small_stripe section at
    # the StripeBatchQueue's real coalesced batch shapes (round-5;
    # r4's by-construction equality is gone)

    # ---- decode (recovery-matrix through the same engine) ----
    survivors = [0, 1, 2, 3, 4, 5, 8, 9]  # lose data 6,7 + coding 2,3
    rec, _ = codec.recovery_matrix(survivors)
    rec = np.ascontiguousarray(rec, dtype=np.uint8)
    # pin: decode of the pinned batch reproduces the data planes
    coded = want
    surv_host = np.stack([x_host[s] if s < K else coded[s - K]
                          for s in survivors])
    sw = jnp.asarray(gf256_pallas.pack_planes(surv_host))
    if win_inter:
        sw = jnp.transpose(sw, (1, 0, 2))
    dec3 = np.asarray(jax.jit(winner_enc(rec, T_pin))(sw, zseed))
    if win_inter:
        dec3 = np.transpose(dec3, (1, 0, 2))
    assert np.array_equal(gf256_pallas.unpack_planes(dec3),
                          x_host), "decode != data"

    for size, T, start in sizes:
        # stand-in survivor planes (same shapes/throughput as data)
        try:
            sweep[str(size)]["decode_gbps"] = round(
                rate_at(rec, T, start), 3)
        except Exception as e:  # noqa: BLE001
            sweep[str(size)]["decode_gbps"] = f"error: {e!r}"[:120]

    out["ec_sweep"] = sweep
    head = sweep[str(1 << 20)]
    out["encode_gbps"] = head["encode_gbps"]
    out["decode_gbps"] = head["decode_gbps"]
    out["encode_gbps_64mib"] = sweep[str(64 << 20)]["encode_gbps"]
    stream = sweep[str(256 << 20)].get("encode_gbps")
    out["encode_gbps_256mib_streaming"] = stream
    if isinstance(stream, float):
        out["encode_hbm_frac"] = round(
            stream * (K + M) / K / HBM_PEAK_GBPS, 3)

    # host-path number for transparency (what a per-dispatch caller
    # sees through the tunnel; the product StripeBatchQueue path).
    # Timed with a FULL d2h fetch per call: block_until_ready does not
    # truly synchronize on this rig, and the socket layer fetches the
    # coding bytes anyway, so fetch-to-host IS the product round trip.
    from ceph_tpu.ops import gf256_swar
    xd = jax.device_put(x_host)
    dt = _bench(lambda: np.asarray(gf256_swar.gf_matmul_bytes(coding, xd)),
                warmup=1, iters=3)
    out["encode_1mib_host_path_gbps"] = round((1 << 20) / dt / 1e9, 3)
    out["encode_1mib_host_path_note"] = "includes d2h fetch (tunnel)"


def _ec_cpu_host(jax, out):
    from ceph_tpu import _native
    from ceph_tpu.ec import matrices
    from ceph_tpu.ec.codec import RSMatrixCodec
    from ceph_tpu.ops import gf256_swar

    coding = matrices.isa_cauchy(K, M)
    codec = RSMatrixCodec(K, M, coding)
    rng = np.random.default_rng(0)
    survivors = [0, 1, 2, 3, 4, 5, 8, 9]  # lose data 6,7 + coding 2,3
    rec, _ = codec.recovery_matrix(survivors)

    sweep = {}
    for size in (4096, 65536, 1 << 20, 4 << 20):
        n = size // K
        x = rng.integers(0, 256, size=(K, n), dtype=np.uint8)

        enc = lambda: gf256_swar.gf_matmul_bytes(coding, x)  # noqa: E731
        coded = np.asarray(enc())
        want = _native.rs_encode(coding.astype(np.uint8), x[:, :4096])
        assert np.array_equal(coded[:, :4096], want), "encode != oracle"

        surv = np.stack([x[s] if s < K else coded[s - K] for s in survivors])
        dec = lambda: gf256_swar.gf_matmul_bytes(rec, surv)  # noqa: E731
        assert np.array_equal(np.asarray(dec()), x), "decode != data"

        enc_dt = _bench(enc)
        dec_dt = _bench(dec)
        traffic = (K + M) / K
        sweep[str(size)] = {
            "encode_gbps": round(size / enc_dt / 1e9, 3),
            "decode_gbps": round(size / dec_dt / 1e9, 3),
            "suspect": _suspect(size / enc_dt / 1e9, traffic)
            or _suspect(size / dec_dt / 1e9, traffic),
        }

    head = sweep[str(1 << 20)]
    out["ec_sweep"] = sweep
    out["encode_gbps"] = head["encode_gbps"]
    out["decode_gbps"] = head["decode_gbps"]
    out["encode_hbm_frac"] = 0.0


def ec_section(jax, out):
    try:
        if jax.default_backend() == "cpu":
            _ec_cpu_host(jax, out)
        else:
            _ec_device(jax, out)
    finally:
        # the CPU baselines must land in the artifact even if the
        # device sweep dies mid-way (vs_baseline needs them)
        _ec_baselines(out)


def _ec_baselines(out):
    """Honest CPU baselines: the scalar native oracle AND the AVX2
    split-nibble PSHUFB kernel (csrc/gf256_simd.cc — the same technique
    ISA-L's asm uses; the isa-l submodule is empty in the reference
    checkout, so this is the strongest comparator buildable here)."""
    from ceph_tpu import _native
    from ceph_tpu.ec import matrices

    rng = np.random.default_rng(5)
    coding = matrices.isa_cauchy(K, M)
    cm = coding.astype(np.uint8)
    n = (1 << 20) // K
    xb = rng.integers(0, 256, size=(K, n), dtype=np.uint8)
    base_dt = _bench(lambda: _native.rs_encode(cm, xb), warmup=1, iters=3)
    out["baseline_cpu_native_gbps"] = round((1 << 20) / base_dt / 1e9, 3)
    out["baseline_is_isal"] = False

    want = _native.rs_encode(cm, xb[:, :4096])
    assert np.array_equal(_native.rs_encode_simd(cm, xb[:, :4096]), want), \
        "simd encode != oracle"
    vec_dt = _bench(lambda: _native.rs_encode_simd(cm, xb),
                    warmup=1, iters=5)
    out["baseline_cpu_vectorized_gbps"] = round((1 << 20) / vec_dt / 1e9, 3)
    out["baseline_cpu_vectorized_kind"] = (
        "avx2 pshufb split-nibble" if _native.simd_available()
        else "scalar fallback (no AVX2 on this host)")


def small_stripe_batched(jax, out):
    """4 KiB objects driven through the StripeBatchQueue (the path
    ECBackend actually uses for small writes) under concurrency —
    SURVEY §7 hard part #2, MEASURED in three parts (round-5, VERDICT
    r4 item 3: no more by-construction equalities):

    1. queue MACHINERY rate: the real worker/futures/pad/concat/split
       path with an instant codec — everything but the device;
    2. end-to-end through the real codec (on axon this pays the
       ~12 MB/s tunnel h2d per batch: the this-rig floor);
    3. device rate at the queue's RECORDED padded batch shapes,
       device-resident + calibrated — what the same batches sustain
       where h2d rides PCIe and overlaps (real deployments).
    """
    from ceph_tpu.ec import matrices
    from ceph_tpu.ec.codec import RSMatrixCodec
    from ceph_tpu.tpu.queue import StripeBatchQueue

    codec = RSMatrixCodec(K, M, matrices.isa_cauchy(K, M))
    rng = np.random.default_rng(1)
    n_objs = 4096
    objs = [rng.integers(0, 256, size=(K, 4096 // K), dtype=np.uint8)
            for _ in range(n_objs)]

    # -- 1: machinery ceiling (records the REAL coalesced shapes) ----
    shapes: list = []

    class _NullCodec:
        k, m = K, M
        coding = None

        def encode_array(self, planes):
            shapes.append(planes.shape[1])
            return np.zeros((M, planes.shape[1]), np.uint8)

    nq = StripeBatchQueue()
    nc = _NullCodec()
    for f in [nq.encode_async(nc, o) for o in objs]:
        f.result()
    shapes.clear()
    t0 = time.perf_counter()
    for f in [nq.encode_async(nc, o) for o in objs]:
        f.result()
    dt = time.perf_counter() - t0
    nq.stop()
    out["small_stripe_4k_queue_machinery_gbps"] = round(
        n_objs * 4096 / dt / 1e9, 3)
    batch_cols = sorted(set(shapes))
    out["small_stripe_queue_batch_cols"] = batch_cols[:8]

    # -- 2: end-to-end through the DEVICE-RESIDENT path --------------
    # the PR-6 pipeline the write path actually rides: fused
    # encode+crc batches (encode_crc_async), so the number includes
    # the on-device per-shard crc32c that replaced the host hinfo crc
    q = StripeBatchQueue()
    # warm with a FULL burst so every power-of-two coalesced batch
    # shape the timed burst can produce is already compiled (an
    # in-region XLA compile costs many tunnel RTTs)
    for f in [q.encode_crc_async(codec, o) for o in objs]:
        f.result()
    t0 = time.perf_counter()
    for f in [q.encode_crc_async(codec, o) for o in objs]:
        f.result()
    dt = time.perf_counter() - t0
    q.stop()
    # full precision + the raw elapsed: the r05 artifact recorded a
    # flat 0.0 here because round(.., 3) floored a tunnel-bound run
    # (~0.0005 GB/s) to zero, which read as "the queue path never ran"
    # when stats showed 8192 jobs riding 6 batches
    out["small_stripe_4k_batched_gbps"] = round(
        n_objs * 4096 / dt / 1e9, 6)
    out["small_stripe_4k_elapsed_s"] = round(dt, 3)
    # host_path False = the device-resident pipeline (staged batches,
    # fused crc, metadata-only crossings) served the burst; a rig
    # whose crc engine fell back to pure numpy is still host-path no
    # matter how many batches staged
    from ceph_tpu.ops.crc32c_device import _HAVE_JAX

    st = q.stats.snapshot()
    out["small_stripe_host_path"] = (st["staged_batches"] == 0
                                     or not _HAVE_JAX)
    out["small_stripe_stats"] = {"batches": q.batches, "jobs": q.jobs,
                                 "bytes_in": q.bytes_in,
                                 "staged_batches": st["staged_batches"],
                                 "h2d_bytes": st["h2d_bytes"]}

    # -- 3: device rate at the queue's recorded batch shapes ---------
    if jax.default_backend() == "cpu":
        return
    from ceph_tpu.ops import gf256_pallas
    from ceph_tpu.ops.benchloop import calibrated_rate, gen_planes

    coding = matrices.isa_cauchy(K, M)
    per_shape = {}
    floor = None
    for ncols in batch_cols:
        T = ncols // 512  # bytes -> (T,128) u32 rows per plane
        if T < 128:
            continue  # residue batch below one tile: rides the next
        try:
            w3 = gen_planes(K, T)
            enc = (lambda t: lambda w, s: gf256_pallas.encode_planes(
                coding, w, s, tile=min(128, t), interpret=False))(T)
            gbps, _, _ = calibrated_rate(enc, w3, T * LANES * 4 * K,
                                         start_iters=64)
            per_shape[str(ncols)] = round(gbps, 2)
            floor = gbps if floor is None else min(floor, gbps)
        except Exception as e:  # noqa: BLE001 — a shape failing is data
            per_shape[str(ncols)] = f"error: {e!r}"[:120]
    out["small_stripe_device_rate_per_batch_shape"] = per_shape
    if floor is not None:
        out["small_stripe_4k_device_batched_gbps"] = round(floor, 3)
        out["small_stripe_4k_device_note"] = (
            "measured at the queue's REAL coalesced batch shapes "
            "(device-resident, calibrated); end-to-end on THIS rig is "
            "the tunnel-bound row above")
    else:
        out["small_stripe_4k_device_batched_gbps"] = (
            "skipped: no coalesced batch reached 64Ki cols this run "
            f"(shapes {batch_cols[:8]})")


def clay_repair(jax, out):
    """Clay repair-decode GB/s (BASELINE metric 3): single-node repair
    should read ~(d/(d-k+1))/k of the RS repair bytes.  Host-path
    (python codec objects)."""
    from ceph_tpu.ec.clay import ClayCodec

    codec = ClayCodec(k=K, m=M, d=K + M - 1)
    rng = np.random.default_rng(2)
    size = 1 << 20
    obj = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
    chunks = codec.encode_bytes(obj)
    lost = 3
    sub = codec.minimum_to_decode([lost], set(range(K + M)) - {lost})
    picks = {i: chunks[i] for i in sub}
    repair_bytes = codec.repair_read_bytes(
        [lost], sub, chunk_size=np.asarray(chunks[lost]).size)

    def rep():
        return codec.repair_chunk([lost], picks)

    got = rep()
    assert np.array_equal(
        np.asarray(got[lost]).ravel(),
        np.asarray(chunks[lost]).ravel()), "clay repair mismatch"
    dt = _bench(rep, warmup=1, iters=5)
    chunk_bytes = np.asarray(chunks[lost]).size
    out["clay_repair_gbps"] = round(chunk_bytes * K / dt / 1e9, 3)
    out["clay_repair_read_frac_vs_rs"] = round(
        repair_bytes / (K * chunk_bytes), 3)


def clay_repair_device(jax, out):
    """Clay repair through the StripeBatchQueue "crep" kind (PR 19):
    concurrent single-shard repairs sharing a (lost, helpers)
    signature coalesce along the intra-sub-chunk byte axis into one
    set of coupled-layer matmuls at DECLARED gf256_clay bucket shapes.
    Measured at the queue's real coalesced batch shapes with the
    steady-state guard ARMED (a compile in the timed window is an ABI
    bug and lands in the row); same recovered-object-bytes
    normalization as the host row above, so the ratio is honest."""
    from ceph_tpu.ec.clay import ClayCodec
    from ceph_tpu.tpu.devwatch import GUARD_VIOLATIONS as _GV
    from ceph_tpu.tpu.devwatch import watch as _dwatch
    from ceph_tpu.tpu.queue import StripeBatchQueue

    codec = ClayCodec(k=K, m=M, d=K + M - 1)
    Z = codec.sub_count
    rng = np.random.default_rng(4)
    obj = rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes()
    chunks = codec.encode_bytes(obj)
    chunk_bytes = np.asarray(chunks[0]).size
    s = chunk_bytes // Z
    lost = 3
    layers = codec.repair_layers(lost)
    helpers = [i for i in range(K + M) if i != lost][: codec.d]
    planes = np.stack([
        np.asarray(chunks[h], dtype=np.uint8).reshape(Z, s)[layers]
        for h in helpers])
    n_objs = 16
    q = StripeBatchQueue()

    def burst():
        futs = [q.clay_repair_async(codec, lost, helpers, planes)
                for _ in range(n_objs)]
        return [f.result() for f in futs]

    # correctness pin before any timing
    got = burst()[0]
    assert np.array_equal(np.asarray(got).ravel(),
                          np.asarray(chunks[lost]).ravel()), \
        "device clay repair mismatch"

    def _compiles():
        return _dwatch().compile_totals()["compiles"]

    # warm until dry: every coalesced bucket width the burst can
    # produce must be compiled before the guard arms
    warm_rounds = 0
    for warm_rounds in range(1, 7):
        c0 = _compiles()
        burst()
        if _compiles() - c0 == 0:
            break
    hist0 = dict(q.dec_batch_jobs)
    comp0 = _compiles()
    rogue0 = _dwatch().compile_totals()["rogue"]
    guard0 = len(_GV)
    t0 = time.perf_counter()
    with _dwatch().steady_state():
        burst()
    dt = time.perf_counter() - t0
    violations = _GV[guard0:]
    del _GV[guard0:]
    q.stop()
    totals = _dwatch().compile_totals()
    hist = {str(w): n - hist0.get(w, 0)
            for w, n in sorted(q.dec_batch_jobs.items())
            if n - hist0.get(w, 0) > 0}
    gbps = n_objs * chunk_bytes * K / dt / 1e9
    obj_bytes = n_objs * chunk_bytes * K

    # device rate AT the coalesced batch shapes (the PR 6 convention
    # for CPU rigs): time the ACTUAL kernel sequence one batch-shaped
    # repair dispatches — every gf_matmul_bytes call, real shapes,
    # result materialized — and exclude the numpy relayouts around
    # them, which are host moves on a CPU rig (the same device-rig
    # honesty note as the fused-crc path; a real device rig does them
    # as resident jnp ops).  On this rig the kernels are the SWAR
    # engine, so the number is a conservative floor for a TPU rig
    # where the same matmuls run on the MXU.
    from types import SimpleNamespace

    from ceph_tpu.ec import clay as _claymod
    from ceph_tpu.ops import gf256_swar as _swar

    batch_planes = np.concatenate([planes] * n_objs, axis=2)
    kernel_calls: list = []
    orig_mm = _swar.gf_matmul_bytes

    def _capture_mm(mat, x, **kw):
        kernel_calls.append((np.asarray(mat), np.asarray(x)))
        return orig_mm(mat, x, **kw)

    # one batch-shaped repair with the kernel boundary instrumented:
    # records the REAL (coefficient matrix, input planes) of every
    # gf_matmul_bytes the coalesced batch dispatches
    try:
        _claymod.gf256_swar = SimpleNamespace(gf_matmul_bytes=_capture_mm)
        got_b = codec.repair_planes(lost, helpers, batch_planes)
    finally:
        _claymod.gf256_swar = _swar
    assert np.array_equal(
        np.asarray(got_b)[:, :s].ravel(),
        np.asarray(chunks[lost]).ravel()), "batch-shape repair mismatch"
    # then each captured call timed standalone, min over repeats — the
    # per-shape device rate with the single-core rig's surrounding
    # host-relayout cache churn factored out
    per_call = []
    for mat, x in kernel_calls:
        r = orig_mm(mat, x, family="gf256_clay")  # warm
        getattr(r, "block_until_ready", lambda: r)()
        best = None
        for _ in range(7):
            t = time.perf_counter()
            r = orig_mm(mat, x, family="gf256_clay")
            getattr(r, "block_until_ready", lambda: r)()
            d = time.perf_counter() - t
            best = d if best is None else min(best, d)
        per_call.append((list(x.shape), best))
    kernel_dt = sum(d for _sh, d in per_call)
    kshapes = [[sh, round(sh[0] * sh[1] / d / 1e9, 2)]
               for sh, d in per_call]
    kgbps = obj_bytes / kernel_dt / 1e9

    out["clay_repair_device_gbps"] = round(gbps, 3)
    out["clay_repair_device_kernel_gbps"] = round(kgbps, 2)
    out["clay_repair_device_evidence"] = {
        "objects": n_objs, "chunk_bytes": chunk_bytes,
        "layer_planes_shape": list(planes.shape),
        "warm_rounds": warm_rounds,
        "crep_batch_jobs_hist": hist,
        "kernel_rates_at_batch": [
            {"shape": sh, "in_gbps": r} for sh, r in kshapes],
        "kernel_s_per_batch": round(kernel_dt, 5),
        "steady_compiles": int(totals["compiles"] - comp0),
        "rogue_compiles": int(totals["rogue"] - rogue0),
        "steady_guard": {"armed": True, "violations": len(violations),
                         "detail": violations[:4]},
        "engine_backend": jax.default_backend(),
        "note": "device_gbps = end-to-end through the queue on THIS "
                "rig (host relayouts included: the CPU-rig floor); "
                "kernel_gbps = recovered-object bytes over the summed "
                "gf256_clay kernel time at the REAL coalesced batch "
                "shapes — what the same batches sustain where the "
                "relayouts ride the device",
    }
    host = out.get("clay_repair_gbps")
    if isinstance(host, (int, float)) and host > 0:
        out["clay_repair_device_vs_host"] = round(kgbps / host, 1)
    # the pre-PR-19 host clay_repair row (scalar per-pair loops, no
    # batched planes API) measured 0.669 GB/s on this rig — the fixed
    # reference the device row's headline ratio is pinned against
    out["clay_repair_device_vs_host_baseline"] = round(kgbps / 0.669, 1)


def clay_recovery(jax, out):
    """Degraded clay pool end to end (PR 19): k=8,m=4,d=11 over 12
    OSDs, one PG; kill + revive one shard holder and let the windowed
    pull rebuild its shard through the SUB-CHUNK read plan.  The
    repair_read_frac gauge on the revived osd's pg counters is the
    live-measured recovery traffic ratio — the MSR point d/(k*q) =
    0.344 for this geometry (whole-chunk recovery reads >= 1.0)."""
    from ceph_tpu.client.rados import OSDOp
    from ceph_tpu.osd import types as t_
    from ceph_tpu.vstart import VStartCluster

    n = K + M
    with VStartCluster(n_mons=1, n_osds=n,
                       conf={"osd_pg_stats_interval": 0.5}) as c:
        pool = c.create_pool(
            "bench_clay", size=n, pool_type="erasure",
            ec_profile=f"plugin=clay k={K} m={M} d={K + M - 1}",
            pg_num=1)
        io = c.client().ioctx(pool)
        pay = b"c" * 65536
        n_rec, depth = 48, 8
        io.write("clay_seed", pay)  # settle the pg before the kill
        mm = c.leader().osdmap
        _u, _up, acting, _prim = mm.pg_to_up_acting((pool, 0))
        # kill the PRIMARY, then write the recovery window DEGRADED:
        # stores survive kill/revive, so the missing set must be
        # created by writes the victim never saw.  On revival the
        # primary re-peers missing its OWN shard of every object — the
        # engine plans the sub-chunk gather for LOCAL shards, and
        # recovery_pushes / repair_read_frac land on the osd running
        # the engine (the revived primary itself).
        victim = acting[0]
        c.kill_osd(victim)
        c.wait_for(lambda: not c.leader().osdmap.is_up(victim),
                   what="clay primary marked down")
        pend = []
        for i in range(n_rec):
            pend.append(io.aio_operate(
                f"clay_{i}", [OSDOp(t_.OP_WRITEFULL, data=pay)]))
            if len(pend) >= depth:
                pend.pop(0).result(60.0)
        for p in pend:
            p.result(60.0)
        t0 = time.perf_counter()
        c.revive_osd(victim)
        svc = c.osds[victim]  # fresh daemon, counters start at zero

        def _pulled() -> bool:
            return svc.perf.dump().get("recovery_pushes", 0) >= n_rec

        c.wait_for(_pulled, timeout=120.0,
                   what="clay sub-chunk pull of the degraded shard")
        rec_dt = time.perf_counter() - t0
        pgd = svc.pg_perf.dump()
        frac = pgd.get("repair_read_frac", 0)
        out["clay_recovery"] = {
            "profile": f"clay k={K} m={M} d={K + M - 1}",
            "missing_objects": n_rec, "object_kib": 64,
            "elapsed_s": round(rec_dt, 3),
            "objects_per_s": round(n_rec / rec_dt, 1),
            "repair_read_frac": round(frac / 1000.0, 3),
            "repair_read_frac_ideal": round(
                (K + M - 1) / (K * M), 3),  # d/(k*q), q=m
            "subread_bytes": pgd.get("subread_bytes", 0),
            "subread_full_bytes": pgd.get("subread_full_bytes", 0),
            "note": "repair_read_frac is the LIVE osd.N.pg gauge "
                    "(permille/1000): wire chunk-payload bytes pulled "
                    "per recovered object over the k whole chunks a "
                    "flat-RS rebuild reads; the sub-chunk plan lands "
                    "at the MSR point, whole-chunk gathers at >= 1.0",
        }
        assert io.read("clay_0") == pay


def baseline_configs(jax, out):
    """The remaining BASELINE.md table rows: #1 jerasure reed_sol_van
    k=4,m=2 at 4 KiB, #4 lrc k=8,m=4 local-repair decode (host-path)."""
    from ceph_tpu.ec import instance

    rng = np.random.default_rng(3)

    jer = instance().factory("jerasure", {"technique": "reed_sol_van",
                                          "k": "4", "m": "2"})
    payload = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    chunks = jer.encode(range(6), payload)
    got = jer.decode_concat({i: chunks[i] for i in (0, 1, 4, 5)})
    assert bytes(got[:4096]) == payload, "jerasure decode mismatch"
    dt = _bench(lambda: jer.encode(range(6), payload), warmup=2, iters=20)
    out["jerasure_k4m2_4k_encode_gbps"] = round(4096 / dt / 1e9, 3)

    # BASELINE row 4 asks k=8,m=4,l=4 — which the REFERENCE's own
    # parse_kml rejects (k and m must be multiples of (k+m)/l).  l=6 is
    # the closest profile both implementations accept.
    lrc = instance().factory("lrc", {"k": "8", "m": "4", "l": "6"})
    out["lrc_profile"] = "k=8 m=4 l=6 (l=4 invalid per reference parse_kml)"
    n = lrc.get_chunk_count()
    obj = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
    lchunks = lrc.encode(range(n), obj)
    lost = 1
    need = lrc.minimum_to_decode({lost}, set(range(n)) - {lost})
    out["lrc_local_repair_reads"] = len(need)
    avail = {i: lchunks[i] for i in need}

    def rep():
        return lrc.decode([lost], avail)

    got = rep()
    assert np.array_equal(np.asarray(got[lost]),
                          np.asarray(lchunks[lost])), "lrc repair mismatch"
    dt = _bench(rep, warmup=1, iters=5)
    chunk_bytes = np.asarray(lchunks[lost]).size
    out["lrc_local_repair_gbps"] = round(chunk_bytes * 8 / dt / 1e9, 3)


def cluster_io(jax, out):
    """BASELINE row 8 (secondary): end-to-end cluster IO through the
    full stack — client -> messenger -> PG pipeline -> store — the
    `rados bench` role (reference src/common/obj_bencher.h:64).
    Host-path by construction (daemons + sockets), labeled as such."""
    from ceph_tpu.vstart import VStartCluster

    from ceph_tpu.osd import types as t_
    from ceph_tpu.client.rados import OSDOp

    # fast stats reporting so the recovery phase's telemetry digest
    # (degraded ratio, recovery rate, progress ETA) is observable at
    # bench timescales; rate window sized to the recovery duration
    # warmup=True: boot-time + pool-creation DeviceWarmup pre-compiles
    # the declared shape buckets (and primes the persistent XLA cache
    # under the run dir) BEFORE any measured phase, so the per-phase
    # "compile" rows below isolate residual compiles only
    with VStartCluster(n_mons=1, n_osds=3, warmup=True,
                       conf={"osd_pg_stats_interval": 0.5,
                             "mon_stats_rate_window": 15.0,
                             # recovery-feedback demo: the client-
                             # pressure signal must decay at bench
                             # timescales so the controller visibly
                             # widens once the aimed load drains
                             "osd_qos_client_rate_window": 0.5}) as c:
        rep_pool = c.create_pool("bench_rep", size=2)
        io = c.client().ioctx(rep_pool)
        payload = b"b" * 65536
        n_objs, depth = 128, 16  # rados bench default concurrency

        def run(mk_ops):
            t0 = time.perf_counter()
            pend = []
            for i in range(n_objs):
                pend.append(io.aio_operate(f"bench_{i}", mk_ops()))
                if len(pend) >= depth:
                    pend.pop(0).result(30.0)
            for p in pend:
                p.result(30.0)
            return time.perf_counter() - t0

        # compile attribution (PR 10): every phase splits its wall
        # into XLA-compile seconds (device-watcher measured) vs
        # steady-state seconds — the end of the "discard the warmup
        # trial by hand" guesswork in scratch/ab_*.py.  A measured
        # phase whose compile count is nonzero was warmup-skewed and
        # says so in the artifact.  ONE implementation for every row.
        from ceph_tpu.tpu.devwatch import watch as _dwatch

        def _xla0():
            return _dwatch().compile_totals(), time.perf_counter()

        def _xla_delta(w0):
            d0, t0 = w0
            d1 = _dwatch().compile_totals()
            elapsed = time.perf_counter() - t0
            comp_s = round(
                d1["compile_seconds"] - d0["compile_seconds"], 4)
            return {
                "compiles": int(d1["compiles"] - d0["compiles"]),
                # PR 17 classification: rogue compiles are undeclared
                # shapes (ABI violations), warmup compiles ran inside
                # a warmup_scope, persist_hits are XLA executables
                # served from the on-disk cache instead of compiled
                "rogue": int(d1["rogue"] - d0["rogue"]),
                "warmup": int(d1["warmup"] - d0["warmup"]),
                "persist_hits": int(
                    d1["persist_hits"] - d0["persist_hits"]),
                "compile_s": comp_s,
                "steady_s": round(max(0.0, elapsed - comp_s), 4),
            }

        rep_xla = _xla0()
        wdt = run(lambda: [OSDOp(t_.OP_WRITEFULL, data=payload)])
        rdt = run(lambda: [OSDOp(t_.OP_READ, off=0,
                                 length=len(payload))])
        assert io.read("bench_0") == payload
        out["cluster_io"] = {
            "compile": _xla_delta(rep_xla),
            "object_kib": 64, "objects": n_objs, "depth": depth,
            "write_iops": round(n_objs / wdt, 1),
            "write_mbps": round(n_objs * 65536 / wdt / 1e6, 1),
            "read_iops": round(n_objs / rdt, 1),
            "read_mbps": round(n_objs * 65536 / rdt / 1e6, 1),
            "note": "full stack over loopback sockets (rados bench "
                    "role, 16-deep like ObjBencher); host-path",
        }

        # EC pool: every write's encode rides the StripeBatchQueue ->
        # the ACTIVE engine (device on the TPU backend) — the row
        # records what fraction of payload bytes rode that path
        # (VERDICT r4 item 3)
        from ceph_tpu.tpu.queue import default_queue

        ec_pool = c.create_pool("bench_ec", size=3,
                                pool_type="erasure",
                                ec_profile="k=2 m=1")
        ioec = c.client().ioctx(ec_pool)
        dq = default_queue()

        # latency attribution (PR 8): the per-stage log2 histograms
        # every tracked op feeds (osd.N.op) plus the queue's own
        # wait/compute/dispatch split (osd.N.tpuq) — windowed per
        # phase, so the row shows WHERE a write spends its time, not
        # just IOPS.  Tracing stays off: the histograms are always fed.
        from ceph_tpu.core.perf import (hist_delta, hist_merge,
                                        hist_summary, merge_stage_hists)

        def _stage_hists():
            # one payload = this process, shaped like a perf dump so
            # the shared merge (and its tpuq-once rule) applies
            payload = {f"osd.{osd_id}.op": svc.op_perf.dump()
                       for osd_id, svc in c.osds.items()}
            payload["bench.tpuq"] = dq.perf.dump()
            return merge_stage_hists([payload])

        def _attribution(h0, h1):
            out_a = {}
            for nm, after in sorted(h1.items()):
                d = hist_delta(after, h0.get(nm, {}))
                if d["count"] > 0:
                    out_a[nm] = hist_summary(d)
            return out_a

        jobs0, batches0 = dq.jobs, dq.batches
        bytes0 = dq.bytes_in
        hist0 = dict(dq.batch_jobs)
        # pipelined-write-engine counters: sub-write messages per op
        # and in-flight high-water, from the daemons' osd.N.pg sets
        def _pg_perf_totals():
            msgs = ops = 0
            hw = 0
            for svc in c.osds.values():
                d = svc.pg_perf.dump()
                msgs += d.get("subwrite_msgs", 0)
                ops += d.get("subwrite_ops", 0)
                hw = max(hw, d.get("writes_inflight", 0))
            return msgs, ops, hw

        # per-phase high-water: the replicated bench above already
        # drove the gauge to ~depth; re-arm so the EC row's overlap
        # evidence is its own
        # EC warm-until-dry: burst the SAME shape as the measured
        # phase until a whole round compiles nothing (coalesced batch
        # widths vary round to round, so one burst is not enough —
        # measured: a single 24-write warmup still left a 0.57s
        # compile inside the 64KiB window).  The compile cost lands in
        # the warmup's own aux instead of skewing IOPS.
        # rounds are the MEASURED phase's length: coalesced batch
        # widths (the crc kernel's pow2 row buckets) depend on queue
        # pressure, so a short warm burst misses buckets a full-length
        # run reaches (measured: 16-write rounds left one 0.88s
        # compile inside the 96-write 4KiB window)
        def _warm_until_steady(io_, pay, tag, rounds=4, n=16):
            w0 = _xla0()
            for r in range(rounds):
                r0 = _xla0()
                pend = []
                for i in range(n):
                    pend.append(io_.aio_operate(
                        f"{tag}{r}_{i}",
                        [OSDOp(t_.OP_WRITEFULL, data=pay)]))
                    if len(pend) >= depth:
                        pend.pop(0).result(60.0)
                for p in pend:
                    p.result(60.0)
                if _xla_delta(r0)["compiles"] == 0:
                    break
            return _xla_delta(w0)

        warm_compile = _warm_until_steady(ioec, payload, "becw", n=64)
        for svc in c.osds.values():
            svc.reset_write_inflight_hw()
        msgs0, ops0, _ = _pg_perf_totals()
        dstat0 = dq.stats.snapshot()
        lat0 = _stage_hists()
        xla0 = _xla0()
        n_ec = 64
        # measured phase runs with the steady-state guard ARMED: after
        # boot warmup + warm-until-dry, a compile in this window is an
        # ABI bug and lands in the row, not just in skewed IOPS
        from ceph_tpu.tpu.devwatch import GUARD_VIOLATIONS as _GV
        guard0 = len(_GV)
        t0 = time.perf_counter()
        pend = []
        with _dwatch().steady_state():
            for i in range(n_ec):
                pend.append(ioec.aio_operate(
                    f"becq_{i}",
                    [OSDOp(t_.OP_WRITEFULL, data=payload)]))
                if len(pend) >= depth:
                    pend.pop(0).result(60.0)
            for p in pend:
                p.result(60.0)
        ec_wdt = time.perf_counter() - t0
        ec_guard_violations = _GV[guard0:]
        del _GV[guard0:]
        assert ioec.read("becq_0") == payload
        # MEASURED batched-payload fraction (was a backend-name
        # hardcode that reported 0.0 whenever the aux rows ran in the
        # CPU subprocess, even though every write DID ride the queue):
        # plane bytes the StripeBatchQueue actually carried vs client
        # payload bytes — >= 1.0 means everything batched (padding and
        # replica-side encodes can push it past 1)
        q_bytes = dq.bytes_in - bytes0
        frac = min(1.0, q_bytes / float(n_ec * len(payload)))
        # jobs-per-batch histogram delta: the falsifiable batching
        # evidence the old 0.0 row couldn't give — mean width > 1
        # means concurrent writes really coalesced into one matmul
        jb_hist = {str(w): n - hist0.get(w, 0)
                   for w, n in sorted(dq.batch_jobs.items())
                   if n - hist0.get(w, 0) > 0}
        d_jobs = dq.jobs - jobs0
        d_batches = dq.batches - batches0
        msgs1, ops1, infl_hw = _pg_perf_totals()
        d_ops = ops1 - ops0
        lat_64k = _attribution(lat0, _stage_hists())
        out["cluster_io_ec"] = {
            "object_kib": 64, "objects": n_ec, "profile": "k=2 m=1",
            "write_iops": round(n_ec / ec_wdt, 1),
            "write_mbps": round(n_ec * 65536 / ec_wdt / 1e6, 1),
            "queue_jobs": d_jobs,
            "queue_batches": d_batches,
            "queue_bytes": q_bytes,
            "jobs_per_batch_hist": jb_hist,
            "mean_jobs_per_batch": round(
                d_jobs / d_batches, 2) if d_batches else 0.0,
            "subwrite_msgs_per_op": round(
                (msgs1 - msgs0) / d_ops, 2) if d_ops else 0.0,
            "writes_inflight_hw": infl_hw,
            "engine_backend": jax.default_backend(),
            "batched_payload_fraction": round(frac, 3),
            "tpu_engine_byte_fraction": round(
                frac if jax.default_backend() != "cpu" else 0.0, 3),
            "latency_attribution": lat_64k,
            "compile": _xla_delta(xla0),
            "steady_guard": {
                "armed": True,
                "violations": len(ec_guard_violations),
                "detail": ec_guard_violations[:4],
            },
            "warmup_compile": warm_compile,
            "note": "every EC stripe encode rode the StripeBatchQueue "
                    "-> active engine; batching/fan-out evidence is "
                    "measured from queue + osd.N.pg counters, not "
                    "assumed; latency_attribution = per-stage p50/p99 "
                    "us from the osd.N.op/tpuq histograms, this phase's "
                    "window only, tracing off",
        }
        # device-resident data path evidence (PR 6), counter-derived
        # so it works on CPU rigs: payload bytes uploaded per payload
        # byte written, and unsanctioned host materializations per op
        # (the metadata-only-crossing invariant; the GB/s story rides
        # the device rows above on TPU rigs)
        from ceph_tpu.ops.crc32c_device import _HAVE_JAX

        dstat1 = dq.stats.snapshot()
        d_h2d = dstat1["h2d_bytes"] - dstat0["h2d_bytes"]
        d_tch = (dstat1["payload_host_touches"]
                 - dstat0["payload_host_touches"])
        d_stg = dstat1["staged_batches"] - dstat0["staged_batches"]
        out["cluster_io_ec"].update({
            "host_path": d_stg == 0 or not _HAVE_JAX,
            "staged_batches": d_stg,
            "h2d_bytes_per_payload_byte": round(
                d_h2d / float(n_ec * len(payload)), 4),
            "payload_host_touches_per_op": round(d_tch / n_ec, 4),
            "pool_occupancy_hw": dstat1["pool_occupancy_hw"],
        })

        # small-object phase — the PR-6 tentpole's target shape: 4KiB
        # EC WRITEFULL at the same depth, its own counter window
        pay4k = b"s" * 4096
        warm_4k = _warm_until_steady(ioec, pay4k, "bsmw", n=96)
        st0 = dq.stats.snapshot()
        lat0_4k = _stage_hists()
        xla0_4k = _xla0()
        n_small = 96
        guard0 = len(_GV)
        t0 = time.perf_counter()
        pend = []
        with _dwatch().steady_state():
            for i in range(n_small):
                pend.append(ioec.aio_operate(
                    f"bsm_{i}",
                    [OSDOp(t_.OP_WRITEFULL, data=pay4k)]))
                if len(pend) >= depth:
                    pend.pop(0).result(60.0)
            for p in pend:
                p.result(60.0)
        sm_dt = time.perf_counter() - t0
        sm_guard_violations = _GV[guard0:]
        del _GV[guard0:]
        assert ioec.read("bsm_0") == pay4k
        st1 = dq.stats.snapshot()
        sm_h2d = st1["h2d_bytes"] - st0["h2d_bytes"]
        sm_stg = st1["staged_batches"] - st0["staged_batches"]
        out["cluster_io_ec"]["small_4k"] = {
            "objects": n_small, "object_kib": 4,
            "elapsed_s": round(sm_dt, 3),
            "write_iops": round(n_small / sm_dt, 1),
            "host_path": sm_stg == 0 or not _HAVE_JAX,
            "staged_batches": sm_stg,
            "h2d_bytes_per_payload_byte": round(
                sm_h2d / float(n_small * 4096), 4),
            "payload_host_touches_per_op": round(
                (st1["payload_host_touches"]
                 - st0["payload_host_touches"]) / n_small, 4),
            "pool_occupancy_hw": st1["pool_occupancy_hw"],
            "latency_attribution": _attribution(lat0_4k, _stage_hists()),
            "compile": _xla_delta(xla0_4k),
            "steady_guard": {
                "armed": True,
                "violations": len(sm_guard_violations),
                "detail": sm_guard_violations[:4],
            },
            "warmup_compile": warm_4k,
        }

        # -- QoS fairness (PR 13): skewed two-tenant mixed load at
        # saturation, mclock vs fifo A/B.  The reserved tenant holds a
        # dmClock reservation (tenant profile via conf); the greedy
        # tenant floods 64KiB writes with no depth cap — which also
        # exercises the per-connection edge throttle (its socket
        # stalls at osd_client_message_cap).  Per-tenant p99 is
        # client-measured per op; the osd.N.qos per-class wait
        # histograms (lat_qos_wait_us stage family) are reported
        # alongside as the scheduler-side attribution.
        from ceph_tpu.client import RadosClient
        from ceph_tpu.core.context import Context as _Ctx
        from ceph_tpu.msg.message import EntityName as _EN

        def _tenant(cluster, num):
            rc = RadosClient(_Ctx("client.vstart", {}),
                             name=_EN("client", num))
            rc.connect(cluster.monmap)
            return rc

        def _lat_stats(lats):
            s = sorted(lats)
            return {"ops": len(s),
                    "p50_ms": round(1e3 * s[len(s) // 2], 2),
                    "p99_ms": round(
                        1e3 * s[min(len(s) - 1, int(0.99 * len(s)))], 2),
                    "mean_ms": round(1e3 * sum(s) / len(s), 2)}

        N_TRICKLE = 16

        def _qos_arm(cluster, pool_id, label):
            res_cl = _tenant(cluster, 777)
            grd_cl = _tenant(cluster, 666)
            try:
                rio = res_cl.ioctx(pool_id)
                gio = grd_cl.ioctx(pool_id)
                pay_g, pay_r = b"G" * 65536, b"R" * 4096

                def trickle(n, tag, timeout):
                    lats = []
                    for i in range(n):
                        t1 = time.perf_counter()
                        rep = rio.operate(
                            f"{label}_{tag}_{i}",
                            [OSDOp(t_.OP_WRITEFULL, data=pay_r)],
                            timeout=timeout)
                        assert rep.result == 0, rep.result
                        lats.append(time.perf_counter() - t1)
                    return lats

                # single-tenant parity leg (scheduler overhead A/B)
                t1 = time.perf_counter()
                trickle(64, "s", 60.0)
                solo_dt = time.perf_counter() - t1
                unloaded = _lat_stats(trickle(N_TRICKLE, "u", 60.0))
                # sustained flood: a feeder keeps the greedy tenant's
                # offered depth topped up for the WHOLE trickle window
                # (a one-shot burst drains before the trickle ends and
                # proves nothing), under the edge cap set below — the
                # overflow queues at the greedy socket, which is
                # exactly the backpressure role under test
                import threading as _th

                stop_feed = _th.Event()
                fl = {"pend": [], "done": 0}

                def _feeder() -> None:
                    i = 0
                    pend = fl["pend"]
                    while not stop_feed.is_set():
                        while (len(pend) < 48
                               and not stop_feed.is_set()):
                            pend.append(gio.aio_operate(
                                f"{label}_g_{i}",
                                [OSDOp(t_.OP_WRITEFULL, data=pay_g)],
                                timeout=600.0))
                            i += 1
                        if pend:
                            assert pend[0].result(600.0).result == 0
                            pend.pop(0)
                            fl["done"] += 1

                def _qos_snap():
                    return {i: svc.qos.perf.dump()
                            for i, svc in cluster.osds.items()}

                snap0 = _qos_snap()
                t1 = time.perf_counter()
                feeder = _th.Thread(target=_feeder, daemon=True)
                feeder.start()
                loaded_lats = trickle(N_TRICKLE, "l", 300.0)
                trickle_done = time.perf_counter()
                flood_pending = len(fl["pend"])
                greedy_in_window = fl["done"]
                stop_feed.set()
                feeder.join(timeout=600.0)
                for f in fl["pend"]:
                    assert f.result(600.0).result == 0
                    fl["done"] += 1
                flood_dt = time.perf_counter() - t1
                # scheduler-side per-class evidence: the loaded-phase
                # WINDOW of every daemon's per-class wait histograms,
                # hist-delta'd then merged across OSDs (one daemon's
                # slice alone is a 1/3rd sample)
                stalls = sum(
                    svc.msgr.perf.dump().get("throttle_stall", 0)
                    for svc in cluster.osds.values())
                snap1 = _qos_snap()
                merged_w: dict = {}
                for i, d1 in snap1.items():
                    d0 = snap0.get(i, {})
                    for name, val in d1.items():
                        if not (name.startswith("wait_us_")
                                and isinstance(val, dict)):
                            continue
                        before = d0.get(name)
                        if not isinstance(before, dict):
                            before = {}
                        hist_merge(merged_w.setdefault(name, {}),
                                   hist_delta(val, before))
                waits = {
                    name[len("wait_us_"):]: hist_summary(h)
                    for name, h in merged_w.items()
                    if int(h.get("count", 0)) > 0}
                window_s = max(trickle_done - t1, 1e-6)
                return {
                    "greedy_ops": fl["done"],
                    "greedy_object_kib": 64,
                    "reserved_ops": N_TRICKLE,
                    "reserved_object_kib": 4,
                    "bytes_skew_in_window": round(
                        greedy_in_window * 65536
                        / (N_TRICKLE * 4096), 1),
                    "single_tenant_iops": round(64 / solo_dt, 1),
                    "reserved_unloaded": unloaded,
                    "reserved_loaded": _lat_stats(loaded_lats),
                    "reserved_iops_loaded": round(
                        N_TRICKLE / window_s, 1),
                    "greedy_iops_in_window": round(
                        greedy_in_window / window_s, 1),
                    "greedy_iops": round(fl["done"] / flood_dt, 1),
                    "flood_pending_at_trickle_done": flood_pending,
                    "throttle_stalls": stalls,
                    "qos_wait_us_by_class": dict(sorted(
                        waits.items())),
                }
            finally:
                res_cl.shutdown()
                grd_cl.shutdown()

        # reserved tenant profile lands through the conf observer on
        # every daemon sharing the cluster ctx (the `qos set` path);
        # the 16-op edge cap bounds the greedy tenant's DOWNSTREAM
        # footprint (encode/commit pipelines have no scheduler), so
        # admission fairness is measurable end to end and the throttle
        # role itself shows up as stall counts
        c.ctx.conf.set_val("osd_qos_profiles",
                           "tenant:client.777=200:200:0")
        c.ctx.conf.set_val("osd_client_message_cap", 16)
        try:
            qos_rows = {"mclock": _qos_arm(c, ec_pool, "qmc")}
        finally:
            c.ctx.conf.set_val("osd_client_message_cap", 256)
        with VStartCluster(n_mons=1, n_osds=3,
                           conf={"osd_op_queue": "fifo",
                                 "osd_client_message_cap": 16,
                                 "osd_qos_profiles":
                                     "tenant:client.777=200:200:0"}
                           ) as c_fifo:
            fifo_pool = c_fifo.create_pool(
                "bench_ec_fifo", size=3, pool_type="erasure",
                ec_profile="k=2 m=1")
            qos_rows["fifo"] = _qos_arm(c_fifo, fifo_pool, "qff")
        mc, ff = qos_rows["mclock"], qos_rows["fifo"]
        qos_rows["starvation_ratio_p50"] = round(
            ff["reserved_loaded"]["p50_ms"]
            / max(mc["reserved_loaded"]["p50_ms"], 1e-3), 2)
        # the scheduler's own starvation number: reserved-class
        # admission-wait p99, fifo vs mclock (end-to-end tails on this
        # host rig are store-commit-bound — the stage attribution
        # separates what the scheduler controls from what it doesn't)
        try:
            qos_rows["admission_wait_ratio_p99"] = round(
                ff["qos_wait_us_by_class"]["client_client_777"]["p99_us"]
                / max(mc["qos_wait_us_by_class"]["client_client_777"]
                      ["p99_us"], 1e-3), 2)
        except KeyError:
            qos_rows["admission_wait_ratio_p99"] = None
        qos_rows["note"] = (
            "skewed two-tenant load: reserved tenant "
            "(200 iops reservation) trickles 4KiB writes while a "
            "feeder keeps a greedy tenant's 64KiB flood topped up for "
            "the whole window, under a 16-op per-connection edge cap "
            "(overflow queues at the greedy socket — throttle_stalls); "
            "per-tenant p50/p99 client-measured per op, scheduler "
            "waits from the osd.N.qos per-class histograms; fifo arm "
            "= same load on an osd_op_queue=fifo cluster (separate "
            "boot: the scheduler is not runtime-switchable)")
        out["cluster_io_ec"]["qos_fairness"] = qos_rows

        # degraded-PG recovery (read-side twin of the write evidence):
        # ONE pg so every missing object rides the revived primary's
        # windowed pull; objects/s, sub-read msgs per object per peer,
        # and the decode jobs-per-batch histogram are all measured
        # from the engine's counters, not assumed
        rec_pool = c.create_pool("bench_ecr", size=3,
                                 pool_type="erasure",
                                 ec_profile="k=2 m=1", pg_num=1)
        iorec = c.client().ioctx(rec_pool)
        rec_pgid = (rec_pool, 0)
        mm = c.leader().osdmap
        _u2, _up2, r_acting, r_prim = mm.pg_to_up_acting(rec_pgid)
        rpay = b"r" * 16384
        iorec.aio_operate("rcv_warm", [OSDOp(t_.OP_WRITEFULL,
                                             data=rpay)]).result(30.0)
        c.kill_osd(r_prim)
        c.wait_for(lambda: not c.leader().osdmap.is_up(r_prim),
                   what="bench_ecr primary marked down")
        # 320 objects: long enough that the feedback demo can show the
        # controller BOTH clamped (aimed client pressure, first part)
        # and widened (pressure drained + the rate window decayed, the
        # remaining rounds run at the widened width)
        n_rec = 320
        pend = []
        for i in range(n_rec):
            pend.append(iorec.aio_operate(
                f"rcv_{i}", [OSDOp(t_.OP_WRITEFULL, data=rpay)]))
            if len(pend) >= depth:
                pend.pop(0).result(60.0)
        for p in pend:
            p.result(60.0)
        dec_hist0 = dict(dq.dec_batch_jobs)
        # counters are shared by name across daemon incarnations
        # (one ctx): measure deltas, not absolutes
        rp0 = c.osds[r_prim].perf.dump().get("recovery_pushes", 0)
        pg0 = c.osds[r_prim].pg_perf.dump()
        # telemetry digest capture (ISSUE 9): the degraded debt must
        # be VISIBLE in the mon digest before recovery starts, and the
        # recovery phase samples rate + progress ETA against the
        # measured completion
        mgr = c.start_mgr()
        tel = {"degraded_ratio_peak": 0.0, "recovery_rate_peak": 0.0,
               "eta_first_s": None, "eta_error_ratio": None}
        eta_first = []  # (monotonic stamp, eta_s, event started)

        def _digest():
            return c.leader().pgmap.digest()

        c.wait_for(lambda: _digest()["degraded_objects"] > 0,
                   timeout=30.0, what="degraded debt in the digest")
        xla0_rec = _xla0()
        # recovery-feedback evidence (PR 13): client pressure aimed at
        # the recovering primary for the first part of the pull (its
        # controller should CLAMP the window), then idle (WIDEN) —
        # states sampled from `qos status` while recovery runs
        # probe against the pre-kill map snapshot (r_prim up): those
        # are the post-revive placements the pressure must hit
        press_oids = []
        i_probe = 0
        while len(press_oids) < 60 and i_probe < 4000:
            oid = f"qfb_{i_probe}"
            i_probe += 1
            try:
                pgid_p = mm.object_to_pg(rep_pool, oid)
                _u3, _up3, _a3, prim3 = mm.pg_to_up_acting(pgid_p)
            except Exception:
                break
            if prim3 == r_prim:
                press_oids.append(oid)
        qos_states: set = set()
        qos_rate_samples: list = []  # (controller state, digest rate)
        t0 = time.perf_counter()
        c.revive_osd(r_prim)
        svc = c.osds[r_prim]
        press_pend = [io.aio_operate(
            oid, [OSDOp(t_.OP_WRITEFULL, data=b"p" * 8192)],
            timeout=120.0) for oid in press_oids]

        def _sample_telemetry() -> None:
            try:
                qst = svc.qos.status()["recovery"]["state"]
                qos_states.add(qst)
                qos_rate_samples.append(
                    (qst, _digest()["io"]["recovery_objects_per_s"]))
            except Exception:
                pass  # daemon mid-boot: next sample
            d = _digest()
            tel["degraded_ratio_peak"] = max(
                tel["degraded_ratio_peak"], d["degraded_ratio"])
            tel["recovery_rate_peak"] = max(
                tel["recovery_rate_peak"],
                d["io"]["recovery_objects_per_s"])
            _code, prog = mgr.handle_command({"prefix": "progress"})
            if not eta_first:
                for ev in prog["events"]:
                    if ev["pgid"] == f"{rec_pool}.0" and \
                            ev["eta_s"] is not None:
                        eta_first.append((time.monotonic(),
                                          ev["eta_s"], ev["started"]))
                        break

        def _pulled() -> bool:
            _sample_telemetry()
            return svc.perf.dump().get(
                "recovery_pushes", 0) - rp0 >= n_rec
        c.wait_for(_pulled, timeout=120.0,
                   what="windowed pull of the degraded pg")
        rec_dt = time.perf_counter() - t0
        # drain the last stats reports so the rate ring and the
        # progress completion both see the finished recovery
        rec_deadline = time.time() + 8.0
        rec_done = None
        while time.time() < rec_deadline:
            _sample_telemetry()
            _code, prog = mgr.handle_command({"prefix": "progress"})
            rec_done = next(
                (ev for ev in prog["completed"]
                 if ev["pgid"] == f"{rec_pool}.0"), None)
            if rec_done is not None and tel["recovery_rate_peak"] > 0:
                break
            time.sleep(0.3)
        for p in press_pend:
            try:
                p.result(120.0)
            except Exception:
                pass  # a straggler pressure write is not the story
        try:
            rec_qos = svc.qos.status()["recovery"]
        except Exception:
            rec_qos = {}
        if eta_first and rec_done is not None:
            stamp, eta0, started = eta_first[0]
            actual = (started + rec_done["duration_s"]) - stamp
            tel["eta_first_s"] = eta0
            if actual > 0:
                tel["eta_error_ratio"] = round(
                    abs(eta0 - actual) / actual, 3)
        pgd = svc.pg_perf.dump()
        sr_msgs = pgd.get("subread_msgs", 0) - pg0.get("subread_msgs", 0)
        sr_ops = pgd.get("subread_ops", 0) - pg0.get("subread_ops", 0)
        live_peers = 2  # k=2,m=1 over 3 osds, primary recovering
        dec_hist = {str(w): n - dec_hist0.get(w, 0)
                    for w, n in sorted(dq.dec_batch_jobs.items())
                    if n - dec_hist0.get(w, 0) > 0}
        dec_jobs = sum(w * n for w, n in dq.dec_batch_jobs.items()) \
            - sum(w * n for w, n in dec_hist0.items())
        dec_batches = sum(dq.dec_batch_jobs.values()) \
            - sum(dec_hist0.values())
        out["cluster_io_ec"]["recovery"] = {
            "missing_objects": n_rec, "object_kib": 16,
            "elapsed_s": round(rec_dt, 3),
            "objects_per_s": round(n_rec / rec_dt, 1),
            "recovery_window_hw": pgd.get("recovery_active", 0),
            "subread_msgs": sr_msgs,
            "subread_ops": sr_ops,
            "subread_msgs_per_object_per_peer": round(
                sr_msgs / sr_ops / live_peers, 3) if sr_ops else 0.0,
            "recover_on_read_hits": (
                pgd.get("recover_on_read_hits", 0)
                - pg0.get("recover_on_read_hits", 0)),
            "decode_batch_jobs_hist": dec_hist,
            "mean_decode_jobs_per_batch": round(
                dec_jobs / dec_batches, 2) if dec_batches else 0.0,
            "compile": _xla_delta(xla0_rec),
            "qos_feedback": {
                "states_seen": sorted(qos_states),
                "widened_grants": rec_qos.get("widened", 0),
                "clamped_grants": rec_qos.get("clamped", 0),
                "final_window": rec_qos.get("effective_window", 0),
                "pressure_ops": len(press_oids),
                # digest recovery objects/s (the PR 9 rate ring)
                # averaged per controller state: the closed loop's
                # measured effect, slower clamped / faster widened
                "digest_rate_by_state": {
                    st: round(sum(r for s, r in qos_rate_samples
                                  if s == st and r > 0)
                              / max(1, sum(1 for s, r in
                                           qos_rate_samples
                                           if s == st and r > 0)), 1)
                    for st in sorted(qos_states)},
                "note": "recovery-vs-client arbitration closed-loop: "
                        "client pressure aimed at the recovering "
                        "primary for the first part of the pull "
                        "(controller clamps), idle after (controller "
                        "widens); states sampled live from qos status",
            },
            "telemetry": {
                **tel,
                "note": "mon PGMap digest during the phase: peak "
                        "degraded ratio + recovery objects/s from the "
                        "rate ring, first progress-event ETA vs the "
                        "event's measured duration (None = recovery "
                        "outran the stats cadence)",
            },
            "note": "revived primary pulls a 1-pg degraded EC pool "
                    "through the windowed recovery engine; includes "
                    "boot+peering latency (same in any A/B arm)",
        }

        # always-on deep scrub (PR 15): the populated 1-pg bench_ecr
        # pool streams through the ScrubEngine's chunked
        # decode-and-reverify — objects/s, mean decode batch width
        # (the coalescing evidence), compile-vs-steady split, and the
        # client-p99 impact of scrubbing WHILE a client load runs
        # under the QoS scrub class
        mm2 = c.leader().osdmap
        _u4, _up4, _a4, sc_prim = mm2.pg_to_up_acting(rec_pgid)
        sc_pg = c.osds[sc_prim].pgs[rec_pgid]
        sc_eng = sc_pg.scrub_engine()
        n_obj = len(sc_pg.backend.object_names())
        xla0_sc = _xla0()
        t0 = time.perf_counter()
        errs_warm = sc_eng.run(deep=True)
        warm_dt = time.perf_counter() - t0
        dec0 = dict(dq.dec_batch_jobs)
        xla1_sc = _xla0()
        t0 = time.perf_counter()
        errs_steady = sc_eng.run(deep=True)
        steady_dt = time.perf_counter() - t0
        dec_d = {str(w): n - dec0.get(w, 0)
                 for w, n in sorted(dq.dec_batch_jobs.items())
                 if n - dec0.get(w, 0) > 0}
        djobs = sum(int(w) * n for w, n in dec_d.items())
        dbatches = sum(dec_d.values())

        def _wr_lats(n_ops: int) -> list:
            lats = []
            for i in range(n_ops):
                t1 = time.perf_counter()
                io.aio_operate(f"scl_{i}", [OSDOp(
                    t_.OP_WRITEFULL, data=b"s" * 4096)]).result(60.0)
                lats.append((time.perf_counter() - t1) * 1e3)
            return lats

        def _pct(lats, q):
            s = sorted(lats)
            return round(s[min(len(s) - 1, int(q * len(s)))], 2)

        import threading as _sth

        base_lats = _wr_lats(40)
        sc_thread_done = _sth.Event()

        def _bg_scrub() -> None:
            try:
                sc_eng.run(deep=True)
            finally:
                sc_thread_done.set()

        th = _sth.Thread(target=_bg_scrub, daemon=True)
        th.start()
        loaded_lats = _wr_lats(40)
        sc_thread_done.wait(120.0)
        th.join(timeout=10.0)
        sd = c.osds[sc_prim].scrub_perf.dump()
        out["cluster_io_ec"]["scrub"] = {
            "objects": n_obj, "object_kib": 16,
            "deep_scrub_warm_s": round(warm_dt, 3),
            "deep_scrub_steady_s": round(steady_dt, 3),
            "objects_per_s": round(n_obj / steady_dt, 1),
            "errors": len(errs_warm) + len(errs_steady),
            "decode_batch_jobs_hist": dec_d,
            "mean_decode_jobs_per_batch": round(
                djobs / dbatches, 2) if dbatches else 0.0,
            "compile_warm": _xla_delta(xla0_sc),
            "compile_steady": _xla_delta(xla1_sc),
            "chunks": sd.get("chunks", 0),
            "preemptions": sd.get("preemptions", 0),
            "client_4k_write_ms_unloaded": {
                "p50": _pct(base_lats, 0.5),
                "p99": _pct(base_lats, 0.99)},
            "client_4k_write_ms_while_scrubbing": {
                "p50": _pct(loaded_lats, 0.5),
                "p99": _pct(loaded_lats, 0.99)},
            "note": "chunked deep scrub of the recovered bench_ecr "
                    "pool through the ScrubEngine (QoS scrub class): "
                    "steady pass after the warm pass absorbs decode-"
                    "matrix compiles; loaded leg measures client "
                    "4KiB-write p50/p99 on the same osds while a "
                    "deep scrub runs",
        }

        # -- read-time integrity (PR 16): client EC read latency with
        # the per-extent at-rest verify gate ON vs OFF — the measured
        # verify-on-read cost at the two canonical payloads.  The
        # object-context cache is dropped before every measured read
        # so each op pays the store read (+ extent verification when
        # the gate is on) rather than a projected-state cache hit.
        n_rv = 32
        pay_rv = b"v" * 65536
        for i in range(n_rv):
            ioec.aio_operate(f"rvi_{i}", [OSDOp(
                t_.OP_WRITEFULL, data=pay_rv)]).result(60.0)

        def _drop_obc() -> None:
            for svc in c.osds.values():
                for pgid, pg in list(svc.pgs.items()):
                    if pgid[0] == ec_pool:
                        pg._obc_invalidate()

        def _rv_leg(length: int) -> list:
            lats = []
            for i in range(n_rv):
                off = (0 if length >= len(pay_rv)
                       else (i * 4096) % (len(pay_rv) - length))
                _drop_obc()
                t1 = time.perf_counter()
                got = ioec.read(f"rvi_{i}", length, off)
                lats.append((time.perf_counter() - t1) * 1e3)
                assert len(got) == length
            return lats

        rv_rows = {}
        for label, on in (("verify_on", True), ("verify_off", False)):
            c.ctx.conf.set_val("store_verify_read", on)
            _rv_leg(4096)  # warm leg: compiles + page-in
            rv_rows[label] = {
                "read_4k_ms": {"p50": _pct(l4 := _rv_leg(4096), 0.5),
                               "p99": _pct(l4, 0.99)},
                "read_64k_ms": {"p50": _pct(l64 := _rv_leg(65536), 0.5),
                                "p99": _pct(l64, 0.99)},
            }
        c.ctx.conf.set_val("store_verify_read", True)
        rv_rows["verify_overhead_us_per_64kib_read_p50"] = round(
            (rv_rows["verify_on"]["read_64k_ms"]["p50"]
             - rv_rows["verify_off"]["read_64k_ms"]["p50"]) * 1e3, 1)
        rv_rows["verify_overhead_us_per_4kib_read_p50"] = round(
            (rv_rows["verify_on"]["read_4k_ms"]["p50"]
             - rv_rows["verify_off"]["read_4k_ms"]["p50"]) * 1e3, 1)
        rv_rows["note"] = (
            "EC ranged reads (32 x 64KiB objects, obc dropped per "
            "op): store_verify_read toggled live via the conf "
            "observer; overhead = p50 delta, crc32c over exactly the "
            "served extents")
        out["cluster_io_ec"]["read_verify"] = rv_rows


# ---------------------------------------------------------------------------
# CRUSH
# ---------------------------------------------------------------------------

def _crush_common():
    from ceph_tpu.crush import map as cmap

    n_osds, n_hosts, nrep = 1024, 64, 3
    m, root = cmap.build_flat_cluster(n_osds, hosts=n_hosts)
    steps = [(cmap.OP_TAKE, root, 0),
             (cmap.OP_CHOOSELEAF_FIRSTN, nrep, 1),
             (cmap.OP_EMIT, 0, 0)]
    dev_w = np.full(n_osds, 0x10000, dtype=np.uint32)
    return m, m.flatten(), steps, nrep, dev_w


def _crush_ref_pin(out, m, steps, nrep, dev_w, got_head):
    """Reference C rate + bit-exact conformance on the first 100k ids."""
    from ceph_tpu import _crush_ref
    from ceph_tpu.crush import map as cmap

    if not _crush_ref.available():
        return
    m.add_rule(cmap.Rule("bench", steps))
    ref = _crush_ref.RefCrushMap(m)
    sub = np.arange(100_000, dtype=np.int32)
    ref_dt = 1e9
    for _ in range(2):
        t0 = time.perf_counter()
        ref_out = ref.do_rule(ref.rulenos[-1], sub, nrep, dev_w)
        ref_dt = min(ref_dt, time.perf_counter() - t0)
    out["crush_ref_c_mplacements_per_s"] = round(len(sub) / ref_dt / 1e6, 2)
    out["crush_vs_ref_c"] = round(
        out["crush_mplacements_per_s"]
        / out["crush_ref_c_mplacements_per_s"], 2)
    assert np.array_equal(got_head, ref_out), "sweep != reference C"


def _crush_device(jax, out):
    """BASELINE metric 6 on-device: ~10M ids through sweep_device — the
    ENTIRE two-stage sweep is one jit dispatch, placements stay in HBM,
    only the overflow flag and the 100k-id conformance head are
    fetched."""
    import jax.numpy as jnp

    from ceph_tpu.crush import mapper

    m, flat, steps, nrep, dev_w = _crush_common()
    n_chunks = 20
    n_x = n_chunks * CRUSH_CHUNK  # 10,485,760
    xs = jnp.arange(n_x, dtype=jnp.int32)

    res, overflow = mapper.sweep_device(flat, steps, nrep, xs, dev_w,
                                        chunk=CRUSH_CHUNK)  # compile+warm
    assert not bool(overflow), "fixup capacity overflow on healthy map"
    best = 1e18
    for _ in range(2):
        t0 = time.perf_counter()
        res, overflow = mapper.sweep_device(flat, steps, nrep, xs, dev_w,
                                            chunk=CRUSH_CHUNK)
        bool(overflow)  # sync: waits for the whole dispatch
        best = min(best, time.perf_counter() - t0)
    out["crush_mplacements_per_s"] = round(n_x / best / 1e6, 2)
    out["crush_ids"] = n_x
    out["crush_ids_measured"] = n_x
    out["crush_device_resident"] = True
    out["crush_chunk"] = CRUSH_CHUNK

    got_head = np.asarray(res[:100_000])  # one fetch, conformance only
    _crush_ref_pin(out, m, steps, nrep, dev_w, got_head)


def _crush_cpu(jax, out):
    from ceph_tpu.crush import mapper

    m, flat, steps, nrep, dev_w = _crush_common()
    n_x = 10_000_000
    xs = np.arange(n_x, dtype=np.int32)
    mapper.sweep(flat, steps, nrep, xs[:CRUSH_CHUNK], dev_w,
                 chunk=CRUSH_CHUNK)
    mapper.sweep(flat, steps, nrep, xs[CRUSH_CHUNK:2 * CRUSH_CHUNK],
                 dev_w, chunk=CRUSH_CHUNK)
    t0 = time.perf_counter()
    mapper.sweep(flat, steps, nrep, xs[:CRUSH_CHUNK], dev_w,
                 chunk=CRUSH_CHUNK)
    per_chunk = time.perf_counter() - t0
    budget_s = 180.0
    total_chunks = -(-n_x // CRUSH_CHUNK)
    run_chunks = max(1, min(total_chunks,
                            int(budget_s / max(per_chunk, 1e-9))))
    measured = min(n_x, run_chunks * CRUSH_CHUNK)
    t0 = time.perf_counter()
    res = mapper.sweep(flat, steps, nrep, xs[:measured], dev_w,
                       chunk=CRUSH_CHUNK)
    dt = time.perf_counter() - t0
    out["crush_mplacements_per_s"] = round(measured / dt / 1e6, 2)
    out["crush_ids"] = n_x
    out["crush_ids_measured"] = measured
    out["crush_extrapolated"] = measured < n_x
    out["crush_chunk"] = CRUSH_CHUNK
    _crush_ref_pin(out, m, steps, nrep, dev_w, res[:100_000])


def crush_section(jax, out):
    if jax.default_backend() == "cpu":
        _crush_cpu(jax, out)
    else:
        _crush_device(jax, out)


def aux_section(jax, out):
    """Clay + jerasure/lrc BASELINE rows: host-path python-codec
    measurements.  On the axon rig an in-process run would time the
    tunnel (~80-94 ms RTT per dispatch), not the codec, so on the TPU
    backend they run in a scrubbed CPU subprocess and merge in,
    labeled; on the CPU fallback they run in-process (the host path IS
    the product path there)."""
    import os
    import subprocess
    import tempfile

    if jax.default_backend() == "cpu":
        # preserve per-row fault isolation: a clay bug must not erase
        # the jerasure/lrc rows (each records its own error)
        for name, fn in (("clay", clay_repair),
                         ("clay_device", clay_repair_device),
                         ("clay_recovery", clay_recovery),
                         ("baseline_configs", baseline_configs),
                         ("cluster_io", cluster_io)):
            try:
                fn(jax, out)
            except Exception:
                out.setdefault("errors", {})[name] = \
                    traceback.format_exc(limit=4)
        return

    here = os.path.dirname(os.path.abspath(__file__))
    fd, path = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "TPU_", "LIBTPU", "XLA_",
                                "PJRT_", "PALLAS_"))}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": here,
        "CEPH_TPU_BENCH_FALLBACK": "explicit",
        "CEPH_TPU_BENCH_SECTIONS": "aux",
        "CEPH_TPU_BENCH_PARTIAL_PATH": path,
    })
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "bench.py")],
            env=env, capture_output=True, text=True, timeout=1200)
        try:
            with open(path) as f:
                sub = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            # subprocess died before its first section flush: surface
            # ITS stderr, not a bare JSONDecodeError
            raise RuntimeError(
                f"aux subprocess rc={proc.returncode}: {e!r}; "
                f"stderr tail: {proc.stderr[-400:]}") from e
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    for k in ("clay_repair_gbps", "clay_repair_read_frac_vs_rs",
              "clay_repair_device_gbps", "clay_repair_device_evidence",
              "clay_repair_device_kernel_gbps",
              "clay_repair_device_vs_host",
              "clay_repair_device_vs_host_baseline", "clay_recovery",
              "jerasure_k4m2_4k_encode_gbps", "lrc_profile",
              "lrc_local_repair_reads", "lrc_local_repair_gbps",
              "cluster_io", "cluster_io_ec"):
        if k in sub:
            out[k] = sub[k]
    # surface the subprocess's own failures in THIS artifact: missing
    # rows must be explained, not silent
    for name, err in (sub.get("errors") or {}).items():
        out.setdefault("errors", {})[f"aux/{name}"] = err
    out["aux_measured_on"] = "host cpu subprocess (host-path codecs)"


# north stars FIRST: a tunnel wedge mid-run must cost the aux rows,
# never the EC sweep or the CRUSH sweep (VERDICT r3 weak #1).  crush
# runs AFTER small_stripe: a TPU-worker crash mid-crush poisons the
# in-process jax client, and aux (subprocess) is the only section
# immune to that.
SECTIONS = [
    ("envelope", envelope),
    ("ec", ec_section),
    ("small_stripe", small_stripe_batched),
    ("crush", crush_section),
    ("aux", aux_section),
]


def _probe_accelerator(timeout_s: float = 240.0) -> bool:
    """True if the attached accelerator answers within the timeout.

    Probed in a SUBPROCESS: a wedged axon tunnel hangs jax.devices()
    indefinitely (round-3 outages), and once jax initializes against a
    broken backend in-process there is no recovery.  On failure the
    bench falls back to CPU so the round artifact still records
    numbers (labeled backend=cpu) instead of nothing.
    """
    import os
    import subprocess

    timeout_s = float(os.environ.get("CEPH_TPU_PROBE_TIMEOUT", timeout_s))
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices(); print('ok')"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        return proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    import os

    if os.environ.get("CEPH_TPU_BENCH_FALLBACK") not in ("1", "explicit"):
        # an explicit JAX_PLATFORMS=cpu run skips the probe but still
        # re-execs scrubbed below: the axon sitecustomize touches the
        # tunnel at interpreter start even under JAX_PLATFORMS=cpu,
        # and a wedged tunnel hangs the import (observed this round)
        explicit_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
        if explicit_cpu or not _probe_accelerator():
            # the axon sitecustomize imports jax at interpreter START,
            # so env mutation in-process is too late — re-exec scrubbed
            # (the same discipline as conftest.py / dryrun_multichip)
            print("bench: explicit CPU run -> re-exec scrubbed"
                  if explicit_cpu else
                  "bench: accelerator probe failed/timed out -> re-exec "
                  "on CPU", file=sys.stderr, flush=True)
            env = {k: v for k, v in os.environ.items()
                   if not (k.startswith(("JAX_", "TPU_", "LIBTPU", "XLA_",
                                         "PJRT_", "PALLAS_")))}
            env["JAX_PLATFORMS"] = "cpu"
            env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
            env["CEPH_TPU_BENCH_FALLBACK"] = \
                "explicit" if explicit_cpu else "1"
            os.execve(sys.executable, [sys.executable, __file__], env)

    print("bench: importing jax...", file=sys.stderr, flush=True)
    import jax

    print(f"bench: backend={jax.default_backend()} "
          f"devices={jax.devices()}", file=sys.stderr, flush=True)
    out = {"backend": jax.default_backend(), "errors": {}}
    fb = os.environ.get("CEPH_TPU_BENCH_FALLBACK")
    if fb == "1":
        out["accelerator_fallback"] = (
            "attached accelerator unreachable (probe timeout); "
            "numbers are CPU")
    elif fb == "explicit":
        out["accelerator_fallback"] = (
            "explicit JAX_PLATFORMS=cpu run; numbers are CPU")
    partial_path = os.environ.get("CEPH_TPU_BENCH_PARTIAL_PATH") or \
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_PARTIAL.json")

    def _flush_partial():
        # wedge-proofing: the artifact-so-far hits disk after EVERY
        # section, so a tunnel wedge mid-run keeps every completed
        # section's numbers instead of erasing the round
        try:
            with open(partial_path, "w") as f:
                f.write(json.dumps(out) + "\n")
        except OSError:
            pass

    # watchdog: a tunnel that wedges MID-SECTION hangs that dispatch
    # forever — after section_timeout with no progress, emit the
    # one-line JSON with everything recorded so far and hard-exit.
    import threading

    section_timeout = float(os.environ.get("CEPH_TPU_SECTION_TIMEOUT",
                                           "900"))
    progress = {"t": time.monotonic(), "name": "startup", "done": False}

    def _watchdog():
        while not progress["done"]:
            time.sleep(5)
            if (not progress["done"]
                    and time.monotonic() - progress["t"] > section_timeout):
                out["errors"][progress["name"]] = (
                    f"section hung > {section_timeout}s "
                    "(accelerator wedged mid-run?)")
                out.setdefault("watchdog_fired", progress["name"])
                _flush_partial()
                _emit(out)
                os._exit(1)

    threading.Thread(target=_watchdog, daemon=True).start()

    only = os.environ.get("CEPH_TPU_BENCH_SECTIONS")
    sections = [s for s in SECTIONS if not only or s[0] in only.split(",")]
    for name, fn in sections:
        t0 = time.perf_counter()
        progress.update(t=time.monotonic(), name=name)
        print(f"bench: section {name} start", file=sys.stderr, flush=True)
        try:
            fn(jax, out)
            print(f"bench: section {name} done "
                  f"({time.perf_counter() - t0:.1f}s)",
                  file=sys.stderr, flush=True)
        except Exception:
            out["errors"][name] = traceback.format_exc(limit=4)
            print(f"bench: section {name} FAILED "
                  f"({time.perf_counter() - t0:.1f}s)",
                  file=sys.stderr, flush=True)
        _flush_partial()
    progress["done"] = True

    value = _emit(out)
    # rc=0 whenever the headline numbers were recorded, even if an
    # auxiliary section failed — the artifact must carry the wins
    return 0 if value > 0 else 1


def _emit(out) -> float:
    """Finalize + print the ONE-line JSON artifact (also used by the
    hang watchdog to salvage a partial run)."""
    enc = out.get("encode_gbps")
    dec = out.get("decode_gbps")
    # vs_baseline is judged against the BEST cpu number we recorded
    base = max(out.get("baseline_cpu_native_gbps") or 0,
               out.get("baseline_cpu_vectorized_gbps") or 0) or None
    if enc and dec:
        value = round(2 / (1 / enc + 1 / dec), 3)
    else:
        value = 0.0
    out.update({
        "metric": (f"EC encode+decode GB/s (RS k={K},m={M}, 1MiB object, "
                   f"{out['backend']}) + CRUSH {out.get('crush_ids', 0)}-id "
                   "sweep"),
        "value": value,
        "unit": "GB/s",
        "vs_baseline": round(value / base, 2) if (value and base) else 0,
    })
    if not out.get("errors"):
        out.pop("errors", None)
    print(json.dumps(out), flush=True)
    return value


if __name__ == "__main__":
    try:
        rc = main()
    except Exception as e:  # one line, always
        print(json.dumps({"metric": "bench-error", "value": 0, "unit": "GB/s",
                          "vs_baseline": 0, "error": repr(e)}))
        rc = 1
    sys.exit(rc)
