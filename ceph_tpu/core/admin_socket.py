"""Admin socket — live JSON command endpoint per daemon.

Reference: AdminSocket (src/common/admin_socket.h:41) — a unix-domain
socket each daemon serves; `ceph daemon <name> <cmd>` sends a JSON
command and reads a JSON reply.  Built-ins registered here mirror the
reference set: perf dump, config get/set/diff, log dump, help.
Protocol: one JSON object per line in, one JSON document out,
connection closed after each command (matches the reference's
one-shot framing).
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Any, Callable, Dict


class AdminSocket:
    def __init__(self, path: str) -> None:
        self.path = path
        self._commands: Dict[str, tuple[Callable[[Dict[str, Any]], Any], str]] = {}
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.register("help", lambda cmd: {
            name: desc for name, (_, desc) in sorted(self._commands.items())
        }, "list available commands")

    def register(
        self,
        prefix: str,
        fn: Callable[[Dict[str, Any]], Any],
        desc: str = "",
    ) -> None:
        self._commands[prefix] = (fn, desc)

    def start(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(8)
        self._sock.settimeout(0.25)
        self._thread = threading.Thread(
            target=self._serve, name="admin-socket", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        assert self._sock is not None
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                if self._stop.is_set():
                    return
                # transient accept error (e.g. EMFILE): back off instead
                # of spinning a core while the condition persists — on
                # the stop event, so shutdown interrupts the back-off
                self._stop.wait(0.25)
                continue
            try:
                data = b""
                conn.settimeout(5.0)
                try:
                    while b"\n" not in data:
                        chunk = conn.recv(65536)
                        if not chunk:
                            break
                        data += chunk
                    reply = self._handle(data.split(b"\n", 1)[0])
                except socket.timeout:
                    reply = b'{"error": "request timed out"}\n'
                conn.sendall(reply)
            except OSError:
                pass
            finally:
                conn.close()

    def _handle(self, line: bytes) -> bytes:
        try:
            cmd = json.loads(line.decode("utf-8") or "{}")
            prefix = cmd.get("prefix", "help")
            entry = self._commands.get(prefix)
            if entry is None:
                out: Any = {"error": f"unknown command {prefix!r}"}
            else:
                out = entry[0](cmd)
        except Exception as e:  # noqa: BLE001 — never kill the server
            out = {"error": str(e)}
        return json.dumps(out, default=str).encode("utf-8") + b"\n"

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            self._sock.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if os.path.exists(self.path):
            try:
                os.unlink(self.path)
            except OSError:
                pass


def admin_command(path: str, prefix: str, **kwargs: Any) -> Any:
    """Client side: `ceph daemon` equivalent."""
    cmd = {"prefix": prefix, **kwargs}
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        s.settimeout(5.0)
        s.connect(path)
        s.sendall(json.dumps(cmd).encode("utf-8") + b"\n")
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
        return json.loads(data.decode("utf-8"))
    finally:
        s.close()
