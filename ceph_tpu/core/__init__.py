"""Core host runtime (L0/L1): encoding, crc, config, log, perf, throttle.

The infrastructure layer every daemon and client shares, mirroring the
reference's `src/include/` + `src/common/` + `src/log/` + `src/global/`
(reference: SURVEY.md L0/L1 rows): versioned wire encoding, crc32c,
typed config with hot reload, leveled subsystem logging with a crash
ring, perf counters, throttles, the admin socket, thread liveness, and
sharded work queues.
"""
