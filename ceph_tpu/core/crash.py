"""Crash-dump capture + archive.

Reference roles: the crash metadata writer (src/global/signal_handler.cc
writes a backtrace + recent log ring on fatal signals; the ceph-crash
agent and the mgr crash module, src/pybind/mgr/crash/module.py, archive
and list them).  Here `CrashArchive.record()` captures a Python
exception — backtrace, entity, version, the log ring tail — as a JSON
crash report in a spool directory; `install()` hooks
`threading.excepthook` so an unhandled daemon-thread death is archived
automatically; `ls`/`info` serve the mgr `crash ls` commands.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from typing import Dict, List, Optional


class CrashArchive:
    def __init__(self, path: str, entity: str = "",
                 log=None) -> None:
        self.path = path
        self.entity = entity
        self.log = log
        self._lock = threading.Lock()
        self._installed_hook = None
        os.makedirs(path, exist_ok=True)

    # -- capture ----------------------------------------------------------
    def record(self, exc: BaseException,
               entity: Optional[str] = None) -> str:
        """Archive one crash; returns the crash id."""
        stamp = time.time()
        with self._lock:
            crash_id = (time.strftime("%Y-%m-%dT%H:%M:%S",
                                      time.gmtime(stamp))
                        + f".{int(stamp * 1e6) % 1_000_000:06d}")
            report = {
                "crash_id": crash_id,
                "timestamp": stamp,
                "entity_name": entity or self.entity,
                "exception": repr(exc),
                "backtrace": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
                "recent_events": (self.log.dump_recent(200)
                                  if self.log is not None else []),
            }
            with open(os.path.join(self.path, crash_id + ".json"),
                      "w") as f:
                json.dump(report, f, indent=1)
        return crash_id

    def install(self) -> None:
        """Hook threading.excepthook: a daemon thread dying on an
        unhandled exception leaves a crash report behind (the fatal
        signal-handler role)."""
        prev = threading.excepthook

        def hook(args):
            if args.exc_value is not None:
                try:
                    self.record(args.exc_value)
                except Exception:
                    pass
            prev(args)

        self._installed_hook = hook
        threading.excepthook = hook

    def uninstall(self) -> None:
        if (self._installed_hook is not None
                and threading.excepthook is self._installed_hook):
            threading.excepthook = threading.__excepthook__
        self._installed_hook = None

    # -- query (mgr crash module commands) --------------------------------
    def ls(self) -> List[Dict[str, object]]:
        out = []
        for fn in sorted(os.listdir(self.path)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.path, fn)) as f:
                    r = json.load(f)
                out.append({"crash_id": r["crash_id"],
                            "entity_name": r.get("entity_name", ""),
                            "timestamp": r.get("timestamp", 0)})
            except (OSError, ValueError):
                continue
        return out

    def info(self, crash_id: str) -> Optional[Dict[str, object]]:
        p = os.path.join(self.path, crash_id + ".json")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def prune(self, keep: int = 100) -> None:
        files = sorted(fn for fn in os.listdir(self.path)
                       if fn.endswith(".json"))
        for fn in files[:-keep] if keep else files:
            try:
                os.unlink(os.path.join(self.path, fn))
            except OSError:
                pass
