"""Crash-dump capture + archive.

Reference roles: the crash metadata writer (src/global/signal_handler.cc
writes a backtrace + recent log ring on fatal signals; the ceph-crash
agent and the mgr crash module, src/pybind/mgr/crash/module.py, archive
and list them).  Here `CrashArchive.record()` captures a Python
exception — backtrace, entity, version, the log ring tail, and a
DEVICE section (queue depth, staging occupancy, the in-flight batch,
last compiles — see ceph_tpu.tpu.devwatch.device_state) — as a JSON
crash report in a spool directory; `install()` hooks
`threading.excepthook` AND `sys.excepthook` so an unhandled daemon
thread OR main-thread death is archived automatically, and registers
the archive for asyncio event-loop deaths (messengers wire their
loops through :func:`install_loop_handler`); `ls`/`info` serve the
mgr `crash ls` commands.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

# archives whose install() is live: asyncio loop handlers (wired per
# loop by install_loop_handler) record into every one of these — the
# loop exists before any archive does, so the binding is by lookup,
# not by reference
_INSTALLED: List["CrashArchive"] = []


class CrashArchive:
    def __init__(self, path: str, entity: str = "",
                 log=None, device_state_cb: Optional[Callable] = None
                 ) -> None:
        self.path = path
        self.entity = entity
        self.log = log
        # device-state provider for the crash report's device section;
        # default: the process-wide DeviceWatch snapshot (a wedged
        # device worker leaves its in-flight batch + last compiles in
        # the corpse).  Pass a callable to override (tests).
        self.device_state_cb = device_state_cb
        self._lock = threading.Lock()
        self._installed_hook = None
        self._installed_sys_hook = None
        self._prev_hook = None
        self._prev_sys_hook = None
        os.makedirs(path, exist_ok=True)

    # -- capture ----------------------------------------------------------
    def _device_section(self) -> Optional[Dict[str, object]]:
        cb = self.device_state_cb
        if cb is None:
            try:
                from ceph_tpu.tpu.devwatch import watch

                cb = watch().device_state
            except Exception:  # pragma: no cover — torn interpreter
                return None
        try:
            return cb()
        except Exception as e:  # the device snapshot must never
            return {"error": repr(e)}  # prevent the crash report itself

    def record(self, exc: BaseException,
               entity: Optional[str] = None) -> str:
        """Archive one crash; returns the crash id."""
        stamp = time.time()
        device = self._device_section()
        with self._lock:
            crash_id = (time.strftime("%Y-%m-%dT%H:%M:%S",
                                      time.gmtime(stamp))
                        + f".{int(stamp * 1e6) % 1_000_000:06d}")
            report = {
                "crash_id": crash_id,
                "timestamp": stamp,
                "entity_name": entity or self.entity,
                "exception": repr(exc),
                "backtrace": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
                "recent_events": (self.log.dump_recent(200)
                                  if self.log is not None else []),
            }
            if device is not None:
                report["device"] = device
            with open(os.path.join(self.path, crash_id + ".json"),
                      "w") as f:
                json.dump(report, f, indent=1)
        return crash_id

    def install(self) -> None:
        """Hook the process's unhandled-exception surfaces: a daemon
        THREAD dying (threading.excepthook), the MAIN thread dying
        (sys.excepthook), and — via install_loop_handler, which
        messengers call on their event loops — an asyncio callback
        dying, all leave a crash report behind (the fatal
        signal-handler role; before this, only daemon threads did)."""
        prev = threading.excepthook

        def hook(args):
            if args.exc_value is not None:
                try:
                    self.record(args.exc_value)
                except Exception:
                    pass
            prev(args)

        self._installed_hook = hook
        self._prev_hook = prev
        threading.excepthook = hook

        prev_sys = sys.excepthook

        def sys_hook(exc_type, exc, tb):
            if exc is not None:
                try:
                    self.record(exc)
                # cephlint: disable=silent-except — hook of last
                # resort: a failing archive write must never mask the
                # original fatal exception being reported below
                except Exception:
                    pass
            prev_sys(exc_type, exc, tb)

        self._installed_sys_hook = sys_hook
        self._prev_sys_hook = prev_sys
        sys.excepthook = sys_hook
        if self not in _INSTALLED:
            _INSTALLED.append(self)

    def uninstall(self) -> None:
        # restore the hook install() CHAINED, not the interpreter
        # default — a harness's own excepthook (pytest plugin, error
        # reporter) installed before us must survive our teardown
        if (self._installed_hook is not None
                and threading.excepthook is self._installed_hook):
            threading.excepthook = (self._prev_hook
                                    or threading.__excepthook__)
        self._installed_hook = None
        self._prev_hook = None
        if (self._installed_sys_hook is not None
                and sys.excepthook is self._installed_sys_hook):
            sys.excepthook = self._prev_sys_hook or sys.__excepthook__
        self._installed_sys_hook = None
        self._prev_sys_hook = None
        if self in _INSTALLED:
            _INSTALLED.remove(self)

    # -- query (mgr crash module commands) --------------------------------
    def ls(self) -> List[Dict[str, object]]:
        out = []
        for fn in sorted(os.listdir(self.path)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.path, fn)) as f:
                    r = json.load(f)
                out.append({"crash_id": r["crash_id"],
                            "entity_name": r.get("entity_name", ""),
                            "timestamp": r.get("timestamp", 0)})
            except (OSError, ValueError):
                continue
        return out

    def info(self, crash_id: str) -> Optional[Dict[str, object]]:
        p = os.path.join(self.path, crash_id + ".json")
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def prune(self, keep: int = 100) -> None:
        files = sorted(fn for fn in os.listdir(self.path)
                       if fn.endswith(".json"))
        for fn in files[:-keep] if keep else files:
            try:
                os.unlink(os.path.join(self.path, fn))
            except OSError:
                pass


def install_loop_handler(loop) -> None:
    """Wire an asyncio event loop's exception handler into the crash
    machinery: an exception escaping a loop callback/task is recorded
    into every installed archive, then handed to the loop's default
    handler (the log line survives unchanged).  Messengers call this
    on the loop they own — before this, an event-loop death left no
    crash report at all (the satellite fix for crash.py:58)."""
    def handler(lp, context):
        exc = context.get("exception")
        if exc is not None:
            for arch in list(_INSTALLED):
                try:
                    arch.record(exc)
                # cephlint: disable=silent-except — handler of last
                # resort: one torn archive must not stop the others,
                # and the default handler below still logs the death
                except Exception:
                    pass
        lp.default_exception_handler(context)

    loop.set_exception_handler(handler)
