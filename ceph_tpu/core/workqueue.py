"""Sharded work queues — ordered parallel dispatch for the OSD op path.

Reference: ThreadPool/WorkQueue (src/common/WorkQueue.h:28,266) and the
OSD's sharded op queue (src/osd/OSD.cc:2030 op_shardedwq, OSDShard at
:2065): items hash to a shard by ordering token (pg id), each shard is
a thread draining a priority queue, so per-PG ordering is preserved
while PGs run in parallel.

Two schedulers drain a shard (conf ``osd_op_queue``):

- ``mclock`` (default): a dmClock reservation/weight/limit queue per
  shard.  With a ``qos`` scheduler attached (osd/qos.py) the shard
  queues come from it — tenant-resolved classes, cost-aware tags,
  conf-driven profiles; standalone, a bare MClockQueue over the
  reference class defaults.
- ``fifo`` (alias ``wpq``): the legacy (priority, seq) heap — the A/B
  arm QoS measurements compare against.

``queue()`` accepts an ``on_admit(cls, phase, wait_s)`` callback fired
on the worker the moment the item is dequeued, BEFORE it runs: the
daemon marks the op's ``qos_admitted`` stage and feeds the per-class
wait histograms from it, under either scheduler (the fifo arm reports
phase ``fifo`` so A/B p99s come from the same stage histograms).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Any, Callable, Hashable, List, Optional, Tuple


def _prio_to_class(priority: int) -> str:
    """WPQ priority -> mClock op class (the mClockOpClassQueue mapping
    role: client ops at high priority, sub-ops mid, recovery/scrub low)."""
    if priority >= 60:
        return "client"
    if priority >= 10:
        return "osd_subop"
    if priority >= 3:
        return "recovery"
    return "scrub"


class ShardedWorkQueue:
    def __init__(
        self,
        name: str,
        num_shards: int,
        process: Callable[[Any], None],
        on_error: Optional[Callable[[Any, BaseException], None]] = None,
        scheduler: str = "wpq",
        qos=None,
    ) -> None:
        self.name = name
        self.process = process
        self.on_error = on_error
        self.scheduler = scheduler
        self.qos = qos
        if scheduler == "mclock":
            if qos is not None:
                self._mclock: Optional[List] = [
                    qos.make_shard_queue() for _ in range(num_shards)
                ]
            else:
                from ceph_tpu.osd.mclock import MClockQueue

                self._mclock = [MClockQueue() for _ in range(num_shards)]
        else:
            self._mclock = None
        self._shards: List[List[Tuple[int, int, Any]]] = [
            [] for _ in range(num_shards)
        ]
        self._conds = [threading.Condition() for _ in range(num_shards)]
        self._seq = itertools.count()
        self._stop = False
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,), name=f"{name}-{i}", daemon=True
            )
            for i in range(num_shards)
        ]
        self._inflight = 0
        self._drain_cond = threading.Condition()

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def queue(self, token: Hashable, item: Any, priority: int = 63,
              qos_class: Optional[str] = None, qos_cost: float = 1.0,
              on_admit: Optional[Callable[[str, str, float], None]] = None
              ) -> None:
        """Higher priority dispatches first; same token stays ordered.
        Under the mclock scheduler, `qos_class` (or the priority
        mapping) selects the dmClock class and `qos_cost` advances its
        tags (payload-byte charging).  `on_admit` fires at dequeue."""
        if self._stop:
            raise RuntimeError(f"work queue {self.name} is stopped")
        shard = hash(token) % len(self._shards)
        cls = qos_class or _prio_to_class(priority)
        entry = (item, on_admit, time.monotonic(), cls)
        with self._drain_cond:
            self._inflight += 1
        with self._conds[shard]:
            if self._mclock is not None:
                self._mclock[shard].enqueue(cls, entry, cost=qos_cost)
            else:
                heapq.heappush(
                    self._shards[shard], (-priority, next(self._seq), entry)
                )
            self._conds[shard].notify()

    def _worker(self, i: int) -> None:
        cond = self._conds[i]
        q = self._shards[i]
        mq = self._mclock[i] if self._mclock is not None else None
        while True:
            with cond:
                if mq is not None:
                    cond.wait_for(lambda: len(mq) or self._stop)
                    if self._stop and not len(mq):
                        return
                    cls, entry = mq.dequeue()
                    phase = mq.last_phase
                else:
                    cond.wait_for(lambda: q or self._stop)
                    if self._stop and not q:
                        return
                    _, _, entry = heapq.heappop(q)
                    cls, phase = entry[3], "fifo"
            item, on_admit, t0, _cls = entry
            if on_admit is not None:
                try:
                    on_admit(cls, phase, time.monotonic() - t0)
                # cephlint: disable=silent-except — QoS accounting is
                # advisory; a broken callback must never stop the item
                # itself from dispatching
                except Exception:
                    pass
            try:
                self.process(item)
            except BaseException as e:  # noqa: BLE001 — worker must survive
                if self.on_error:
                    self.on_error(item, e)
            finally:
                with self._drain_cond:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._drain_cond.notify_all()

    def drain(self, timeout: float | None = None) -> bool:
        with self._drain_cond:
            return self._drain_cond.wait_for(
                lambda: self._inflight == 0, timeout
            )

    def stop(self) -> None:
        self._stop = True
        for c in self._conds:
            with c:
                c.notify_all()
        for t in self._threads:
            t.join(timeout=5)
