"""Sharded work queues — ordered parallel dispatch for the OSD op path.

Reference: ThreadPool/WorkQueue (src/common/WorkQueue.h:28,266) and the
OSD's sharded op queue (src/osd/OSD.cc:2030 op_shardedwq, OSDShard at
:2065): items hash to a shard by ordering token (pg id), each shard is
a thread draining a priority queue, so per-PG ordering is preserved
while PGs run in parallel.  mClock/WPQ scheduling reduces here to a
(priority, seq) heap per shard — QoS class weights can be layered on
the priority without changing the structure.
"""

from __future__ import annotations

import heapq
import itertools
import threading
from typing import Any, Callable, Hashable, List, Optional, Tuple


def _prio_to_class(priority: int) -> str:
    """WPQ priority -> mClock op class (the mClockOpClassQueue mapping
    role: client ops at high priority, sub-ops mid, recovery/scrub low)."""
    if priority >= 60:
        return "client"
    if priority >= 10:
        return "osd_subop"
    if priority >= 3:
        return "recovery"
    return "scrub"


class ShardedWorkQueue:
    def __init__(
        self,
        name: str,
        num_shards: int,
        process: Callable[[Any], None],
        on_error: Optional[Callable[[Any, BaseException], None]] = None,
        scheduler: str = "wpq",
    ) -> None:
        self.name = name
        self.process = process
        self.on_error = on_error
        self.scheduler = scheduler
        if scheduler == "mclock":
            from ceph_tpu.osd.mclock import MClockQueue

            self._mclock: Optional[List] = [
                MClockQueue() for _ in range(num_shards)
            ]
        else:
            self._mclock = None
        self._shards: List[List[Tuple[int, int, Any]]] = [
            [] for _ in range(num_shards)
        ]
        self._conds = [threading.Condition() for _ in range(num_shards)]
        self._seq = itertools.count()
        self._stop = False
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,), name=f"{name}-{i}", daemon=True
            )
            for i in range(num_shards)
        ]
        self._inflight = 0
        self._drain_cond = threading.Condition()

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def queue(self, token: Hashable, item: Any, priority: int = 63,
              qos_class: Optional[str] = None) -> None:
        """Higher priority dispatches first; same token stays ordered.
        Under the mclock scheduler, `qos_class` (or the priority
        mapping) selects the dmClock reservation/weight/limit class."""
        if self._stop:
            raise RuntimeError(f"work queue {self.name} is stopped")
        shard = hash(token) % len(self._shards)
        with self._drain_cond:
            self._inflight += 1
        with self._conds[shard]:
            if self._mclock is not None:
                self._mclock[shard].enqueue(
                    qos_class or _prio_to_class(priority), item)
            else:
                heapq.heappush(
                    self._shards[shard], (-priority, next(self._seq), item)
                )
            self._conds[shard].notify()

    def _worker(self, i: int) -> None:
        cond = self._conds[i]
        q = self._shards[i]
        mq = self._mclock[i] if self._mclock is not None else None
        while True:
            with cond:
                if mq is not None:
                    cond.wait_for(lambda: len(mq) or self._stop)
                    if self._stop and not len(mq):
                        return
                    _, item = mq.dequeue()
                else:
                    cond.wait_for(lambda: q or self._stop)
                    if self._stop and not q:
                        return
                    _, _, item = heapq.heappop(q)
            try:
                self.process(item)
            except BaseException as e:  # noqa: BLE001 — worker must survive
                if self.on_error:
                    self.on_error(item, e)
            finally:
                with self._drain_cond:
                    self._inflight -= 1
                    if self._inflight == 0:
                        self._drain_cond.notify_all()

    def drain(self, timeout: float | None = None) -> bool:
        with self._drain_cond:
            return self._drain_cond.wait_for(
                lambda: self._inflight == 0, timeout
            )

    def stop(self) -> None:
        self._stop = True
        for c in self._conds:
            with c:
                c.notify_all()
        for t in self._threads:
            t.join(timeout=5)
