"""Leveled subsystem logging with an in-memory crash ring.

Reference roles: `dout` over per-subsystem levels (src/common/dout.h,
src/common/subsys.h), the async flusher and most-recent-events ring
dumped on crash (src/log/Log.cc), and the cluster log channel
(src/common/LogClient.h) which here is the `cluster_cb` hook daemons
point at their mon session.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback
from typing import Callable, Deque, Dict, List, Optional, TextIO, Tuple

SUBSYS = (
    "ms", "mon", "paxos", "osd", "pg", "ec", "crush", "store", "journal",
    "client", "objecter", "bench", "admin", "heartbeat", "tpu", "rbd",
    "compressor", "scrub", "recovery", "test",
)

# ring entry shape: (unix_ts, context_name, subsys, level, message)


class Log:
    """Per-context logger; gather() gives a `dout`-style callable."""

    def __init__(
        self,
        default_level: int = 1,
        ring_size: int = 10000,
        stream: Optional[TextIO] = None,
        name: str = "",
    ) -> None:
        self._levels: Dict[str, int] = {s: default_level for s in SUBSYS}
        self._ring: Deque[Tuple[float, str, str, int, str]] = (
            collections.deque(maxlen=ring_size)
        )
        # ring always records up to this level even when not emitted,
        # mirroring the reference's gather_level > log_level crash ring
        self._gather_level = 20
        self._lock = threading.Lock()
        self._stream = stream if stream is not None else sys.stderr
        self.name = name
        self.cluster_cb: Optional[Callable[[str, str], None]] = None

    def set_level(self, subsys: str, level: int) -> None:
        self._levels[subsys] = level

    def would_emit(self, subsys: str, level: int) -> bool:
        return level <= self._levels.get(subsys, 1)

    def log(self, subsys: str, level: int, msg: str) -> None:
        now = time.time()
        with self._lock:
            if level <= self._gather_level:
                self._ring.append((now, self.name, subsys, level, msg))
            if level <= self._levels.get(subsys, 1):
                ts = time.strftime("%H:%M:%S", time.localtime(now))
                print(
                    f"{ts}.{int(now * 1000) % 1000:03d} {self.name} "
                    f"{level:2d} {subsys}: {msg}",
                    file=self._stream,
                )

    def dout(self, subsys: str) -> Callable[[int, str], None]:
        def emit(level: int, msg: str) -> None:
            self.log(subsys, level, msg)

        return emit

    def cluster(self, level: str, msg: str) -> None:
        """Cluster-log channel (INF/WRN/ERR) routed to the mon when wired."""
        self.log("mon", 0, f"cluster [{level}] {msg}")
        if self.cluster_cb:
            self.cluster_cb(level, msg)

    def dump_recent(self, n: int = 1000) -> List[str]:
        with self._lock:
            items = list(self._ring)[-n:]
        return [
            f"{ts:.6f} {name} {lvl:2d} {sub}: {msg}"
            for ts, name, sub, lvl, msg in items
        ]

    def dump_on_crash(self, exc: BaseException) -> str:
        lines = ["--- begin crash dump ---"]
        lines += traceback.format_exception(type(exc), exc, exc.__traceback__)
        lines += ["--- recent events ---"]
        lines += self.dump_recent()
        lines += ["--- end crash dump ---"]
        text = "\n".join(lines)
        print(text, file=self._stream)
        return text
