"""lockdep — runtime lock-order cycle detection.

Reference role: src/common/lockdep.cc + mutex_debug.h: every named
mutex acquisition records "held -> acquiring" order edges in a global
graph; an acquisition that would close a cycle (lock A held while
taking B, elsewhere B held while taking A) raises immediately with
both chains — deadlocks become deterministic test failures instead of
rare production hangs.

Zero-cost when disabled: `make_lock(name)` hands back a plain RLock
unless lockdep is enabled (the reference gates on the `lockdep` config
the same way).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

_enabled = False
_graph_lock = threading.Lock()
# edges[a][b]: b was acquired while a was held (a precedes b)
_edges: Dict[str, Set[str]] = {}
_local = threading.local()


class LockOrderError(RuntimeError):
    pass


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _graph_lock:
        _edges.clear()


def _held() -> List[str]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def _path(frm: str, to: str) -> Optional[List[str]]:
    """A recorded order path frm -> ... -> to, or None."""
    seen = {frm}
    stack = [(frm, [frm])]
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == to:
                return path + [to]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def will_lock(name: str) -> None:
    held = _held()
    if not held:
        return
    with _graph_lock:
        for h in held:
            if h == name:
                continue  # re-entrant
            # adding h -> name; a recorded name -> ... -> h closes a cycle
            cycle = _path(name, h)
            if cycle is not None:
                raise LockOrderError(
                    f"lock order violation: acquiring {name!r} while "
                    f"holding {h!r}, but the reverse order "
                    f"{' -> '.join(cycle)} was recorded earlier"
                )
            _edges.setdefault(h, set()).add(name)


def locked(name: str) -> None:
    _held().append(name)


def unlocked(name: str) -> None:
    held = _held()
    if name in held:
        held.reverse()
        held.remove(name)
        held.reverse()


class DMutex:
    """Lock-order-checked re-entrant mutex (reference mutex_debug).

    Re-entrancy is judged against THIS thread's hold depth (a
    thread-local), never a shared counter — a contended acquisition
    (another thread holds the lock) is exactly the case the order
    check exists for."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.RLock()

    def _my_depth(self) -> Dict[int, int]:
        if not hasattr(_local, "depth"):
            _local.depth = {}
        return _local.depth

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        depths = self._my_depth()
        mine = depths.get(id(self), 0)
        if _enabled and mine == 0:
            will_lock(self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            depths[id(self)] = mine + 1
            if _enabled and mine == 0:
                locked(self.name)
        return got

    def release(self) -> None:
        depths = self._my_depth()
        mine = depths.get(id(self), 1) - 1
        if mine <= 0:
            depths.pop(id(self), None)
            if _enabled:
                unlocked(self.name)
        else:
            depths[id(self)] = mine
        self._lock.release()

    def __enter__(self) -> "DMutex":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def make_lock(name: str):
    """A named checked mutex when lockdep is on, a bare RLock when off
    (the zero-overhead production default)."""
    if _enabled:
        return DMutex(name)
    return threading.RLock()
