"""lockdep — runtime lock-order cycle detection.

Reference role: src/common/lockdep.cc + mutex_debug.h: every named
mutex acquisition records "held -> acquiring" order edges in a global
graph; an acquisition that would close a cycle (lock A held while
taking B, elsewhere B held while taking A) raises immediately with
both chains — deadlocks become deterministic test failures instead of
rare production hangs.

Zero-cost when disabled: `make_lock(name)` hands back a plain RLock
unless lockdep is enabled (the reference gates on the `lockdep` config
the same way).
"""

from __future__ import annotations

import json
import os
import threading
import traceback
from typing import Dict, List, Optional, Set

_enabled = False
_graph_lock = threading.Lock()
# edges[a][b]: b was acquired while a was held (a precedes b)
_edges: Dict[str, Set[str]] = {}
# first-seen acquisition site per edge, captured on the cold path
# only (once per distinct edge): "file:line in func" innermost-first
_edge_sites: Dict[tuple, str] = {}
_local = threading.local()


class LockOrderError(RuntimeError):
    pass


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()


def _held() -> List[str]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def _path(frm: str, to: str) -> Optional[List[str]]:
    """A recorded order path frm -> ... -> to, or None."""
    seen = {frm}
    stack = [(frm, [frm])]
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == to:
                return path + [to]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


_EMPTY: frozenset = frozenset()


def will_lock(name: str) -> None:
    held = _held()
    if not held:
        return
    _check_order(held, name)


def _check_order(held: List[str], name: str) -> None:
    """Validate held -> name order edges.  Steady state is LOCK-FREE:
    set membership reads are GIL-atomic and the edge graph only ever
    grows, so a present edge is proof this exact order was already
    validated — the whole tier-1 suite runs with lockdep armed, and a
    global mutex + BFS per acquisition was measurable suite time."""
    g = _edges
    need = None
    for h in held:
        if h != name and name not in g.get(h, _EMPTY):
            if need is None:
                need = [h]
            else:
                need.append(h)
    if need is None:
        return
    with _graph_lock:
        for h in need:
            if name in g.get(h, _EMPTY):
                continue  # another thread validated it meanwhile
            # adding h -> name; a recorded name -> ... -> h closes a cycle
            cycle = _path(name, h)
            if cycle is not None:
                raise LockOrderError(
                    f"lock order violation: acquiring {name!r} while "
                    f"holding {h!r}, but the reverse order "
                    f"{' -> '.join(cycle)} was recorded earlier"
                )
            g.setdefault(h, set()).add(name)
            _edge_sites[(h, name)] = _acquire_site()


def _acquire_site() -> str:
    """The innermost non-lockdep frame of the current acquisition —
    cold path only (runs once per distinct edge)."""
    here = os.path.abspath(__file__)
    for fr in reversed(traceback.extract_stack()):
        if os.path.abspath(fr.filename) != here:
            return f"{fr.filename}:{fr.lineno} in {fr.name}"
    return "<unknown>"


def locked(name: str) -> None:
    _held().append(name)


def unlocked(name: str) -> None:
    held = _held()
    if name in held:
        held.reverse()
        held.remove(name)
        held.reverse()


class DMutex:
    """Lock-order-checked re-entrant mutex (reference mutex_debug).

    Re-entrancy is judged against THIS thread's hold depth (a
    thread-local), never a shared counter — a contended acquisition
    (another thread holds the lock) is exactly the case the order
    check exists for."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.RLock()

    def _my_depth(self) -> Dict[int, int]:
        try:
            return _local.depth
        except AttributeError:
            _local.depth = {}
            return _local.depth

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # hand-flattened hot path: this wrapper runs on every named
        # lock in the system for the whole lockdep-armed test suite
        try:
            depths = _local.depth
        except AttributeError:
            depths = _local.depth = {}
        k = id(self)
        mine = depths.get(k, 0)
        first = mine == 0
        if first and _enabled:
            try:
                held = _local.stack
            except AttributeError:
                held = _local.stack = []
            if held:
                _check_order(held, self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            depths[k] = mine + 1
            if first and _enabled:
                _local.stack.append(self.name)
        return got

    def release(self) -> None:
        try:
            depths = _local.depth
        except AttributeError:
            depths = _local.depth = {}
        k = id(self)
        mine = depths.get(k, 1) - 1
        if mine <= 0:
            depths.pop(k, None)
            if _enabled:
                stack = getattr(_local, "stack", None)
                if stack:
                    name = self.name
                    for i in range(len(stack) - 1, -1, -1):
                        if stack[i] == name:
                            del stack[i]
                            break
        else:
            depths[k] = mine
        self._lock.release()

    # exactly like CPython's C lock objects: __enter__ IS acquire
    # (returns True, not self — nobody binds `with lock as x`), saving
    # a frame per with-block on the hottest wrapper in the suite
    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    # -- threading.Condition protocol -------------------------------------
    # Condition(make_lock(...)) must behave exactly like
    # Condition(RLock()): delegate the save/restore hooks to the inner
    # RLock and keep our depth/held bookkeeping consistent across the
    # wait window.  No order check on re-acquire: the wakeup restores
    # an ordering that was already validated when the lock was first
    # taken.

    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        depths = self._my_depth()
        mine = depths.pop(id(self), 0)
        if _enabled and mine:
            unlocked(self.name)
        return (self._lock._release_save(), mine)

    def _acquire_restore(self, saved) -> None:
        state, mine = saved
        self._lock._acquire_restore(state)
        if mine:
            self._my_depth()[id(self)] = mine
            if _enabled:
                locked(self.name)


def make_lock(name: str):
    """A named checked mutex when lockdep is on, a bare RLock when off
    (the zero-overhead production default)."""
    if _enabled:
        return DMutex(name)
    return threading.RLock()


# -- graph export (PR 18: static/runtime cross-validation) ----------------

def edge_graph() -> Dict[str, Dict[str, str]]:
    """Snapshot of the runtime-observed order graph:
    ``{held: {acquired: first_seen_site}}``.  Each edge carries the
    acquisition site recorded the FIRST time that order was seen —
    when the static model (analysis/checks/lock_cycle.py) is missing
    an edge, the site names the unmodeled call path."""
    with _graph_lock:
        return {a: {b: _edge_sites.get((a, b), "<unknown>")
                    for b in sorted(bs)}
                for a, bs in sorted(_edges.items())}


def dump(path: str) -> None:
    """Write the observed graph as JSON (the CEPH_TPU_LOCKDEP_DUMP
    hook and the vstart cross-check both consume this shape)."""
    payload = {"enabled": _enabled, "edges": edge_graph()}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)


_DUMP_ENV = "CEPH_TPU_LOCKDEP_DUMP"
if os.environ.get(_DUMP_ENV):
    import atexit

    atexit.register(lambda: dump(os.environ[_DUMP_ENV]))

# arm from the environment so a CLI/vstart run can record edges
# without the test conftest (which arms explicitly and still wins):
# CEPH_TPU_LOCKDEP=1 tools/ceph.py --vstart ... dumps a live graph
if os.environ.get("CEPH_TPU_LOCKDEP") == "1":
    enable(True)
