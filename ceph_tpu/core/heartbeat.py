"""Internal thread-liveness map with grace/suicide timeouts.

Reference: HeartbeatMap (src/common/HeartbeatMap.h:54) — worker threads
touch a handle inside their loop; a checker flags handles past their
grace (unhealthy → daemon reports itself) or suicide timeout (reference
aborts; here we raise via callback so tests can assert on it).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class Handle:
    __slots__ = ("name", "grace", "suicide_grace", "last_touch", "suicided")

    def __init__(self, name: str, grace: float, suicide_grace: float) -> None:
        self.name = name
        self.grace = grace
        self.suicide_grace = suicide_grace
        self.last_touch = time.monotonic()
        self.suicided = False

    def touch(self) -> None:
        self.last_touch = time.monotonic()
        self.suicided = False


class HeartbeatMap:
    def __init__(
        self, on_suicide: Optional[Callable[[str], None]] = None
    ) -> None:
        self._lock = threading.Lock()
        self._handles: Dict[str, Handle] = {}
        self.on_suicide = on_suicide

    def add_worker(
        self, name: str, grace: float = 15.0, suicide_grace: float = 150.0
    ) -> Handle:
        h = Handle(name, grace, suicide_grace)
        with self._lock:
            self._handles[name] = h
        return h

    def remove_worker(self, name: str) -> None:
        with self._lock:
            self._handles.pop(name, None)

    def is_healthy(self) -> bool:
        return not self.unhealthy_workers()

    def unhealthy_workers(self) -> List[str]:
        now = time.monotonic()
        bad: List[str] = []
        to_fire: List[str] = []
        with self._lock:
            for h in self._handles.values():
                age = now - h.last_touch
                # latch under the lock: the abort callback fires once per
                # stall even with concurrent health queries (touch()
                # re-arms after recovery); only latch when a callback is
                # installed so one registered later still sees the stall
                if (age > h.suicide_grace and not h.suicided
                        and self.on_suicide is not None):
                    h.suicided = True
                    to_fire.append(h.name)
                if age > h.grace:
                    bad.append(h.name)
        if self.on_suicide:
            for name in to_fire:
                self.on_suicide(name)
        return bad
