"""Context — the per-process service bundle (CephContext equivalent).

Reference: CephContext/g_ceph_context (src/common/ceph_context.h) as
created by global_init (src/global/global_init.h:34): owns the config,
the log, the perf-counter collection, the admin socket, and the
heartbeat map, and hands them to every subsystem.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ceph_tpu.core.admin_socket import AdminSocket
from ceph_tpu.core.config import Config
from ceph_tpu.core.heartbeat import HeartbeatMap
from ceph_tpu.core.log import Log
from ceph_tpu.core.perf import PerfCountersCollection


class Context:
    def __init__(
        self,
        name: str = "client.admin",
        overrides: Optional[Dict[str, Any]] = None,
    ) -> None:
        overrides = dict(overrides or {})
        overrides.setdefault("name", name)
        self.conf = Config(overrides)
        self.name = self.conf.get("name")
        self.log = Log(
            default_level=self.conf.get("log_level"),
            ring_size=self.conf.get("log_ring_size"),
            name=self.name,
        )
        self.perf = PerfCountersCollection()
        self.heartbeat = HeartbeatMap()
        from ceph_tpu.core.tracing import Tracer

        self.trace = Tracer(self.name,
                            enabled=bool(self.conf.get("tracing")))
        self.admin: Optional[AdminSocket] = None
        path = self.conf.get("admin_socket")
        if path:
            self._start_admin(path)
        self.conf.add_observer(
            ("log_level",),
            lambda _n, v: [self.log.set_level(s, v) for s in self.log._levels],
        )

    def _start_admin(self, path: str) -> None:
        a = AdminSocket(path)
        a.register("perf dump", lambda c: self.perf.dump(),
                   "dump perf counters")
        a.register("config get",
                   lambda c: {c["key"]: self.conf.get(c["key"])},
                   "get one config value")
        a.register("config set",
                   lambda c: (self.conf.set_val(c["key"], c["value"]),
                              {"success": True})[1],
                   "set a config value at runtime")
        a.register("config diff", lambda c: self.conf.diff(),
                   "non-default config values")
        a.register("log dump", lambda c: self.log.dump_recent(
            int(c.get("count", 1000))), "recent in-memory log events")
        a.register("health", lambda c: {
            "healthy": self.heartbeat.is_healthy(),
            "unhealthy_workers": self.heartbeat.unhealthy_workers(),
        }, "thread liveness")
        def _dump_trace(c):
            if "trace_id" in c:
                return self.trace.dump(int(str(c["trace_id"]), 16))
            return self.trace.recent(int(c.get("count", 100)))

        a.register("dump_tracing", _dump_trace,
                   "archived trace spans (blkin role)")
        a.register("dump_trace", _dump_trace,
                   "spans of one trace: dump_trace trace_id=<hex> "
                   "(without trace_id: the ring tail)")

        def _device_dump(c):
            # process-wide like the StripeBatchQueue: one device
            # runtime per process, one compile table
            from ceph_tpu.tpu.devwatch import watch

            return watch().dump()

        a.register("device compile dump", _device_dump,
                   "per-kernel-family XLA compile table: compiles, "
                   "wall seconds, distinct shape signatures, cache "
                   "hits, recent storms and events")
        a.start()
        self.admin = a

    def shutdown(self) -> None:
        if self.admin is not None:
            self.admin.stop()
            self.admin = None


def global_init(
    name: str, overrides: Optional[Dict[str, Any]] = None, argv=None
):
    """Config-parse + context construction (global_init equivalent)."""
    ctx = Context(name, overrides)
    rest = ctx.conf.parse_argv(argv) if argv else []
    ctx.conf.startup_done()  # non-runtime options frozen from here on
    return ctx, rest
