"""Byte/op throttles — flow control for messengers, objecter, recovery.

Reference: src/common/Throttle.{h,cc} (blocking `get` against a max,
`get_or_fail`, dynamic resize waking waiters) used by the messenger's
dispatch throttle and the Objecter's in-flight op budget.
"""

from __future__ import annotations

import threading


class Throttle:
    def __init__(self, name: str, maximum: int) -> None:
        self.name = name
        self._max = maximum
        self._current = 0
        self._cond = threading.Condition()

    @property
    def current(self) -> int:
        return self._current

    @property
    def maximum(self) -> int:
        return self._max

    def reset_max(self, maximum: int) -> None:
        with self._cond:
            self._max = maximum
            self._cond.notify_all()

    def _should_wait(self, count: int) -> bool:
        if self._max <= 0:
            return False
        # always let a single oversized request through an empty throttle
        return (
            self._current + count > self._max
            and not (self._current == 0 and count > self._max)
        )

    def get(self, count: int = 1, timeout: float | None = None) -> bool:
        """Block until `count` fits; False on timeout."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._should_wait(count), timeout
            )
            if not ok:
                return False
            self._current += count
            return True

    def get_or_fail(self, count: int = 1) -> bool:
        with self._cond:
            if self._should_wait(count):
                return False
            self._current += count
            return True

    def put(self, count: int = 1) -> None:
        with self._cond:
            self._current -= count
            assert self._current >= 0, f"throttle {self.name} underflow"
            self._cond.notify_all()

    def __repr__(self) -> str:
        return f"Throttle({self.name}, {self._current}/{self._max})"
