"""Distributed trace spans (the blkin/Zipkin + LTTng tracepoint role).

Reference: src/blkin/ (Zipkin-style trace/span/parent ids propagated
with requests, annotations at interesting points) and the LTTng-UST
tracepoints compiled into the daemons (src/tracing/*.tp).  Here:

- `Tracer.start_span(name, parent=...)` opens a span; `span.annotate()`
  adds timestamped events; `span.finish()` archives it in a bounded
  ring.
- Wire propagation is by VALUE, not by magic: `span.context()` returns
  (trace_id, span_id) to embed in a message (the client library puts it
  in the op reqid; any carrier works), and the receiving daemon opens
  its span with `parent=that_context` — the cross-daemon parent/child
  chain of blkin.
- `Tracer.dump(trace_id)` returns the archived spans of one trace,
  `Tracer.recent()` the ring tail — the admin-socket surface.

Tracepoint analog: `Tracer.event(subsys, name, **kw)` records a flat
timestamped event in the same ring when tracing is enabled — the
compiled-in, off-by-default tracepoint shape.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

TraceContext = Tuple[int, int]  # (trace_id, span_id)

# -- stage-name registry -----------------------------------------------------
#
# Every stage event recorded on an op timeline (TrackedOp.mark_event)
# or annotated as a literal stage on a hot-path span must come from
# this table.  The name IS the contract between the instrumented site,
# the per-stage latency histogram it feeds (the osd.N.op `lat_*_us`
# counters — value below; '' = timeline-only), and every dump consumer
# (dump_historic_slow_ops, the mgr merge, cephtop, thrash forensics).
# A typo'd site is a dead timeline row that silently never feeds its
# histogram — cephlint's `span-discipline` check validates literal
# call-site names against this table (never baselineable, the
# failpoint-name-registry shape).
#
# Primary write-pipeline order (each histogram buckets the latency
# since the PREVIOUS timeline event, in microseconds):
#   initiated -> queued_for_pg -> qos_admitted -> reached_pg ->
#   [staged] -> admitted -> submitted -> commit -> [ack_gated]
#   -> commit_sent
STAGES: Dict[str, str] = {
    # client / generic
    "sent": "",                # client: op handed to the messenger
    "initiated": "",           # tracker entry created (messenger receive)
    # daemon dispatch
    "queued_for_pg": "lat_recv_us",      # decode -> sharded-queue entry
    # QoS admission (PR 13): the dmClock (or fifo A/B) scheduler
    # granted this op a workqueue slot — the delta since
    # queued_for_pg is the scheduler wait, the per-tenant fairness
    # number; reached_pg then measures only the dispatch residual
    "qos_admitted": "lat_qos_wait_us",
    "reached_pg": "lat_queue_us",        # queue wait: a shard picked it up
    # write pipeline
    "staged": "lat_staging_us",          # pinned staging-pool acquire
    "admitted": "lat_admission_us",      # _OidPipe admission FIFO grant
    "submitted": "lat_encode_fanout_us",  # exec+encode queued+fan-out sent
    "commit": "lat_commit_wait_us",      # last shard ack arrived
    "ack_gated": "lat_ack_gate_us",      # durable-ack gate released
    "commit_sent": "lat_reply_us",       # reply sent to the client
    # device runtime (PR 10): annotation, not a pipeline stage — the
    # overlap duration feeds lat_compile_wait_us DIRECTLY (an
    # EXTRA_HISTS entry), because the blame is "how long a live XLA
    # compile overlapped this op's encode wait", not a
    # since-previous-event delta
    "compile_wait": "",        # encode batch stalled behind a live compile
    # read path
    "parked": "",              # read parked on recover-on-read
    "read_sent": "lat_read_us",  # terminal for reads: execute -> reply
    #   (reads must NOT conclude as commit_sent — that would feed the
    #   whole read service time into lat_reply_us, which for writes
    #   measures only reply-send time)
    # peer-side span stages (cross-daemon children)
    "sub_write_recv": "",      # peer: MECSubWriteVec dispatched
    "store_commit": "",        # peer: merged store transaction durable
    "sub_read_served": "",     # peer: MECSubReadVec rows answered
    "note_persisted": "",      # peer: commit-note watermark on stable storage
    # terminal events (history admission; see optracker.TERMINAL_STAGES)
    "done": "",
    "eagain": "",              # retryable reply (peering gate, deadline sweep)
    "aborted": "",             # error reply or dispatch exception
    "daemon_shutdown": "",     # daemon went down with the op in flight
    "leaked": "",              # force-finished lifecycle leak (a bug)
}


class Span:
    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "start", "end", "annotations")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 span_id: int, parent_id: int) -> None:
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.time()
        self.end = 0.0
        self.annotations: List[Tuple[float, str]] = []

    def annotate(self, what: str) -> None:
        self.annotations.append((time.time(), what))

    def context(self) -> TraceContext:
        """The wire-propagatable identity of this span."""
        return (self.trace_id, self.span_id)

    def finish(self) -> None:
        if not self.end:
            self.end = time.time()
            self.tracer._archive(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "trace_id": f"{self.trace_id:016x}",
            "span_id": f"{self.span_id:016x}",
            "parent_id": (f"{self.parent_id:016x}"
                          if self.parent_id else None),
            "start": self.start,
            "duration_s": round((self.end or time.time()) - self.start, 6),
            "annotations": [
                {"at": at, "what": w} for at, w in self.annotations],
        }


class Tracer:
    """Per-daemon span recorder; disabled tracers are near-free."""

    def __init__(self, name: str = "", enabled: bool = True,
                 ring_size: int = 2048) -> None:
        self.name = name
        self.enabled = enabled
        self._ring: Deque[Span] = collections.deque(maxlen=ring_size)
        self._lock = threading.Lock()

    # -- spans -------------------------------------------------------------
    def start_span(self, name: str,
                   parent: Optional[TraceContext] = None) -> Span:
        if parent is not None:
            trace_id, parent_id = parent
        else:
            trace_id, parent_id = random.getrandbits(63) | 1, 0
        return Span(self, name, trace_id, random.getrandbits(63) | 1,
                    parent_id)

    def _archive(self, span: Span) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(span)

    # -- tracepoints -------------------------------------------------------
    def event(self, subsys: str, name: str, **kw) -> None:
        """Flat tracepoint (the LTTng .tp role): recorded only when
        enabled, compiled in always."""
        if not self.enabled:
            return
        s = Span(self, f"{subsys}:{name}", 0, 0, 0)
        s.end = s.start
        if kw:
            s.annotations.append((s.start, repr(kw)))
        with self._lock:
            self._ring.append(s)

    # -- query (admin-socket surface) --------------------------------------
    def dump(self, trace_id: int) -> List[Dict]:
        with self._lock:
            spans = [s for s in self._ring if s.trace_id == trace_id]
        return [s.to_dict() for s in sorted(spans, key=lambda s: s.start)]

    def recent(self, n: int = 100) -> List[Dict]:
        with self._lock:
            tail = list(self._ring)[-n:]
        return [s.to_dict() for s in tail]


def trace_id_of(reqid: str) -> int:
    """Deterministic trace id from a request id: every daemon touching
    one client op derives the SAME trace id without any wire change —
    the reqid IS the correlator (the reference's osd_reqid_t threading
    through op tracking)."""
    from ceph_tpu.core.crc import crc32c

    b = reqid.encode()
    return ((crc32c(b) << 32) | crc32c(b, 0xA5A5A5A5)) | 1


_global = Tracer("global")


def tracer() -> Tracer:
    return _global
