"""OpTracker — in-flight op tracking with slow-op and historic dumps.

Reference role: src/common/TrackedOp.h + src/osd/OpRequest.h (the
`ceph daemon <osd> dump_ops_in_flight / dump_historic_ops /
dump_historic_slow_ops` surface): every tracked op records its arrival
and a timeline of state events; completed ops feed a bounded history,
slow ones (>= threshold) a separate ring so stalls leave evidence.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional


class TrackedOp:
    __slots__ = ("tracker", "desc", "start", "events", "done_at")

    def __init__(self, tracker: "OpTracker", desc: str) -> None:
        self.tracker = tracker
        self.desc = desc
        self.start = time.monotonic()
        self.events: List = [(0.0, "initiated")]
        self.done_at: Optional[float] = None

    def mark_event(self, event: str) -> "TrackedOp":
        self.events.append((time.monotonic() - self.start, event))
        return self

    @property
    def age(self) -> float:
        end = self.done_at if self.done_at is not None else time.monotonic()
        return end - self.start

    def finish(self) -> None:
        self.tracker.unregister(self)

    def dump(self) -> Dict[str, Any]:
        return {
            "description": self.desc,
            "age": round(self.age, 6),
            "events": [{"t": round(t, 6), "event": e}
                       for t, e in self.events],
        }

    # context-manager sugar
    def __enter__(self) -> "TrackedOp":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.mark_event(f"aborted: {exc!r}")
        self.finish()


class OpTracker:
    def __init__(self, slow_op_threshold: float = 1.0,
                 history_size: int = 20, slow_history_size: int = 20):
        self.slow_op_threshold = slow_op_threshold
        self._lock = threading.Lock()
        self._in_flight: Dict[int, TrackedOp] = {}
        self._history = collections.deque(maxlen=history_size)
        self._slow = collections.deque(maxlen=slow_history_size)
        self.ops_tracked = 0
        self.slow_ops = 0

    def create_op(self, desc: str) -> TrackedOp:
        op = TrackedOp(self, desc)
        with self._lock:
            self._in_flight[id(op)] = op
            self.ops_tracked += 1
        return op

    def unregister(self, op: TrackedOp) -> None:
        op.done_at = time.monotonic()
        op.events.append((op.done_at - op.start, "done"))
        with self._lock:
            self._in_flight.pop(id(op), None)
            self._history.append(op)
            if op.age >= self.slow_op_threshold:
                self._slow.append(op)
                self.slow_ops += 1

    # -- dumps (admin socket payloads) --------------------------------
    def dump_in_flight(self) -> Dict[str, Any]:
        with self._lock:
            ops = sorted(self._in_flight.values(), key=lambda o: o.start)
            return {"num_ops": len(ops),
                    "ops": [o.dump() for o in ops]}

    def dump_historic(self) -> Dict[str, Any]:
        with self._lock:
            return {"num_ops": len(self._history),
                    "ops": [o.dump() for o in self._history]}

    def dump_slow(self) -> Dict[str, Any]:
        with self._lock:
            return {"threshold": self.slow_op_threshold,
                    "num_ops": len(self._slow),
                    "ops": [o.dump() for o in self._slow]}
