"""OpTracker — in-flight op tracking with slow-op and historic dumps.

Reference role: src/common/TrackedOp.h + src/osd/OpRequest.h (the
`ceph daemon <osd> dump_ops_in_flight / dump_historic_ops /
dump_historic_slow_ops` surface): every tracked op records its arrival
and a timeline of state events; completed ops feed a bounded history,
slow ones (>= threshold) a separate ring so stalls leave evidence.

Stage attribution (PR 8): timeline events use names declared in
``tracing.STAGES``, and each stage whose registry entry names a
histogram ALSO feeds that log2 latency histogram (the daemon's
``osd.N.op`` set) with the microseconds since the PREVIOUS event — so
per-stage p50/p99 is derivable from ``perf dump`` with tracing off.

Lifecycle contract: every tracked op ends with a TERMINAL stage
(``commit_sent`` / ``read_sent`` / ``eagain`` / ``aborted`` /
``daemon_shutdown``) and
lands in history — ops that EAGAIN at the peering gate or are answered
by the write-deadline sweep included.  An op whose terminal stage was
recorded but that never left the in-flight table is a lifecycle LEAK:
``drain()`` (daemon teardown) reports it on the ``LEAKS`` channel,
which the tier-1 conftest asserts empty after every test (the
loop-stall sanitizer shape).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from ceph_tpu.core.lockdep import make_lock
from ceph_tpu.core.tracing import STAGES

# marking one of these concludes the op: unregister skips the implicit
# "done", and a daemon draining a CONCLUDED-but-still-in-flight op
# records a lifecycle leak (its reply went out; nothing can finish it)
TERMINAL_STAGES = frozenset((
    "done", "commit_sent", "read_sent", "eagain", "aborted",
    "daemon_shutdown", "leaked",
))

# lifecycle-leak evidence (tier-1 sanitizer channel, the LOOP_STALLS
# shape): ops whose terminal stage was recorded but that never left
# the in-flight table
LEAKS: List[str] = []

# histograms fed directly by instrumented sites rather than through
# the mark_event flow (declared alongside the stage hists so one
# declare_op_hists() builds the whole osd.N.op set)
EXTRA_HISTS: Dict[str, str] = {
    "lat_fanout_rtt_us": "per-peer sub-write send -> commit ack",
    "lat_recovery_round_us": "one windowed recovery round, send -> settled",
    "lat_parked_read_us": "recover-on-read park -> wake",
    "lat_op_us": "tracked op total: receive -> terminal event",
    "lat_compile_wait_us": "op encode wait overlapped by a live XLA "
                           "compile (devwatch blame)",
}


def declare_op_hists(pc) -> None:
    """Build a daemon's ``osd.N.op`` per-stage histogram set (adds are
    idempotent, like every PerfCounters builder)."""
    for stage, hist in STAGES.items():
        if hist:
            pc.add_histogram(hist, f"stage latency ending at {stage!r} (us)")
    for name, desc in EXTRA_HISTS.items():
        pc.add_histogram(name, desc)


class TrackedOp:
    __slots__ = ("tracker", "desc", "start", "events", "done_at",
                 "trace_ctx", "_last", "concluded", "_mu")

    def __init__(self, tracker: "OpTracker", desc: str,
                 start: Optional[float] = None) -> None:
        self.tracker = tracker
        self.desc = desc
        # start may be the messenger's receive stamp: the first stage
        # delta then covers frame decode + dispatch, not just tracking
        self.start = time.monotonic() if start is None else start
        self.events: List = [(0.0, "initiated", "")]
        self._last = self.start
        self.done_at: Optional[float] = None
        self.concluded = False
        self.trace_ctx = None  # (trace_id, span_id) when the op is traced
        # stages are marked from different threads (submitted on the
        # fan-out lane, commit/ack_gated on store-commit callbacks, the
        # deadline sweep on the osd tick): the per-op lock keeps the
        # timeline ordered and the since-previous-event histogram
        # deltas non-negative, and makes conclusion (terminal event +
        # done_at) atomic against straggler marks
        self._mu = make_lock("optracker.op")

    def mark_event(self, stage: str, detail: str = "",
                   annotation: bool = False) -> "TrackedOp":
        """annotation=True records the event on the timeline WITHOUT
        advancing the since-previous-event baseline: out-of-band
        observations (e.g. compile_wait blame from the device worker)
        must not shift the adjacent pipeline stages' histogram
        deltas."""
        with self._mu:
            return self._mark_locked(stage, detail,
                                     annotation=annotation)

    def _mark_locked(self, stage: str, detail: str = "",
                     annotation: bool = False) -> "TrackedOp":
        if self.done_at is not None:
            # the op already concluded into history (e.g. the deadline
            # sweep answered EAGAIN): a straggler commit firing later
            # must not mutate the dumped timeline or feed a bogus
            # since-the-reply delta into the stage histograms
            return self
        now = time.monotonic()
        self.events.append((now - self.start, stage, detail))
        if annotation:
            return self
        hist = STAGES.get(stage, "")
        perf = self.tracker.perf
        if hist and perf is not None:
            perf.hinc(hist, (now - self._last) * 1e6)
        self._last = now
        if stage in TERMINAL_STAGES:
            self.concluded = True
        return self

    @property
    def age(self) -> float:
        end = self.done_at if self.done_at is not None else time.monotonic()
        return end - self.start

    def finish(self, stage: Optional[str] = None, detail: str = "") -> None:
        self.tracker.unregister(self, stage=stage, detail=detail)

    def dump(self) -> Dict[str, Any]:
        with self._mu:  # in-flight dumps race live marks
            events = list(self.events)
        out = {
            "description": self.desc,
            "age": round(self.age, 6),
            "events": [{"t": round(t, 6),
                        "event": f"{s} {d}" if d else s}
                       for t, s, d in events],
        }
        if self.trace_ctx is not None:
            out["trace_id"] = f"{self.trace_ctx[0]:016x}"
        return out

    # context-manager sugar (finish() is idempotent, so an explicit
    # finish inside the block is fine)
    def __enter__(self) -> "TrackedOp":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and not self.concluded:
            self.finish(stage="aborted", detail=repr(exc))
        else:
            self.finish()


class OpTracker:
    def __init__(self, slow_op_threshold: float = 1.0,
                 history_size: int = 20, slow_history_size: int = 20,
                 perf=None):
        self.slow_op_threshold = slow_op_threshold
        # optional per-stage histogram sink (the daemon's osd.N.op
        # PerfCounters, pre-declared via declare_op_hists)
        self.perf = perf
        self._lock = threading.Lock()
        self._in_flight: Dict[int, TrackedOp] = {}
        self._history = collections.deque(maxlen=history_size)
        self._slow = collections.deque(maxlen=slow_history_size)
        self.ops_tracked = 0
        self.slow_ops = 0
        self.ops_leaked = 0

    def create_op(self, desc: str,
                  start: Optional[float] = None) -> TrackedOp:
        op = TrackedOp(self, desc, start=start)
        with self._lock:
            self._in_flight[id(op)] = op
            self.ops_tracked += 1
        return op

    def unregister(self, op: TrackedOp, stage: Optional[str] = None,
                   detail: str = "") -> None:
        with self._lock:
            if self._in_flight.pop(id(op), None) is None:
                return  # idempotent: second finish (context-manager
                # sugar after an explicit finish, racing reply paths)
        with op._mu:
            # terminal event + done_at land atomically: a straggler
            # mark either precedes the terminal event in the timeline
            # or sees done_at and drops
            if stage is None and not op.concluded:
                stage = "done"
            if stage:
                op._mark_locked(stage, detail)
            op.done_at = time.monotonic()
        if self.perf is not None:
            self.perf.hinc("lat_op_us", (op.done_at - op.start) * 1e6)
        with self._lock:
            self._history.append(op)
            if op.age >= self.slow_op_threshold:
                self._slow.append(op)
                self.slow_ops += 1

    def drain(self, reason: str = "daemon_shutdown") -> None:
        """Daemon teardown: every in-flight op moves to history.  An op
        that CONCLUDED (terminal stage recorded — its reply went out)
        but never unregistered is a lifecycle leak and is reported on
        the LEAKS sanitizer channel; ops genuinely cut down mid-flight
        (a thrash kill landing between submit and commit) are not."""
        with self._lock:
            ops = list(self._in_flight.values())
        for op in ops:
            if op.concluded:
                self.ops_leaked += 1
                LEAKS.append(
                    f"{op.desc}: terminal event "
                    f"{op.events[-1][1]!r} recorded but the op never "
                    f"left the in-flight table")
                self.unregister(op, stage="leaked")
            else:
                self.unregister(op, stage=reason)

    @property
    def num_in_flight(self) -> int:
        with self._lock:
            return len(self._in_flight)

    def slow_depth(self, window_s: float = 30.0) -> int:
        """Live slow-op pressure for the mon's SLOW_OPS health check:
        in-flight ops already past the complaint threshold, plus slow
        ring entries whose completion is younger than ``window_s`` —
        so the check fires while a stall is fresh and CLEARS once the
        ring evidence ages out (the entries stay dumpable; only the
        health signal decays)."""
        now = time.monotonic()
        with self._lock:
            live = sum(1 for op in self._in_flight.values()
                       if op.age >= self.slow_op_threshold)
            recent = sum(1 for op in self._slow
                         if op.done_at is not None
                         and now - op.done_at < window_s)
        return live + recent

    # -- dumps (admin socket payloads) --------------------------------
    def dump_in_flight(self) -> Dict[str, Any]:
        with self._lock:
            ops = sorted(self._in_flight.values(), key=lambda o: o.start)
            return {"num_ops": len(ops),
                    "ops": [o.dump() for o in ops]}

    def dump_historic(self) -> Dict[str, Any]:
        with self._lock:
            return {"num_ops": len(self._history),
                    "ops": [o.dump() for o in self._history]}

    def dump_slow(self) -> Dict[str, Any]:
        with self._lock:
            return {"threshold": self.slow_op_threshold,
                    "num_ops": len(self._slow),
                    "ops": [o.dump() for o in self._slow]}
