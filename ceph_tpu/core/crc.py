"""CRC-32C (Castagnoli) — message footers, store checksums, scrub digests.

Native C++ slicing-by-8 kernel (csrc/crc32c.cc) via ctypes, with a
numpy table fallback.  Reference role: src/common/crc32c.h (messenger
footer crcs, BlueStore csums, ECUtil HashInfo per-shard running crc at
src/osd/ECUtil.h:101-122).
"""

from __future__ import annotations

import ctypes

import numpy as np

_POLY = np.uint32(0x82F63B78)


def _make_table() -> np.ndarray:
    t = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        t = np.where(t & 1, (t >> 1) ^ _POLY, t >> 1)
    return t


_TABLE = _make_table()
_native = None
_native_nogil = None
# hold the GIL for crcs below this size: the kernel runs ~30-60 us
# there, while a ctypes GIL release costs a full reacquisition wait
# (up to the 5 ms switch interval) under load — profiled at ~0.8 ms
# per call on the loaded write path, ~25x the crc itself
_GIL_HOLD_MAX = 256 << 10


def _load_native():
    global _native, _native_nogil
    if _native is None:
        try:
            from ceph_tpu import _native as nat

            L = nat.lib()
            # c_void_p: bytes pass zero-copy (char* at the object's
            # buffer), and any other buffer-protocol object passes as
            # its raw address (resolved by _native_arg without a dup)
            argtypes = [
                ctypes.c_uint32,
                ctypes.c_void_p,
                ctypes.c_int64,
            ]
            fn = L.ceph_tpu_crc32c
            fn.restype = ctypes.c_uint32
            fn.argtypes = argtypes
            _native_nogil = fn
            # GIL-holding binding (PYFUNCTYPE never drops the GIL) for
            # the messenger/store fast path's small-to-medium buffers
            proto = ctypes.PYFUNCTYPE(ctypes.c_uint32, *argtypes)
            _native = proto(("ceph_tpu_crc32c", L))
        except Exception:
            _native = False
            _native_nogil = False
    return _native


def _native_arg(data):
    """(arg, nbytes, keepalive) for the native call, WITHOUT copying:
    bytes ride c_void_p's zero-copy conversion; memoryviews, numpy
    arrays, and other buffer-protocol objects pass their raw buffer
    address (a zero-copy np.frombuffer supplies it — the bufferlist
    discipline: the crc reads the same memory the messenger/store
    holds).  `keepalive` must stay referenced across the call."""
    if isinstance(data, bytes):
        return data, len(data), None
    if isinstance(data, np.ndarray):
        arr = np.ascontiguousarray(data.reshape(-1)).view(np.uint8)
    else:
        try:
            arr = np.frombuffer(data, dtype=np.uint8)
        except (TypeError, ValueError):  # non-contiguous / exotic
            # cephlint: disable=no-d2h-on-hot-path — cold fallback for
            # non-contiguous buffers only; every hot-path caller hands
            # bytes/contiguous views that take the zero-copy branches
            b = bytes(data)
            return b, len(b), None
    return arr.ctypes.data, arr.size, arr


def crc32c(data, crc: int = 0) -> int:
    """Running crc32c; chain by passing the previous value as `crc`.
    Accepts bytes, bytearray, memoryview, numpy arrays — any
    buffer-protocol object — with no intermediate copy on either the
    native or the fallback path."""
    fn = _load_native()
    if fn:
        arg, n, keep = _native_arg(data)
        if n > _GIL_HOLD_MAX:
            # large buffer (scrub/store sweeps): let other threads run
            r = int(_native_nogil(crc, arg, n))
        else:
            r = int(fn(crc, arg, n))
        del keep  # buffer owner held across the call, released here
        return r
    c = np.uint32(crc) ^ np.uint32(0xFFFFFFFF)
    for b in memoryview(data) if not isinstance(data, np.ndarray) \
            else data.reshape(-1):
        c = _TABLE[(c ^ b) & 0xFF] ^ (c >> np.uint32(8))
    return int(c ^ np.uint32(0xFFFFFFFF))
