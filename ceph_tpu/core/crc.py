"""CRC-32C (Castagnoli) — message footers, store checksums, scrub digests.

Native C++ slicing-by-8 kernel (csrc/crc32c.cc) via ctypes, with a
numpy table fallback.  Reference role: src/common/crc32c.h (messenger
footer crcs, BlueStore csums, ECUtil HashInfo per-shard running crc at
src/osd/ECUtil.h:101-122).
"""

from __future__ import annotations

import ctypes

import numpy as np

_POLY = np.uint32(0x82F63B78)


def _make_table() -> np.ndarray:
    t = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        t = np.where(t & 1, (t >> 1) ^ _POLY, t >> 1)
    return t


_TABLE = _make_table()
_native = None


def _load_native():
    global _native
    if _native is None:
        try:
            from ceph_tpu import _native as nat

            L = nat.lib()
            fn = L.ceph_tpu_crc32c
            fn.restype = ctypes.c_uint32
            # c_char_p: immutable bytes pass zero-copy (no buffer dup)
            fn.argtypes = [
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_int64,
            ]
            _native = fn
        except Exception:
            _native = False
    return _native


def crc32c(data: bytes, crc: int = 0) -> int:
    """Running crc32c; chain by passing the previous value as `crc`."""
    fn = _load_native()
    if fn:
        return int(fn(crc, bytes(data), len(data)))
    c = np.uint32(crc) ^ np.uint32(0xFFFFFFFF)
    for b in data:
        c = _TABLE[(c ^ b) & 0xFF] ^ (c >> np.uint32(8))
    return int(c ^ np.uint32(0xFFFFFFFF))
