"""Perf counters: counter / gauge / long-run-avg / histogram.

Reference: PerfCounters (src/common/perf_counters.h:59-99 — u64
counters, gauges, avgcount+sum pairs, power-of-2 histograms) built via
PerfCountersBuilder, registered in a per-context collection, and dumped
over the admin socket (`perf dump`).  Daemons push these to the mgr
(src/mgr/DaemonServer.cc); here the mgr service polls `dump()`.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

TYPE_U64 = "u64"          # monotonically increasing counter
TYPE_GAUGE = "gauge"      # settable level
TYPE_AVG = "avg"          # (count, sum) pair, e.g. latencies
TYPE_HIST = "histogram"   # log2-bucketed values


class _Counter:
    __slots__ = ("name", "type", "desc", "value", "count", "sum", "buckets")

    def __init__(self, name: str, type_: str, desc: str) -> None:
        self.name = name
        self.type = type_
        self.desc = desc
        self.value = 0
        self.count = 0
        self.sum = 0.0
        self.buckets: List[int] = [0] * 64 if type_ == TYPE_HIST else []


class PerfCounters:
    """One subsystem's counter set (e.g. 'osd', 'ec', 'msgr')."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, _Counter] = {}

    # -- builder ----------------------------------------------------------
    # adds are idempotent: two daemons sharing one counter set (an
    # osd's data + heartbeat messengers) must not re-zero live counters
    def add_u64_counter(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_U64, desc))

    def add_u64_gauge(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_GAUGE, desc))

    def add_time_avg(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_AVG, desc))

    def add_histogram(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_HIST, desc))

    # -- updates ----------------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name].value += by

    def dec(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name].value -= by

    def set(self, name: str, v: int) -> None:
        with self._lock:
            self._counters[name].value = v

    def tinc(self, name: str, seconds: float) -> None:
        with self._lock:
            c = self._counters[name]
            c.count += 1
            c.sum += seconds

    def hinc(self, name: str, value: float) -> None:
        with self._lock:
            c = self._counters[name]
            b = 0 if value < 1 else min(63, int(math.log2(value)) + 1)
            c.buckets[b] += 1
            c.count += 1
            c.sum += value

    # -- output -----------------------------------------------------------
    def dump(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        with self._lock:
            for n, c in self._counters.items():
                if c.type in (TYPE_U64, TYPE_GAUGE):
                    out[n] = c.value
                elif c.type == TYPE_AVG:
                    out[n] = {
                        "avgcount": c.count,
                        "sum": c.sum,
                        "avgtime": c.sum / c.count if c.count else 0.0,
                    }
                else:
                    top = max(
                        (i for i, v in enumerate(c.buckets) if v), default=-1
                    )
                    out[n] = {
                        "count": c.count,
                        "sum": c.sum,
                        "buckets": c.buckets[: top + 1],
                    }
        return out


class PerfCountersCollection:
    """All counter sets of one context; admin `perf dump` target."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loggers: Dict[str, PerfCounters] = {}

    def create(self, name: str) -> PerfCounters:
        with self._lock:
            pc = self._loggers.get(name)
            if pc is None:
                pc = self._loggers[name] = PerfCounters(name)
            return pc

    def register(self, name: str, pc: PerfCounters) -> None:
        """Adopt an externally-built counter set (e.g. an ObjectStore's
        own counters) so `perf dump` covers it without the owner
        needing a Context at construction time."""
        with self._lock:
            self._loggers[name] = pc

    def get(self, name: str) -> Optional[PerfCounters]:
        with self._lock:
            return self._loggers.get(name)

    def dump(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            loggers = list(self._loggers.items())
        return {n: pc.dump() for n, pc in loggers}
