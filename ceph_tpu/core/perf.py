"""Perf counters: counter / gauge / long-run-avg / histogram.

Reference: PerfCounters (src/common/perf_counters.h:59-99 — u64
counters, gauges, avgcount+sum pairs, power-of-2 histograms) built via
PerfCountersBuilder, registered in a per-context collection, and dumped
over the admin socket (`perf dump`).  Daemons push these to the mgr
(src/mgr/DaemonServer.cc); here the mgr service polls `dump()`.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Dict, List, Optional

TYPE_U64 = "u64"          # monotonically increasing counter
TYPE_GAUGE = "gauge"      # settable level
TYPE_AVG = "avg"          # (count, sum) pair, e.g. latencies
TYPE_HIST = "histogram"   # log2-bucketed values


class _Counter:
    __slots__ = ("name", "type", "desc", "value", "count", "sum", "buckets")

    def __init__(self, name: str, type_: str, desc: str) -> None:
        self.name = name
        self.type = type_
        self.desc = desc
        self.value = 0
        self.count = 0
        self.sum = 0.0
        self.buckets: List[int] = [0] * 64 if type_ == TYPE_HIST else []


class PerfCounters:
    """One subsystem's counter set (e.g. 'osd', 'ec', 'msgr')."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, _Counter] = {}

    # -- builder ----------------------------------------------------------
    # adds are idempotent: two daemons sharing one counter set (an
    # osd's data + heartbeat messengers) must not re-zero live counters
    def add_u64_counter(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_U64, desc))

    def add_u64_gauge(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_GAUGE, desc))

    def add_time_avg(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_AVG, desc))

    def add_histogram(self, name: str, desc: str = "") -> None:
        self._counters.setdefault(name, _Counter(name, TYPE_HIST, desc))

    # -- updates ----------------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name].value += by

    def dec(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name].value -= by

    def set(self, name: str, v: int) -> None:
        with self._lock:
            self._counters[name].value = v

    def tinc(self, name: str, seconds: float) -> None:
        with self._lock:
            c = self._counters[name]
            c.count += 1
            c.sum += seconds

    def hinc(self, name: str, value: float) -> None:
        with self._lock:
            c = self._counters[name]
            b = 0 if value < 1 else min(63, int(math.log2(value)) + 1)
            c.buckets[b] += 1
            c.count += 1
            c.sum += value

    # -- output -----------------------------------------------------------
    def value(self, name: str, default: int = 0) -> int:
        """One scalar counter/gauge, without serializing the whole set
        (dump() walks every counter incl. histogram bucket lists — too
        heavy for per-tick single-value reads like the stats report's
        heartbeat_misses)."""
        with self._lock:
            c = self._counters.get(name)
            if c is None or c.type not in (TYPE_U64, TYPE_GAUGE):
                return default
            return c.value

    def dump(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        with self._lock:
            for n, c in self._counters.items():
                if c.type in (TYPE_U64, TYPE_GAUGE):
                    out[n] = c.value
                elif c.type == TYPE_AVG:
                    out[n] = {
                        "avgcount": c.count,
                        "sum": c.sum,
                        "avgtime": c.sum / c.count if c.count else 0.0,
                    }
                else:
                    top = max(
                        (i for i, v in enumerate(c.buckets) if v), default=-1
                    )
                    out[n] = {
                        "count": c.count,
                        "sum": c.sum,
                        "buckets": c.buckets[: top + 1],
                    }
        return out


class SnapshotRing:
    """Bounded ring of (stamp, {key: cumulative value}) snapshots with
    windowed rate derivation — the shared primitive behind the
    windowed "per-second" numbers this repo shows (the mon PGMap's
    client IOPS/BW and recovery objects/s, the StripeBatchQueue's
    device-busy fraction).  The mgr ProgressModule's ETA rate is NOT
    ring-derived: it is a cumulative since-event-start average, the
    smoother input its monotone clamp wants.

    Values pushed are CUMULATIVE counters; ``rate()`` differences the
    newest sample against the oldest sample inside the window, so a
    lost intermediate sample costs resolution, never correctness.
    One implementation so the mon digest, the progress ETAs, and the
    bench telemetry aux derive rates identically."""

    def __init__(self, capacity: int = 128) -> None:
        from ceph_tpu.core.lockdep import make_lock

        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = make_lock("perf.snapring")

    def push(self, values: Dict[str, float],
             stamp: Optional[float] = None) -> None:
        if stamp is None:
            stamp = time.monotonic()
        with self._lock:
            self._ring.append((stamp, dict(values)))

    def latest(self, key: str, default: float = 0.0) -> float:
        with self._lock:
            if not self._ring:
                return default
            return float(self._ring[-1][1].get(key, default))

    def _endpoints(self, window_s: float, now: Optional[float]):
        """Window endpoints (t0, v0, t1, v1) shared by rate()/delta();
        None when fewer than two samples span the window (no invented
        numbers) or — with `now` supplied — when the NEWEST sample
        already fell out of the window: a feed that stopped pushing
        (every reporter died) must decay to zero, not serve its last
        value forever."""
        with self._lock:
            samples = list(self._ring)
        if len(samples) < 2:
            return None
        t1, v1 = samples[-1]
        if now is None:
            now = t1
        if now - t1 > window_s:
            return None
        t0, v0 = samples[0]
        for t, v in samples:
            if now - t <= window_s:
                t0, v0 = t, v
                break
        if t1 <= t0:
            return None
        return t0, v0, t1, v1

    def rate(self, key: str, window_s: float = 10.0,
             now: Optional[float] = None) -> float:
        """(newest - oldest-in-window) / elapsed, per second."""
        ep = self._endpoints(window_s, now)
        if ep is None:
            return 0.0
        t0, v0, t1, v1 = ep
        return (float(v1.get(key, 0.0)) - float(v0.get(key, 0.0))) \
            / (t1 - t0)

    def delta(self, key: str, window_s: float = 10.0,
              now: Optional[float] = None) -> float:
        """Windowed increase of a cumulative counter (identical sample
        selection and decay semantics to rate(), minus the time
        division)."""
        ep = self._endpoints(window_s, now)
        if ep is None:
            return 0.0
        _t0, v0, _t1, v1 = ep
        return float(v1.get(key, 0.0)) - float(v0.get(key, 0.0))


def hist_quantile(hist: Dict[str, object], q: float) -> float:
    """Approximate quantile of a dumped TYPE_HIST counter.

    Bucket b of hinc() holds values in [2^(b-1), 2^b) (b=0 holds
    values < 1), so the true quantile is known to within one power of
    two; interpolating linearly inside the winning bucket gives a
    stable point estimate — the same derivation `cephtop`, the mgr
    merge, and the bench latency-attribution aux all use, so p50/p99
    agree everywhere they are shown."""
    count = int(hist.get("count", 0) or 0)
    buckets = list(hist.get("buckets", []) or [])
    if count <= 0 or not buckets:
        return 0.0
    target = max(1.0, q * count)
    acc = 0.0
    for b, n in enumerate(buckets):
        if not n:
            continue
        if acc + n >= target:
            lo = 0.0 if b == 0 else float(1 << (b - 1))
            hi = 1.0 if b == 0 else float(1 << b)
            return lo + (target - acc) / n * (hi - lo)
        acc += n
    return float(1 << (len(buckets) - 1))


def hist_merge(into: Dict[str, object], val: Dict[str, object]) -> None:
    """Accumulate one dumped histogram into a merge accumulator
    ({count, sum, buckets}) — the cluster-wide aggregation primitive
    shared by the mgr poll and cephtop."""
    into["count"] = int(into.get("count", 0)) + int(val.get("count", 0))
    into["sum"] = float(into.get("sum", 0.0)) + float(val.get("sum", 0.0))
    b = into.setdefault("buckets", [])
    for i, n in enumerate(val.get("buckets", []) or []):
        if i < len(b):
            b[i] += n
        else:
            b.append(n)


def merge_stage_hists(payloads) -> Dict[str, Dict[str, object]]:
    """{counter: merged-histogram} over perf-dump payloads — ONE
    ``{subsys: counters}`` payload per PROCESS.  Only the op/queue
    stage sets (``*.op`` / ``*.tpuq``) participate, and a payload's
    ``.tpuq`` sets merge exactly once: every daemon's ``.tpuq`` is a
    view of that process's ONE StripeBatchQueue, while the ``.op``
    sets are genuinely per-daemon.  The single home of the merge rules
    so mgr `ops latency`, cephtop, and the bench attribution aux
    cannot drift apart."""
    merged: Dict[str, Dict[str, object]] = {}
    for dump in payloads:
        tpuq_done = False
        for subsys, counters in sorted(dump.items()):
            is_q = subsys.endswith(".tpuq")
            if not (subsys.endswith(".op") or is_q):
                continue
            if is_q:
                if tpuq_done:
                    continue
                tpuq_done = True
            for cname, val in counters.items():
                if isinstance(val, dict) and "buckets" in val:
                    hist_merge(merged.setdefault(cname, {}), val)
    return merged


def hist_summary(hist: Dict[str, object]) -> Dict[str, object]:
    """The {count, p50_us, p99_us, mean_us} row every latency surface
    renders (mgr `ops latency`, cephtop, the bench attribution aux) —
    ONE implementation so their numbers agree by construction."""
    count = int(hist.get("count", 0) or 0)
    return {
        "count": count,
        "p50_us": round(hist_quantile(hist, 0.50), 1),
        "p99_us": round(hist_quantile(hist, 0.99), 1),
        "mean_us": round(float(hist.get("sum", 0.0)) / count, 1)
        if count else 0.0,
    }


def hist_delta(after: Dict[str, object],
               before: Dict[str, object]) -> Dict[str, object]:
    """after - before of two dumped histograms (bench phase windows)."""
    ab = list(after.get("buckets", []) or [])
    bb = list(before.get("buckets", []) or [])
    bb += [0] * (len(ab) - len(bb))
    return {
        "count": int(after.get("count", 0)) - int(before.get("count", 0)),
        "sum": float(after.get("sum", 0.0)) - float(before.get("sum", 0.0)),
        "buckets": [a - b for a, b in zip(ab, bb)],
    }


class PerfCountersCollection:
    """All counter sets of one context; admin `perf dump` target."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loggers: Dict[str, PerfCounters] = {}

    def create(self, name: str) -> PerfCounters:
        with self._lock:
            pc = self._loggers.get(name)
            if pc is None:
                pc = self._loggers[name] = PerfCounters(name)
            return pc

    def register(self, name: str, pc: PerfCounters) -> None:
        """Adopt an externally-built counter set (e.g. an ObjectStore's
        own counters) so `perf dump` covers it without the owner
        needing a Context at construction time."""
        with self._lock:
            self._loggers[name] = pc

    def get(self, name: str) -> Optional[PerfCounters]:
        with self._lock:
            return self._loggers.get(name)

    def dump(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            loggers = list(self._loggers.items())
        return {n: pc.dump() for n, pc in loggers}
