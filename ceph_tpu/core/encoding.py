"""Versioned binary encoding — the wire/disk format substrate.

Mirrors the reference's encoding strategy (reference:
src/include/encoding.h — ENCODE_START/ENCODE_FINISH write
`[version u8][compat u8][length u32]` framing so decoders can skip
unknown trailing fields of newer encodings; DECODE_START enforces
compat). Everything that crosses a process or device boundary —
messages, ObjectStore transactions, maps, pg log entries — encodes
through this module, and the dencoder tool (tools/dencoder.py) checks
decode(encode(x)) == x over a pinned corpus the way
src/tools/ceph-dencoder/ does against ceph-object-corpus.

All integers are little-endian fixed-width (the reference's choice for
x86-friendly zero-swap decoding).
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple


class DecodeError(Exception):
    pass


class Encoder:
    """Append-only byte sink with ceph-style struct framing."""

    __slots__ = ("buf", "_frames")

    def __init__(self) -> None:
        self.buf = bytearray()
        self._frames: List[int] = []

    # -- primitives -------------------------------------------------------
    def u8(self, v: int) -> "Encoder":
        self.buf.append(v & 0xFF)
        return self

    def u16(self, v: int) -> "Encoder":
        self.buf += struct.pack("<H", v & 0xFFFF)
        return self

    def u32(self, v: int) -> "Encoder":
        self.buf += struct.pack("<I", v & 0xFFFFFFFF)
        return self

    def u64(self, v: int) -> "Encoder":
        self.buf += struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF)
        return self

    def s32(self, v: int) -> "Encoder":
        self.buf += struct.pack("<i", v)
        return self

    def s64(self, v: int) -> "Encoder":
        self.buf += struct.pack("<q", v)
        return self

    def f64(self, v: float) -> "Encoder":
        self.buf += struct.pack("<d", v)
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def blob(self, v) -> "Encoder":
        """u32-length-prefixed byte string (reference bufferlist
        encode).  Accepts any bytes-like object zero-copy — and a
        DeviceBuf payload handle, materialized through its sanctioned
        (accounted) wire view."""
        if hasattr(v, "wire_view"):  # DeviceBuf duck-type
            v = v.wire_view()
        self.u32(len(v))
        self.buf += v
        return self

    def string(self, v: str) -> "Encoder":
        return self.blob(v.encode("utf-8"))

    def raw(self, v: bytes) -> "Encoder":
        self.buf += v
        return self

    # -- containers -------------------------------------------------------
    def seq(self, items: Iterable[Any], enc_item: Callable[["Encoder", Any], Any]) -> "Encoder":
        items = list(items)
        self.u32(len(items))
        for it in items:
            enc_item(self, it)
        return self

    def mapping(
        self,
        d: Dict[Any, Any],
        enc_k: Callable[["Encoder", Any], Any],
        enc_v: Callable[["Encoder", Any], Any],
    ) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):
            enc_k(self, k)
            enc_v(self, d[k])
        return self

    def optional(self, v: Any, enc_v: Callable[["Encoder", Any], Any]) -> "Encoder":
        if v is None:
            return self.boolean(False)
        self.boolean(True)
        enc_v(self, v)
        return self

    # -- versioned struct framing -----------------------------------------
    def start(self, version: int, compat: int) -> "Encoder":
        """ENCODE_START: [version][compat][u32 len placeholder]."""
        self.u8(version).u8(compat)
        self._frames.append(len(self.buf))
        self.u32(0)
        return self

    def finish(self) -> "Encoder":
        """ENCODE_FINISH: backpatch the payload length."""
        at = self._frames.pop()
        struct.pack_into("<I", self.buf, at, len(self.buf) - at - 4)
        return self

    def bytes(self) -> bytes:
        assert not self._frames, "unbalanced start/finish"
        return bytes(self.buf)


class Decoder:
    """Cursor over an encoded buffer with framing-aware skip."""

    __slots__ = ("buf", "off", "_ends")

    def __init__(self, buf: bytes, off: int = 0) -> None:
        self.buf = buf
        self.off = off
        self._ends: List[int] = []

    def _need(self, n: int) -> None:
        if self.off + n > len(self.buf):
            raise DecodeError(
                f"buffer underrun: need {n} at {self.off}/{len(self.buf)}"
            )

    # -- primitives -------------------------------------------------------
    def u8(self) -> int:
        self._need(1)
        v = self.buf[self.off]
        self.off += 1
        return v

    def _unpack(self, fmt: str, n: int):
        self._need(n)
        v = struct.unpack_from(fmt, self.buf, self.off)[0]
        self.off += n
        return v

    def u16(self) -> int:
        return self._unpack("<H", 2)

    def u32(self) -> int:
        return self._unpack("<I", 4)

    def u64(self) -> int:
        return self._unpack("<Q", 8)

    def s32(self) -> int:
        return self._unpack("<i", 4)

    def s64(self) -> int:
        return self._unpack("<q", 8)

    def f64(self) -> float:
        return self._unpack("<d", 8)

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        n = self.u32()
        self._need(n)
        v = self.buf[self.off : self.off + n]
        self.off += n
        return bytes(v)

    def blob_view(self) -> memoryview:
        """Zero-copy blob: a memoryview into the frame buffer instead
        of a materialized bytes copy — the bufferlist discipline for
        large payload fields (a 64 KiB write body decoded with blob()
        pays a full copy before the op path even sees it).  The view
        pins the whole frame buffer; callers that retain it long-term
        (staging pools) copy out of it exactly once."""
        n = self.u32()
        self._need(n)
        v = memoryview(self.buf)[self.off : self.off + n]
        self.off += n
        return v

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def raw(self, n: int) -> bytes:
        self._need(n)
        v = self.buf[self.off : self.off + n]
        self.off += n
        return bytes(v)

    # -- containers -------------------------------------------------------
    def seq(self, dec_item: Callable[["Decoder"], Any]) -> List[Any]:
        return [dec_item(self) for _ in range(self.u32())]

    def mapping(
        self, dec_k: Callable[["Decoder"], Any], dec_v: Callable[["Decoder"], Any]
    ) -> Dict[Any, Any]:
        n = self.u32()
        out = {}
        for _ in range(n):
            k = dec_k(self)
            out[k] = dec_v(self)
        return out

    def optional(self, dec_v: Callable[["Decoder"], Any]) -> Optional[Any]:
        return dec_v(self) if self.boolean() else None

    # -- versioned struct framing -----------------------------------------
    def start(self, compat_supported: int) -> int:
        """DECODE_START: returns struct version; raises if we're too old."""
        v = self.u8()
        compat = self.u8()
        length = self.u32()
        if compat > compat_supported:
            raise DecodeError(
                f"struct compat {compat} > supported {compat_supported}"
            )
        self._ends.append(self.off + length)
        return v

    def end(self) -> None:
        """DECODE_FINISH: skip unknown trailing fields of newer versions."""
        end = self._ends.pop()
        if self.off > end:
            raise DecodeError("overran struct frame")
        self.off = end

    def remaining_in_frame(self) -> int:
        return self._ends[-1] - self.off if self._ends else len(self.buf) - self.off


# ---------------------------------------------------------------------------
# dencoder registry (reference: src/tools/ceph-dencoder/ strategy)
# ---------------------------------------------------------------------------

DENC_REGISTRY: Dict[str, type] = {}


def denc(cls: type) -> type:
    """Class decorator: register an encodable type for the dencoder tool.

    The class must provide `encode(self, enc)` and classmethod
    `decode(cls, dec)`, plus `example()` producing a representative
    instance for corpus generation.
    """
    DENC_REGISTRY[cls.__name__] = cls
    return cls


def encode_obj(obj: Any) -> bytes:
    e = Encoder()
    obj.encode(e)
    return e.bytes()


def decode_obj(cls: type, data: bytes) -> Any:
    return cls.decode(Decoder(data))
