"""AsyncReserver — bounded-concurrency reservations for recovery.

Reference role: src/common/AsyncReserver.h (recovery/backfill slots are
reserved before any data moves; the reservation count throttles how
many recoveries run at once per OSD).  This is the synchronous
equivalent for the threaded runtime: reserve() blocks until a slot
frees (or times out), release() hands the slot to the next waiter;
`in_use`/`high_water` expose the throttle to tests and perf counters.
"""

from __future__ import annotations

import threading


class AsyncReserver:
    def __init__(self, max_allowed: int) -> None:
        self.max_allowed = max(1, int(max_allowed))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.in_use = 0
        self.high_water = 0  # max concurrent grants ever observed

    def reserve(self, timeout: float = 30.0) -> bool:
        deadline = (threading.TIMEOUT_MAX if timeout is None
                    else timeout)
        with self._cv:
            ok = self._cv.wait_for(
                lambda: self.in_use < self.max_allowed, timeout=deadline)
            if not ok:
                return False
            self.in_use += 1
            self.high_water = max(self.high_water, self.in_use)
            return True

    def release(self) -> None:
        with self._cv:
            if self.in_use > 0:
                self.in_use -= 1
            self._cv.notify()

    def __enter__(self) -> "AsyncReserver":
        if not self.reserve():
            raise TimeoutError("recovery reservation timed out")
        return self

    def __exit__(self, *exc) -> None:
        self.release()
