"""Small thread-safe bounded LRU (reference SharedLRU role).

One implementation for the caches that need capacity-bounded
most-recently-used retention (PG object contexts, and any future
cache); generation tagging lets racing async fills be refused after a
wholesale invalidation (an insert carrying a stale generation is
dropped instead of poisoning the cache).
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, Iterator, Optional, Tuple, TypeVar

V = TypeVar("V")


class LRUCache:
    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._d: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()
        self._gen = 0

    def generation(self) -> int:
        with self._lock:
            return self._gen

    def get(self, key, copy: Optional[Callable[[V], V]] = None):
        """Returns a hit (optionally deep-copied INSIDE the lock so the
        caller can use it lock-free) or None."""
        with self._lock:
            got = self._d.get(key)
            if got is None:
                return None
            self._d.move_to_end(key)
            return copy(got) if copy is not None else got

    def put(self, key, value, gen: Optional[int] = None) -> bool:
        """Insert; refused (False) when `gen` is stale — an async fill
        racing a wholesale invalidation must not reinsert old state."""
        with self._lock:
            if gen is not None and gen != self._gen:
                return False
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)
            return True

    def pop(self, key) -> None:
        with self._lock:
            self._d.pop(key, None)
            self._gen += 1  # in-flight fills for ANY key are now suspect

    def clear(self) -> None:
        with self._lock:
            self._d.clear()
            self._gen += 1

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)
