"""failpoint — deterministic fault/sync injection at named hazard points.

The reference grew `ceph_abort`/failpoint-style debug-inject hooks
(`filestore_debug_inject_read_err`, `osd_debug_inject_failure_on_*`,
the common/fault_injector.h FaultInjector) exactly where distributed
races live: commit-ack delivery, peering arbitration, recovery landing,
journal sync.  This module is that facility for the whole stack: a
process-wide registry of **named points** that are a dict-miss/None
check when disarmed and a schedulable action when armed — so a thrash
race observed once under load becomes a barrier schedule that replays
on a quiet box in milliseconds.

Usage at an instrumented site::

    from ceph_tpu.core import failpoint as fp
    fp.failpoint("pg.rollback.entry", oid=en.oid)          # plain hook
    if fp.enabled("msg.frame.deliver"):                    # hot path:
        if fp.failpoint("msg.frame.deliver",               # no kwargs
                        mtype=type(msg).__name__) is fp.DROP:   # built
            return                                         # disarmed

Sites that honor the ``DROP`` verdict model *message/record loss* (the
operation silently does not happen); the two ``store.corrupt_*`` sites
honor ``CORRUPT`` (the store serves seeded bit-flipped bytes — silent
at-rest corruption); all other actions are effects the point
raises/blocks on directly.

Arming::

    fp.arm("store.commit_batch.sync", fp.sleep_ms(50), prob=0.1)
    fp.arm("pg.commit_note.persist", fp.DROP_ACTION, count=1,
           match={"osd": "2"})
    fp.arm("pg.commit_note.broadcast", fp.barrier("hold-note"))

or declaratively (env ``CEPH_TPU_FAILPOINTS`` / conf
``failpoint_inject``), comma-separated::

    name=action[:modifier[:modifier...]]
    actions:    sleep(ms) | error[(ExcName)] | kill | drop |
                corrupt | barrier(token)
    modifiers:  once | count(n) | prob(p) | match(key=substr)

``prob`` draws from a per-point RNG seeded by ``(seed(), name)``, so a
thrash seed fully determines which points fire at which hit counts —
the seeded deterministic scheduler.  ``barrier(token)`` parks the
hitting thread until the test script calls :func:`release` (or
:func:`abort`); :func:`wait_hit` lets the script rendezvous with the
parked thread first.  Every armed name must exist in :data:`POINTS` —
the same table the ``failpoint-name-registry`` cephlint check holds
call sites to, so a typo is impossible to arm and impossible to ship.
"""

from __future__ import annotations

import os
import random
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ceph_tpu.core.lockdep import make_lock

# ---------------------------------------------------------------------------
# Declaration table — the single source of truth for point names.
# Instrumented call sites (enforced by cephlint failpoint-name-registry)
# and arming both validate against it.
# ---------------------------------------------------------------------------

POINTS: Dict[str, str] = {
    # -- commit-ack delivery & committed_to watermark (osd/pg.py, backend)
    "pg.commit.client_reply":
        "primary, before an acked write's client reply is fired",
    "pg.commit_note.broadcast":
        "primary, before the eager committed_to note broadcast "
        "(degraded-commit durable-ack gate)",
    "pg.commit_note.persist":
        "shard, before merging+persisting a received commit note "
        "(DROP models the in-flight note dying with the primary)",
    "pg.commit_note.ack":
        "shard, before answering a gated commit note (DROP models a "
        "lost ack frame)",
    "backend.subwrite.fanout":
        "primary, before each peer's sub-write(vec) send "
        "(DROP models a sub-write lost to a kill boundary)",
    "backend.commit.ack":
        "primary, as a peer's commit ack is accounted",
    # -- divergent-head arbitration & rewind (osd/pg.py, osd/pglog.py)
    "pg.resolve_divergent":
        "primary, before divergent-head arbitration picks an "
        "authoritative version",
    "pg.rollback.entry":
        "any member, before one divergent entry's rollback record "
        "is applied",
    "pglog.rewind":
        "inside PGLog.rewind_to once divergent entries are dropped",
    # -- recovery landing (osd/recovery.py)
    "recovery.store_recovered":
        "primary, before a rebuilt object's shard txn (with its _av "
        "stamp) is queued",
    # -- staging / device batch (tpu/staging.py, tpu/queue.py)
    "staging.seal":
        "write fan-out, before a staged payload's slot is sealed back "
        "to the pool",
    "queue.batch.dispatch":
        "stripe-batch queue, before a coalesced device batch dispatch",
    # -- messenger & store (msg/messenger.py, store/*.py)
    "msg.frame.deliver":
        "messenger, before a decoded frame reaches dispatch (DROP "
        "models in-flight frame loss at a kill boundary)",
    "store.commit_batch.sync":
        "commit pipeline, between batch swap and the batched sync "
        "(the WAL-appended-nothing-synced kill window)",
    "store.filestore.read":
        "FileStore.read entry (error(EIO) is the "
        "filestore_debug_inject_read_err hook)",
    # -- silent corruption (every store's read boundary, objectstore.py)
    "store.corrupt_chunk":
        "any store's read() return — CORRUPT verdict bit-flips the "
        "served bytes (seeded silent at-rest corruption; scope with "
        "match(oid=/coll=/shard=) so only the targeted shards rot)",
    "store.corrupt_xattr":
        "any store's getattr() return — CORRUPT verdict bit-flips the "
        "served attr value (silent metadata corruption)",
    # -- scrub engine (osd/scrub.py)
    "scrub.chunk":
        "scrub engine, before each deep-scrub chunk is verified (the "
        "kill/preempt/resume seam: a barrier here parks the scrub "
        "with its cursor persisted)",
}

DROP = object()          # verdict: the call site skips the operation
DROP_ACTION = "drop"     # arm(name, DROP_ACTION) => hits return DROP
# verdict: the call site serves CORRUPTED bytes — only the two
# store.corrupt_* points honor it, via corrupt_bytes() below
CORRUPT = object()
CORRUPT_ACTION = "corrupt"


def corrupt_bytes(data, key: str) -> bytes:
    """Deterministic seeded bit-flips for the CORRUPT verdict: flip
    positions come from (seed(), key) — one bit per 512 bytes, at
    least one — so a chaos seed fully determines WHERE the rot lands
    and a replay reproduces the same damage byte for byte."""
    if not data:
        return bytes(data)
    rng = random.Random(f"{_seed}:corrupt:{key}")
    buf = bytearray(data)
    for _ in range(max(1, len(buf) // 512)):
        i = rng.randrange(len(buf))
        buf[i] ^= 1 << rng.randrange(8)
    return bytes(buf)


class FailpointError(RuntimeError):
    """Default exception for error-action points."""


class KilledAtFailpoint(BaseException):
    """Raised by the `kill` action with no kill hook installed; derives
    from BaseException so ordinary `except Exception` recovery code
    cannot swallow a simulated death."""


class FailpointAborted(RuntimeError):
    """Raised in threads parked at a barrier when the schedule aborts
    the token instead of releasing it."""


_ERRORS = {
    "FailpointError": FailpointError,
    "OSError": OSError,
    "IOError": OSError,
    "EIO": None,  # resolved lazily to StoreError (import cycle)
    "RuntimeError": RuntimeError,
    "ConnectionResetError": ConnectionResetError,
    "TimeoutError": TimeoutError,
}


def _resolve_error(name: str):
    if name == "EIO":
        from ceph_tpu.store.objectstore import StoreError

        return StoreError
    exc = _ERRORS.get(name)
    if exc is None:
        raise ValueError(f"failpoint: unknown error class {name!r}")
    return exc


# ---------------------------------------------------------------------------
# Barriers — the no-sleep deterministic scheduler primitive
# ---------------------------------------------------------------------------


class _Barrier:
    def __init__(self, token: str) -> None:
        self.token = token
        self.cond = threading.Condition(make_lock(f"failpoint.barrier.{token}"))
        self.arrived = 0       # total threads that ever hit
        self.waiting = 0       # threads currently parked
        self.released = False
        self.aborted = False

    def park(self) -> None:
        with self.cond:
            self.arrived += 1
            self.waiting += 1
            self.cond.notify_all()  # wake wait_hit observers
            try:
                while not (self.released or self.aborted):
                    self.cond.wait(0.05)
            finally:
                self.waiting -= 1
                self.cond.notify_all()
            if self.aborted:
                raise FailpointAborted(self.token)


_barrier_lock = make_lock("failpoint.barriers")
_barriers: Dict[str, _Barrier] = {}


def _barrier_of(token: str) -> _Barrier:
    with _barrier_lock:
        b = _barriers.get(token)
        if b is None:
            b = _barriers[token] = _Barrier(token)
        return b


def wait_hit(token: str, timeout: float = 10.0, n: int = 1) -> bool:
    """Block until at least `n` threads have ARRIVED at barrier
    `token` (parked or already through); the test-script half of a
    rendezvous.  Returns False on timeout."""
    b = _barrier_of(token)
    deadline = time.monotonic() + timeout
    with b.cond:
        while b.arrived < n:
            left = deadline - time.monotonic()
            if left <= 0:
                return False
            b.cond.wait(min(left, 0.05))
    return True


def release(token: str) -> None:
    """Open barrier `token` permanently: parked threads resume, later
    hits pass straight through."""
    b = _barrier_of(token)
    with b.cond:
        b.released = True
        b.cond.notify_all()


def abort(token: str) -> None:
    """Raise FailpointAborted in every thread parked at `token` (and
    any later arrival) — models the parked operation dying."""
    b = _barrier_of(token)
    with b.cond:
        b.aborted = True
        b.cond.notify_all()


# ---------------------------------------------------------------------------
# Actions (arm() accepts these, a callable, or a DSL string)
# ---------------------------------------------------------------------------


def sleep_ms(ms: float) -> Callable[[dict], None]:
    def act(_ctx: dict) -> None:
        time.sleep(ms / 1000.0)

    act.__name__ = f"sleep({ms})"
    return act


def error(exc=FailpointError) -> Callable[[dict], None]:
    def act(ctx: dict) -> None:
        if isinstance(exc, BaseException):
            raise exc
        raise exc(f"injected at failpoint ({ctx})")

    act.__name__ = "error"
    return act


def barrier(token: str) -> Callable[[dict], None]:
    def act(_ctx: dict) -> None:
        _barrier_of(token).park()

    act.__name__ = f"barrier({token})"
    return act


_kill_hook: Optional[Callable[[str, dict], None]] = None


def set_kill_hook(fn: Optional[Callable[[str, dict], None]]) -> None:
    """Install the process's `kill` action (a MiniCluster harness kills
    the hitting daemon); None restores the default, which raises
    KilledAtFailpoint through the hitting thread."""
    global _kill_hook
    _kill_hook = fn


def kill() -> Callable[[dict], None]:
    def act(ctx: dict) -> None:
        hook = _kill_hook
        if hook is not None:
            hook(ctx.get("_name", "?"), ctx)
            return
        raise KilledAtFailpoint(ctx.get("_name", "?"))

    act.__name__ = "kill"
    return act


# ---------------------------------------------------------------------------
# The registry core
# ---------------------------------------------------------------------------

_seed = 0


class _Point:
    __slots__ = ("name", "action", "count", "prob", "match", "rng",
                 "hits", "fired", "lock")

    def __init__(self, name: str, action, count: Optional[int],
                 prob: Optional[float],
                 match: Optional[Dict[str, str]]) -> None:
        self.name = name
        self.action = action
        self.count = count          # fire at most n times, then disarm
        self.prob = prob
        self.match = match or None
        # per-point deterministic stream: (seed, name) fixes the whole
        # firing pattern independent of arming order
        self.rng = random.Random(f"{_seed}:{name}")
        self.hits = 0
        self.fired = 0
        self.lock = make_lock(f"failpoint.point.{name}")

    def hit(self, ctx: dict):
        with self.lock:
            self.hits += 1
            if self.match:
                for k, want in self.match.items():
                    if want not in str(ctx.get(k, "")):
                        _note_history(self.name, True, False)
                        return None
            if self.prob is not None and self.rng.random() >= self.prob:
                _note_history(self.name, True, False)
                return None
            if self.count is not None and self.fired >= self.count:
                _note_history(self.name, True, False)
                return None
            self.fired += 1
            exhausted = (self.count is not None
                         and self.fired >= self.count)
        _note_history(self.name, True, True)
        if exhausted:
            disarm(self.name, _only_if_is=self)
        if self.action == DROP_ACTION:
            return DROP
        if self.action == CORRUPT_ACTION:
            return CORRUPT
        ctx = dict(ctx)
        ctx["_name"] = self.name
        self.action(ctx)
        return None


_lock = make_lock("failpoint.registry")
# None <=> nothing armed anywhere: failpoint()'s whole disarmed cost is
# this one load + None check (plus the caller's arg packing — hot sites
# guard with enabled() so they pack nothing while disarmed)
_armed: Optional[Dict[str, _Point]] = None
# cumulative (hits, fired) per name, surviving disarm (a count(n)
# point disarms itself after its last firing — observability must not
# vanish with it); reset by disarm_all()
_history: Dict[str, List[int]] = {}


def _note_history(name: str, hit: bool, fired_: bool) -> None:
    with _lock:
        row = _history.setdefault(name, [0, 0])
        if hit:
            row[0] += 1
        if fired_:
            row[1] += 1


def enabled(name: str) -> bool:
    table = _armed
    return table is not None and name in table


def failpoint(name: str, **ctx):
    """The instrumented-site hook: no-op (None) while `name` is
    disarmed; otherwise runs the armed action and returns its verdict
    (DROP, or None after sleep/barrier/raise)."""
    table = _armed
    if table is None:
        return None
    p = table.get(name)
    if p is None:
        return None
    return p.hit(ctx)


def arm(name: str, action, *, once: bool = False,
        count: Optional[int] = None, prob: Optional[float] = None,
        match: Optional[Dict[str, str]] = None) -> None:
    """Arm `name` with `action` (a callable(ctx), DROP_ACTION, or a DSL
    string like "sleep(5)").  Unknown names are an error — the registry
    table is the contract."""
    global _armed
    if name not in POINTS:
        raise KeyError(f"failpoint {name!r} is not declared in "
                       f"failpoint.POINTS")
    if isinstance(action, str) and action not in (DROP_ACTION,
                                                  CORRUPT_ACTION):
        action = _parse_action(action)
    if once:
        count = 1
    p = _Point(name, action, count, prob, match)
    with _lock:
        table = dict(_armed or {})
        table[name] = p
        _armed = table


def disarm(name: str, _only_if_is: Optional[_Point] = None) -> None:
    global _armed
    with _lock:
        if _armed is None:
            return
        if _only_if_is is not None and _armed.get(name) is not _only_if_is:
            return  # re-armed since: the newer arming wins
        table = dict(_armed)
        table.pop(name, None)
        _armed = table or None


def disarm_all() -> None:
    global _armed
    with _lock:
        _armed = None
        _history.clear()  # hits()/fired() promise a reset here
    with _barrier_lock:
        # release any parked threads so tests can't leak wedged daemons
        for b in _barriers.values():
            with b.cond:
                if not b.aborted:
                    b.released = True
                b.cond.notify_all()
        _barriers.clear()


def hits(name: str) -> int:
    """Cumulative times `name` was hit while armed (match-filtered
    hits count; survives the point's self-disarm) — test
    observability.  Reset by disarm_all()."""
    with _lock:
        return _history.get(name, [0, 0])[0]


def fired(name: str) -> int:
    """Cumulative times `name`'s action actually ran (survives
    self-disarm).  Reset by disarm_all()."""
    with _lock:
        return _history.get(name, [0, 0])[1]


def seed(value: int) -> None:
    """Fix the deterministic scheduler seed: every point armed AFTER
    this draws its prob() stream from (value, name), so a thrash seed
    fully determines which points fire."""
    global _seed
    _seed = int(value)


# ---------------------------------------------------------------------------
# DSL parsing (env CEPH_TPU_FAILPOINTS / conf failpoint_inject)
# ---------------------------------------------------------------------------

_ACT_RE = re.compile(r"^(\w+)(?:\(([^)]*)\))?$")


def _parse_action(spec: str):
    mm = _ACT_RE.match(spec.strip())
    if not mm:
        raise ValueError(f"failpoint: bad action {spec!r}")
    kind, arg = mm.group(1), mm.group(2)
    if kind == "sleep":
        return sleep_ms(float(arg))
    if kind == "error":
        return error(_resolve_error(arg) if arg else FailpointError)
    if kind == "kill":
        return kill()
    if kind == "drop":
        return DROP_ACTION
    if kind == "corrupt":
        return CORRUPT_ACTION
    if kind == "barrier":
        if not arg:
            raise ValueError("failpoint: barrier needs a token")
        return barrier(arg)
    raise ValueError(f"failpoint: unknown action {kind!r}")


def arm_from_spec(spec: str) -> List[str]:
    """Parse and arm a DSL spec string (see module docstring); returns
    the armed names.  Empty/blank spec is a no-op."""
    armed: List[str] = []
    for part in filter(None, (s.strip() for s in spec.split(","))):
        if "=" not in part:
            raise ValueError(f"failpoint: bad spec {part!r}")
        name, rhs = part.split("=", 1)
        name = name.strip()
        fields = rhs.split(":")
        action = fields[0]
        kw: Dict[str, Any] = {}
        for mod in fields[1:]:
            mmod = _ACT_RE.match(mod.strip())
            if not mmod:
                raise ValueError(f"failpoint: bad modifier {mod!r}")
            mk, marg = mmod.group(1), mmod.group(2)
            if mk == "once":
                kw["once"] = True
            elif mk == "count":
                kw["count"] = int(marg)
            elif mk == "prob":
                kw["prob"] = float(marg)
            elif mk == "match":
                k, _, v = (marg or "").partition("=")
                kw.setdefault("match", {})[k.strip()] = v.strip()
            else:
                raise ValueError(f"failpoint: unknown modifier {mk!r}")
        act = action.strip()
        arm(name, act if act in (DROP_ACTION, CORRUPT_ACTION)
            else _parse_action(act), **kw)
        armed.append(name)
    return armed


def _arm_from_env() -> None:
    spec = os.environ.get("CEPH_TPU_FAILPOINTS", "")
    sd = os.environ.get("CEPH_TPU_FAILPOINT_SEED", "")
    if sd:
        seed(int(sd, 0))
    if spec:
        arm_from_spec(spec)


_arm_from_env()
