"""Typed config schema + runtime config with observers and hot reload.

Mirrors the reference's option system (reference: src/common/options.cc
— typed schema with levels/defaults/min-max/enum/runtime-updatability —
and md_config_t at src/common/config.h:66 with md_config_obs_t
observers applied via apply_changes).  The monitor's centralized config
service (src/mon/ConfigMonitor.cc) maps to MonService config commands
layered on top of this.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"


@dataclass
class Option:
    name: str
    type: type  # int, float, str, bool
    default: Any
    desc: str = ""
    level: str = LEVEL_ADVANCED
    minval: Optional[float] = None
    maxval: Optional[float] = None
    enum: Optional[Sequence[str]] = None
    runtime: bool = True  # updatable without restart

    def validate(self, value: Any) -> Any:
        if self.type is bool and isinstance(value, str):
            low = value.lower()
            if low in ("true", "yes", "1", "on"):
                value = True
            elif low in ("false", "no", "0", "off"):
                value = False
            else:
                raise ValueError(f"{self.name}: {value!r} is not a boolean")
        try:
            value = self.type(value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"{self.name}: cannot cast {value!r}: {e}")
        if self.minval is not None and value < self.minval:
            raise ValueError(f"{self.name}: {value} < min {self.minval}")
        if self.maxval is not None and value > self.maxval:
            raise ValueError(f"{self.name}: {value} > max {self.maxval}")
        if self.enum is not None and value not in self.enum:
            raise ValueError(f"{self.name}: {value!r} not in {self.enum}")
        return value


def _opts() -> List[Option]:
    O = Option
    return [
        # -- global ---------------------------------------------------------
        O("name", str, "client.admin", "entity name", LEVEL_BASIC, runtime=False),
        O("fsid", str, "", "cluster id", LEVEL_BASIC, runtime=False),
        O("log_level", int, 1, "default log verbosity", LEVEL_BASIC),
        O("log_file", str, "", "log output path ('' = stderr)"),
        O("log_ring_size", int, 10000, "crash-dump ring entries"),
        O("tracing", bool, False, "record blkin-style trace spans"),
        O("admin_socket", str, "", "admin socket path ('' = disabled)"),
        O("heartbeat_interval", float, 5.0, "internal liveness check period"),
        O("failpoint_inject", str, "",
          "arm fault-injection points (core/failpoint.py DSL: "
          "name=action[:modifier...],... — see failpoint.POINTS)"),
        # -- messenger ------------------------------------------------------
        O("ms_bind_ip", str, "127.0.0.1", "listen address", runtime=False),
        O("ms_connect_timeout", float, 10.0, "dial timeout seconds"),
        O("ms_retry_interval", float, 0.2, "session reconnect backoff"),
        O("ms_dispatch_throttle_bytes", int, 100 << 20,
          "max bytes of queued undispatched messages"),
        O("ms_crc_data", bool, True, "checksum message payloads"),
        O("ms_ack_delay", float, 0.005,
          "seconds to hold a dispatch ack hoping it piggybacks on "
          "outgoing data before a dedicated ack frame is sent"),
        O("ms_loop_stall_ms", float, 0.0,
          "loop-stall sanitizer: record a fast-dispatched handler that "
          "holds the messenger event loop longer than this many "
          "milliseconds (0 = off; the test suite arms it via "
          "CEPH_TPU_LOOP_STALL_MS)"),
        # -- monitor --------------------------------------------------------
        O("mon_lease", float, 5.0, "paxos lease seconds"),
        O("mon_tick_interval", float, 1.0, "monitor tick period"),
        O("mon_osd_down_out_interval", float, 600.0,
          "seconds down before auto-out"),
        O("mon_osd_min_down_reporters", int, 2,
          "distinct failure reporters required to mark an osd down"),
        O("mon_osd_adjust_heartbeat_grace", bool, True,
          "scale grace by reporter history"),
        O("mon_pg_stats_stale_s", float, 30.0,
          "seconds after which an OSD's MPGStats report stops feeding "
          "PG health checks; a LIVE osd whose reports go stale past "
          "this raises MON_STALE_PG_REPORTS instead of silently "
          "vanishing from the digest"),
        O("mon_pg_stuck_threshold", float, 300.0,
          "seconds a PG may sit in a non-active state before the "
          "PG_STUCK health check fires (stuck-since stamps come from "
          "the PGMap's state-transition tracking)"),
        O("mon_stats_rate_window", float, 10.0,
          "window (seconds) over which the PGMap digest derives "
          "client IOPS/BW and recovery rates from report deltas"),
        O("mon_warn_not_deep_scrubbed_s", float, 0.0,
          "raise PG_NOT_DEEP_SCRUBBED for primary PGs whose last deep "
          "scrub is older than this many seconds (0 = check disabled; "
          "a PG never deep-scrubbed counts as infinitely old)"),
        O("osd_heartbeat_grace", float, 20.0,
          "seconds without a ping before reporting failure"),
        O("osd_heartbeat_interval", float, 2.0, "osd peer ping period"),
        O("osd_heartbeat_grace_load_stretch", bool, True,
          "stretch the heartbeat grace by the host's load factor "
          "(loadavg per cpu, capped 3x) so a CPU-saturated box does "
          "not mark live-but-starved peers down (ROUND6 bench note)"),
        # -- osd ------------------------------------------------------------
        O("osd_op_num_shards", int, 4, "sharded op queue shards", runtime=False),
        O("osd_op_queue", str, "mclock",
          "op scheduler: mclock (dmClock QoS, default) or fifo "
          "(priority heap; wpq is the legacy spelling)",
          enum=("mclock", "fifo", "wpq"), runtime=False),
        O("osd_qos_profiles", str, "",
          "QoS profile overrides (osd/qos.py DSL): "
          "'<target>=<r>:<w>:<l>;...' where target is a base class "
          "(client, recovery, scrub, snaptrim, ...), tenant:<entity>, "
          "or pool:<id>; runtime-updatable (qos set retunes through "
          "the conf observer)"),
        O("osd_qos_client_rate_window", float, 5.0,
          "window (seconds) over which the QoS scheduler derives the "
          "client-IOPS pressure signal for the recovery feedback "
          "controller"),
        O("osd_recovery_feedback", bool, True,
          "close the recovery-vs-client loop: widen the recovery "
          "window when client IOPS are idle, clamp it under client "
          "pressure (off = the fixed osd_recovery_max_active window)"),
        O("osd_recovery_idle_client_iops", float, 2.0,
          "client ops/s below which clients count as idle and the "
          "recovery window widens"),
        O("osd_recovery_busy_client_iops", float, 50.0,
          "client ops/s at which the recovery window clamps to half"),
        O("osd_recovery_feedback_widen", int, 4,
          "multiplier applied to osd_recovery_max_active while "
          "clients are idle", minval=1),
        O("osd_client_message_cap", int, 256,
          "per-client-connection in-flight op cap at the messenger "
          "(0 = uncapped); an abusive tenant queues at ITS socket, "
          "not in the shared workqueue (reference Throttle role)"),
        O("osd_client_message_size_cap", int, 64 << 20,
          "per-client-connection in-flight payload-byte cap at the "
          "messenger (0 = uncapped)"),
        O("osd_op_complaint_time", float, 30.0,
          "seconds after which an op counts as slow (OpTracker: drives "
          "the dump_historic_slow_ops ring admission; runtime-updatable "
          "so operators can shrink it to catch a live stall)"),
        O("osd_op_history_size", int, 20,
          "completed ops kept for dump_historic_ops", runtime=False),
        O("osd_op_history_slow_size", int, 20,
          "slow ops kept for dump_historic_slow_ops", runtime=False),
        O("osd_slow_op_report_window", float, 30.0,
          "seconds a completed slow op keeps counting toward the "
          "slow-op depth reported to the mon (MPGStats); the SLOW_OPS "
          "health check clears once the ring entries age past this"),
        O("osd_client_write_timeout", float, 30.0,
          "seconds before an in-flight client write whose commit (or "
          "durable-ack gate) never resolves answers retryable EAGAIN"),
        O("osd_max_write_size", int, 90 << 20, "largest single write"),
        O("osd_pool_default_size", int, 3, "replica count"),
        O("osd_pool_default_min_size", int, 0, "0 = size - size/2"),
        O("osd_pool_default_pg_num", int, 32, "pgs per new pool"),
        O("osd_pool_default_erasure_code_profile", str,
          "plugin=isa k=8 m=4 technique=reed_sol_van",
          "default EC profile"),
        O("osd_recovery_max_active", int, 3, "concurrent recovery ops"),
        O("osd_recovery_read_timeout", float, 10.0,
          "seconds to wait for a recovery window's sub-read replies "
          "before the legacy fallback / retryable verdict"),
        O("osd_recovery_chunk_size", int, 8 << 20,
          "bytes per recovery push chunk (resumable progress unit)"),
        O("osd_recovery_push_timeout", float, 30.0,
          "seconds to wait for a recovery push's ack before leaving "
          "the peer stale for this round"),
        O("osd_scrub_interval", float, 86400.0, "seconds between scrubs"),
        O("osd_deep_scrub_interval", float, 604800.0,
          "seconds between DEEP scrubs of one PG: the scheduler runs a "
          "byte-reading deep scrub when a PG's last deep scrub is older "
          "than this (a never-deep-scrubbed PG deep-scrubs first)"),
        O("osd_scrub_chunk_max", int, 16,
          "objects per deep-scrub chunk: the engine verifies (and "
          "persists its resume cursor) one chunk at a time, yielding "
          "to client io between chunks", minval=1),
        O("osd_scrub_auto_repair", bool, False,
          "repair inconsistencies found by deep scrub automatically "
          "(EC consensus rebuild with replace semantics), bounded by "
          "osd_scrub_auto_repair_num_errors"),
        O("osd_scrub_auto_repair_num_errors", int, 5,
          "auto-repair only when deep scrub found at most this many "
          "inconsistent objects (mass damage wants an operator)"),
        O("osd_scrub_busy_client_iops", float, 50.0,
          "client ops/s at which a running deep scrub preempts "
          "between chunks (waits for the pressure to drain)"),
        O("osd_scrub_preempt_max_wait", float, 5.0,
          "longest a preempted deep scrub waits for client pressure "
          "to drain before taking its next chunk anyway"),
        O("osd_pg_stats_interval", float, 2.0,
          "seconds between MPGStats reports to the mon"),
        O("osd_client_op_priority", int, 63, "client op priority"),
        O("osd_recovery_op_priority", int, 3, "recovery op priority"),
        # -- erasure code / device -----------------------------------------
        O("erasure_code_batch_cols", int, 1 << 20,
          "stripe-batch queue target columns per device dispatch"),
        O("erasure_code_tile_n", int, 2048, "pallas column tile"),
        O("tpu_stripe_queue_depth", int, 4, "in-flight device batches"),
        O("tpu_devpath", bool, True,
          "device-resident small-object data path: stage EC WRITEFULL "
          "payloads into the pinned pool, fuse crc32c into the encode "
          "batch, ship DeviceBuf handles end-to-end (off = legacy "
          "host-bytes path)"),
        O("tpu_staging_slots", int, 64,
          "pinned staging pool slots (exhaustion backpressures the "
          "write path)", runtime=False),
        O("tpu_staging_slot_kib", int, 128,
          "pinned staging slot size; larger payloads bypass the pool",
          runtime=False),
        O("tpu_recompile_storm_window", float, 60.0,
          "sliding window (seconds) over which the device watcher "
          "counts distinct compile signatures per kernel family for "
          "recompile-storm detection"),
        O("tpu_recompile_storm_min_sigs", int, 8,
          "distinct compile signatures of ONE kernel family inside "
          "the storm window that raise the RECOMPILE_STORM "
          "cluster-log WARN (naming the family and the churning "
          "shape dimension); default calibrated so a pow2-padded "
          "cold start (~5 bounded shapes/family, ROUND10 measured) "
          "stays quiet while an unpadded dimension trips in seconds"),
        O("tpu_recompile_storm_min_rogue_sigs", int, 3,
          "distinct ROGUE (undeclared by the shape-bucket ABI, "
          "tpu/shapebucket.py) compile signatures of one family "
          "inside the storm window that raise the RECOMPILE_STORM "
          "WARN; much tighter than the total-signature threshold "
          "because a declared cold ladder never counts here — "
          "undeclared shape churn is a bug regardless of volume"),
        O("tpu_compile_cache_dir", str, "",
          "persistent on-disk XLA compilation cache directory "
          "(jax_compilation_cache_dir): a restarted/failed-over "
          "daemon re-reads compiled executables instead of re-paying "
          "the compile wall (osd.N.xla cache_persist_hits counts the "
          "cross-process hits); empty disables (vstart defaults it "
          "under the cluster run dir)", runtime=False),
        O("tpu_warmup_budget_s", float, 30.0,
          "wall-clock budget for the boot-time DeviceWarmup pass "
          "that compiles every registered kernel family against its "
          "declared shape buckets before the daemon answers ops; "
          "buckets the budget cuts off stay pending and resume via "
          "'ceph daemon osd.N device warmup'"),
        O("tpu_boot_warmup", bool, False,
          "run the DeviceWarmup pass at OSD init (before the "
          "messenger serves ops) so restart/failover/backfill never "
          "re-pay the compile wall mid-traffic; off by default so "
          "short-lived test clusters skip it (vstart warmup= knob)",
          runtime=False),
        # -- objectstore ----------------------------------------------------
        O("objectstore", str, "memstore", "backend", enum=("memstore", "filestore")),
        O("objectstore_path", str, "", "data directory for filestore"),
        O("objectstore_wal_sync", bool, False, "fsync the WAL per txn"),
        O("filestore_debug_inject_read_err", bool, False,
          "fault injection: EIO on reads marked bad"),
        O("store_debug_inject_data_err", bool, False,
          "fault injection: reads of objects marked via "
          "debug_inject_data_err serve seeded bit-flipped bytes "
          "(silent corruption, injected BEFORE the read-verify gate — "
          "with store_verify_read on the store catches it at read "
          "time; a rewrite of the object clears its mark)"),
        O("store_csum_extent_kib", int, 64,
          "at-rest checksum granularity: one crc32c seal per this many "
          "KiB of logical object space, sealed in the writing "
          "transaction (BlueStore csum_order analog)"),
        O("store_verify_read", bool, True,
          "verify per-extent at-rest seals on every read; a mismatch "
          "raises instead of serving flipped bytes (off = bench "
          "comparison mode — the corruption seam still applies)"),
        # -- client ---------------------------------------------------------
        O("objecter_timeout", float, 30.0, "op resend timeout"),
        O("objecter_inflight_ops", int, 1024, "op throttle"),
        O("rados_osd_op_timeout", float, 0.0, "0 = no timeout"),
    ]


SCHEMA: Dict[str, Option] = {o.name: o for o in _opts()}


class Config:
    """md_config_t equivalent: values + observers + apply_changes."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None) -> None:
        self._lock = threading.Lock()
        self._started = False  # until startup_done(), non-runtime opts settable
        self._values: Dict[str, Any] = {
            n: o.default for n, o in SCHEMA.items()
        }
        self._observers: List[Tuple[Sequence[str], Callable]] = []
        self._dirty: List[str] = []
        for key, val in os.environ.items():
            if key.startswith("CEPH_TPU_"):
                name = key[len("CEPH_TPU_"):].lower()
                if name in SCHEMA:
                    self._values[name] = SCHEMA[name].validate(val)
        if overrides:
            for k, v in overrides.items():
                self.set_val(k, v, apply=False)
            self._dirty.clear()

    def startup_done(self) -> None:
        """After this, options with runtime=False refuse set_val."""
        self._started = True

    def get(self, name: str) -> Any:
        with self._lock:
            return self._values[name]

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name)

    def set_val(self, name: str, value: Any, apply: bool = True,
                force: bool = False) -> None:
        """force=True bypasses the runtime-updatability guard (startup
        parsing); admin-path callers leave it False so non-runtime
        options reject instead of silently not taking effect."""
        opt = SCHEMA.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        if not opt.runtime and not force and self._started:
            raise ValueError(
                f"{name} is not updatable at runtime (restart required)"
            )
        value = opt.validate(value)
        with self._lock:
            if self._values[name] != value:
                self._values[name] = value
                self._dirty.append(name)
        if apply:
            self.apply_changes()

    def add_observer(
        self, keys: Sequence[str], fn: Callable[[str, Any], None]
    ) -> Callable[[str, Any], None]:
        """fn(name, new_value) fires on apply_changes for watched keys.
        Returns fn as the handle for remove_observer."""
        self._observers.append((tuple(keys), fn))
        return fn

    def remove_observer(self, fn: Callable[[str, Any], None]) -> None:
        """Unhook an observer (by the handle add_observer returned).
        Daemons that die on a shared long-lived Context must remove
        their observers, or every kill/revive cycle pins the dead
        daemon's state for the Context's lifetime."""
        self._observers = [(k, f) for k, f in self._observers
                           if f is not fn]

    def apply_changes(self) -> None:
        with self._lock:
            dirty, self._dirty = self._dirty, []
            values = dict(self._values)
        for name in dirty:
            for keys, fn in self._observers:
                if name in keys:
                    fn(name, values[name])

    def parse_argv(self, argv: Sequence[str]) -> List[str]:
        """Consume --conf-<name>=<v> / --conf-<name> <v>; returns the rest."""
        rest: List[str] = []
        i = 0
        while i < len(argv):
            a = argv[i]
            if a.startswith("--conf-"):
                body = a[len("--conf-"):]
                if "=" in body:
                    name, val = body.split("=", 1)
                else:
                    name = body
                    i += 1
                    if i >= len(argv):
                        raise ValueError(f"missing value for --conf-{name}")
                    val = argv[i]
                self.set_val(name.replace("-", "_"), val, apply=False)
            else:
                rest.append(a)
            i += 1
        self.apply_changes()
        return rest

    def diff(self) -> Dict[str, Any]:
        """Options changed from schema defaults (admin `config diff`)."""
        with self._lock:
            return {
                n: v
                for n, v in self._values.items()
                if v != SCHEMA[n].default
            }

    def dump(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._values)
