"""ceph_tpu — a TPU-native distributed-storage framework.

A from-scratch re-design of the capabilities of Ceph (reference:
liu-chunmei/ceph v13.1.0) around TPU-first math:

- Erasure coding (``ceph_tpu.ec``): GF(2^8) Reed-Solomon and the full
  reference plugin family (jerasure / isa / lrc / shec / clay semantics)
  implemented as batched GF(2) bit-sliced matmuls on the MXU via Pallas
  (``ceph_tpu.ops``), behind an ``ErasureCodeInterface``-equivalent API
  (reference: src/erasure-code/ErasureCodeInterface.h:170).
- Placement (``ceph_tpu.crush``): CRUSH straw2 + rjenkins as vmapped JAX
  kernels; full-cluster PG sweeps are one jitted data-parallel call
  (reference: src/crush/mapper.c:900).
- An OSDMap/PG/object-store runtime (``ceph_tpu.osd``, ``ceph_tpu.rados``,
  ``ceph_tpu.mon``, ``ceph_tpu.msg``) playing the role of Ceph's daemons.
"""

__version__ = "0.1.0"
