"""Block images on RADOS — the librbd role.

Reference: src/librbd/ (librbd::RBD create/open/remove, librbd::Image
read/write/resize) re-derived on this framework's primitives instead of
ported: image metadata is a JSON header object (`rbd_header.<name>`,
the reference's image header + rbd_directory role), bulk data rides the
striping layer (ceph_tpu.client.striper — the reference's
file-layout striping of data objects), and the exclusive-lock feature
is the in-OSD `lock` object class taken on the header (the reference's
cls_lock-based exclusive lock).  Ranged block IO maps 1:1 onto striper
extents, which the Objecter fans out concurrently.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper

DIR_OID = "rbd_directory"


class ImageNotFound(RadosError):
    def __init__(self, name: str) -> None:
        super().__init__(-2, f"image {name!r} not found")


class ImageBusy(RadosError):
    def __init__(self, name: str) -> None:
        super().__init__(-16, f"image {name!r} is locked")


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


class RBD:
    """Admin surface (reference librbd::RBD)."""

    def create(self, io: IoCtx, name: str, size: int, order: int = 22,
               stripe_unit: int = 65536, stripe_count: int = 4) -> None:
        if (1 << order) % stripe_unit:
            raise ValueError("object size must be a stripe_unit multiple")
        try:
            io.stat(_header_oid(name))
            raise RadosError(-17, f"image {name!r} exists")  # EEXIST
        except RadosError as e:
            if e.rc != -2:
                raise
        meta = {"size": size, "order": order,
                "stripe_unit": stripe_unit, "stripe_count": stripe_count,
                "data_prefix": f"rbd_data.{name}"}
        io.write_full(_header_oid(name), json.dumps(meta).encode())
        io.omap_set(DIR_OID, {name: b"1"})

    def list(self, io: IoCtx) -> List[str]:
        try:
            return sorted(io.omap_get(DIR_OID))
        except RadosError:
            return []

    def remove(self, io: IoCtx, name: str) -> None:
        img = Image(io, name)
        try:
            img.striper.remove(img.meta["data_prefix"])
        except RadosError:
            pass
        io.remove(_header_oid(name))
        try:
            io.operate(DIR_OID, [_omap_rm(name)])
        except RadosError:
            pass

    def open(self, io: IoCtx, name: str,
             exclusive: bool = False,
             owner: str = "client") -> "Image":
        return Image(io, name, exclusive=exclusive, owner=owner)


def _omap_rm(key: str):
    from ceph_tpu.osd import types as t_
    from ceph_tpu.osd.types import OSDOp

    return OSDOp(t_.OP_OMAP_RM, keys=[key])


class Image:
    """One open image (reference librbd::Image)."""

    def __init__(self, io: IoCtx, name: str, exclusive: bool = False,
                 owner: str = "client") -> None:
        self.io = io
        self.name = name
        self.owner = owner
        self.locked = False
        try:
            raw = io.read(_header_oid(name))
        except RadosError:
            raise ImageNotFound(name)
        self.meta = json.loads(raw.decode())
        self.striper = RadosStriper(
            io, stripe_unit=self.meta["stripe_unit"],
            stripe_count=self.meta["stripe_count"],
            object_size=1 << self.meta["order"])
        # restore the image's snap context on this ioctx (librbd keeps
        # the SnapContext in the header): writes after reopen must keep
        # cloning for the existing snaps
        snaps = sorted((s["id"] for s in self.meta.get("snaps",
                                                       {}).values()),
                       reverse=True)
        if snaps:
            io.set_snap_context(snaps[0], snaps)
        if exclusive:
            self._take_lock()

    # -- snapshots (librbd snap_create/list/rollback/remove over the
    # pool's self-managed snaps; snapshot metadata lives in the image
    # header exactly like the reference) ----------------------------------
    def snap_create(self, name: str) -> int:
        snaps = self.meta.setdefault("snaps", {})
        if name in snaps:
            raise RadosError(-17, f"snap {name!r} exists")  # EEXIST
        snapid = self.io.selfmanaged_snap_create()
        snaps[name] = {"id": snapid, "size": self.size}
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())
        return snapid

    def snap_list(self) -> List[dict]:
        return [{"name": n, **info}
                for n, info in sorted(self.meta.get("snaps", {}).items())]

    def _snap_info(self, name: str) -> dict:
        snaps = self.meta.get("snaps", {})
        if name not in snaps:
            raise RadosError(-2, f"no snap {name!r}")
        return snaps[name]

    def read_at_snap(self, name: str, off: int, length: int) -> bytes:
        info = self._snap_info(name)
        if off >= info["size"]:
            return b""
        length = min(length, info["size"] - off)
        got = self.striper.read(self.meta["data_prefix"], length, off,
                                snapid=info["id"], size=info["size"])
        if len(got) < length:
            got += b"\0" * (length - len(got))
        return got

    def snap_rollback(self, name: str, chunk: int = 4 << 20) -> None:
        """Rewrite head from the snap's content (librbd snap_rollback)."""
        info = self._snap_info(name)
        self.resize(info["size"])
        for off in range(0, info["size"], chunk):
            n = min(chunk, info["size"] - off)
            self.write(off, self.read_at_snap(name, off, n))

    def snap_remove(self, name: str) -> dict:
        info = self._snap_info(name)
        got = self.io.selfmanaged_snap_trim(info["id"])
        self.io.selfmanaged_snap_remove(info["id"])
        del self.meta["snaps"][name]
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())
        return got

    # -- exclusive lock (the cls_lock-backed feature) ---------------------
    def _take_lock(self) -> None:
        try:
            self.io.call(_header_oid(self.name), "lock", "lock",
                         json.dumps({"name": "rbd_lock",
                                     "owner": self.owner}).encode())
            self.locked = True
        except RadosError as e:
            if e.rc == -16:
                raise ImageBusy(self.name)
            raise

    def close(self) -> None:
        if self.locked:
            try:
                self.io.call(_header_oid(self.name), "lock", "unlock",
                             json.dumps({"name": "rbd_lock",
                                         "owner": self.owner}).encode())
            except RadosError:
                pass
            self.locked = False

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- geometry ----------------------------------------------------------
    @property
    def size(self) -> int:
        return self.meta["size"]

    def resize(self, new_size: int) -> None:
        if new_size < self.meta["size"]:
            try:
                self.striper.truncate(self.meta["data_prefix"], new_size)
            except RadosError:
                pass
        self.meta["size"] = new_size
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())

    # -- block IO ----------------------------------------------------------
    def write(self, off: int, data: bytes) -> int:
        if off + len(data) > self.size:
            raise RadosError(-27, "write past image end")  # EFBIG
        self.striper.write(self.meta["data_prefix"], data, off=off)
        return len(data)

    def read(self, off: int, length: int) -> bytes:
        if off >= self.size:
            return b""
        length = min(length, self.size - off)
        try:
            got = self.striper.read(self.meta["data_prefix"], length, off)
        except RadosError as e:
            if e.rc != -2:
                raise  # real IO failure must surface, not read as zeros
            got = b""  # image has no data objects at all yet
        if len(got) < length:
            got = got + b"\0" * (length - len(got))  # sparse tail zeros
        return got

    def discard(self, off: int, length: int) -> None:
        """Zero a range without materializing it in one buffer: chunked
        zero writes, and a tail discard truncates the striped data
        (the reference deallocates extents; truncate is our extent
        drop)."""
        length = min(length, self.size - off)
        if length <= 0:
            return
        if off + length >= self.size:
            try:
                self.striper.truncate(self.meta["data_prefix"], off)
            except RadosError:
                pass
            return
        step = 1 << 20
        zeros = b"\0" * step
        pos = off
        while pos < off + length:
            n = min(step, off + length - pos)
            self.write(pos, zeros[:n])
            pos += n
