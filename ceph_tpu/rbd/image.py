"""Block images on RADOS — the librbd role.

Reference: src/librbd/ (librbd::RBD create/open/remove, librbd::Image
read/write/resize) re-derived on this framework's primitives instead of
ported: image metadata is a JSON header object (`rbd_header.<name>`,
the reference's image header + rbd_directory role), bulk data rides the
striping layer (ceph_tpu.client.striper — the reference's
file-layout striping of data objects), and the exclusive-lock feature
is the in-OSD `lock` object class taken on the header (the reference's
cls_lock-based exclusive lock).  Ranged block IO maps 1:1 onto striper
extents, which the Objecter fans out concurrently.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper

DIR_OID = "rbd_directory"


class ImageNotFound(RadosError):
    def __init__(self, name: str) -> None:
        super().__init__(-2, f"image {name!r} not found")


class ImageBusy(RadosError):
    def __init__(self, name: str) -> None:
        super().__init__(-16, f"image {name!r} is locked")


def _header_oid(name: str) -> str:
    return f"rbd_header.{name}"


class RBD:
    """Admin surface (reference librbd::RBD)."""

    def create(self, io: IoCtx, name: str, size: int, order: int = 22,
               stripe_unit: int = 65536, stripe_count: int = 4) -> None:
        if (1 << order) % stripe_unit:
            raise ValueError("object size must be a stripe_unit multiple")
        try:
            io.stat(_header_oid(name))
            raise RadosError(-17, f"image {name!r} exists")  # EEXIST
        except RadosError as e:
            if e.rc != -2:
                raise
        meta = {"size": size, "order": order,
                "stripe_unit": stripe_unit, "stripe_count": stripe_count,
                "data_prefix": f"rbd_data.{name}"}
        io.write_full(_header_oid(name), json.dumps(meta).encode())
        io.omap_set(DIR_OID, {name: b"1"})

    def list(self, io: IoCtx) -> List[str]:
        try:
            return sorted(io.omap_get(DIR_OID))
        except RadosError:
            return []

    def remove(self, io: IoCtx, name: str) -> None:
        img = Image(io, name)
        kids = _children_of(io, name)
        if kids:
            raise RadosError(  # ENOTEMPTY, as the reference refuses
                -39, f"image {name!r} has {len(kids)} clone children")
        try:
            img.striper.remove(img.meta["data_prefix"])
        except RadosError:
            pass
        from ceph_tpu.rbd import objectmap as om_

        for oid in [om_._oid(name)] + [
                om_._oid(name, sinfo["id"])
                for sinfo in img.meta.get("snaps", {}).values()]:
            # direct removes: no reason to read a bitmap to delete it,
            # and per-snap frozen maps would otherwise leak forever
            try:
                io.remove(oid)
            except RadosError:
                pass
        parent = img.meta.get("parent")
        if parent:
            _deregister_child(io, parent["image"], name)
        io.remove(_header_oid(name))
        try:
            io.operate(DIR_OID, [_omap_rm(name)])
        except RadosError:
            pass

    def open(self, io: IoCtx, name: str,
             exclusive: bool = False,
             owner: str = "client") -> "Image":
        return Image(io, name, exclusive=exclusive, owner=owner)

    # -- clone / layering (reference librbd::RBD::clone,
    # src/librbd/librbd.cc:506; children bookkeeping = cls_rbd's
    # children keys on the parent header) ---------------------------------
    def clone(self, io: IoCtx, parent: str, snap: str, child: str,
              order: Optional[int] = None,
              stripe_unit: Optional[int] = None,
              stripe_count: Optional[int] = None) -> None:
        """Copy-on-write child of a PROTECTED parent snapshot."""
        # fresh ioctx: opening the parent must not clobber the caller's
        # snap context
        with Image(io.client.ioctx(io.pool), parent) as p:
            info = p._snap_info(snap)
            if not info.get("protected"):
                raise RadosError(-22, f"snap {snap!r} is not protected")
            self.create(io, child, info["size"],
                        order=order or p.meta["order"],
                        stripe_unit=stripe_unit or p.meta["stripe_unit"],
                        stripe_count=stripe_count or p.meta["stripe_count"])
            raw = io.read(_header_oid(child))
            meta = json.loads(raw.decode())
            meta["parent"] = {"image": parent, "snap": snap,
                              "snapid": info["id"], "size": info["size"]}
            io.write_full(_header_oid(child), json.dumps(meta).encode())
            # register the child as an OMAP key on the parent header
            # (cls_rbd children keys): atomic server-side, so a stale
            # in-memory header on some other open handle can never
            # erase the registration with a full-header rewrite
            io.omap_set(_header_oid(parent),
                        {f"child.{child}": snap.encode()})


def _omap_rm(key: str):
    from ceph_tpu.osd import types as t_
    from ceph_tpu.osd.types import OSDOp

    return OSDOp(t_.OP_OMAP_RM, keys=[key])


def _deregister_child(io: IoCtx, parent_image: str, child: str) -> None:
    """Drop `child` from the parent's children omap (cls_rbd children
    bookkeeping role); parent already gone is fine."""
    try:
        io.stat(_header_oid(parent_image))  # write ops create-on-miss:
        # a removed parent must stay removed, not come back as an
        # empty header object
        io.operate(_header_oid(parent_image),
                   [_omap_rm(f"child.{child}")])
    except RadosError:
        pass


def _children_of(io: IoCtx, image: str) -> List[dict]:
    try:
        om = io.omap_get(_header_oid(image))
    except RadosError as e:
        if e.rc != -2:
            raise  # transient IO failure must not read as "no children"
        return []
    return [{"image": k[len("child."):], "snap": v.decode()}
            for k, v in sorted(om.items()) if k.startswith("child.")]


class Image:
    """One open image (reference librbd::Image)."""

    def __init__(self, io: IoCtx, name: str, exclusive: bool = False,
                 owner: str = "client") -> None:
        self.io = io
        self.name = name
        self.owner = owner
        self.locked = False
        try:
            raw = io.read(_header_oid(name))
        except RadosError:
            raise ImageNotFound(name)
        self.meta = json.loads(raw.decode())
        self.striper = RadosStriper(
            io, stripe_unit=self.meta["stripe_unit"],
            stripe_count=self.meta["stripe_count"],
            object_size=1 << self.meta["order"])
        # restore the image's snap context on this ioctx (librbd keeps
        # the SnapContext in the header): writes after reopen must keep
        # cloning for the existing snaps
        snaps = sorted((s["id"] for s in self.meta.get("snaps",
                                                       {}).values()),
                       reverse=True)
        if snaps:
            io.set_snap_context(snaps[0], snaps)
        # layering: clones carry a parent link + an object map whose
        # clear bits route reads to the parent snapshot and trigger
        # copy-up on first write (reference ObjectMap.h:26 + the
        # copyup path of io/ObjectRequest)
        self.objmap = None
        self._parent_img: Optional["Image"] = None
        if self.meta.get("parent"):
            from ceph_tpu.rbd.objectmap import ObjectMap

            self.objmap = ObjectMap(io, name, self._num_blocks())
        if exclusive:
            self._take_lock()

    def _num_blocks(self) -> int:
        bs = 1 << self.meta["order"]
        return (self.meta["size"] + bs - 1) // bs

    def _snap_objmap(self, info: dict, bs: int):
        """Cached frozen per-snap object map, sized by the SNAP's
        geometry (a later head shrink must not clip it)."""
        from ceph_tpu.rbd.objectmap import ObjectMap

        cache = getattr(self, "_snap_maps", None)
        if cache is None:
            cache = self._snap_maps = {}
        om = cache.get(info["id"])
        if om is None:
            nblocks = (info["size"] + bs - 1) // bs
            om = ObjectMap(self.io, self.name, nblocks,
                           snapid=info["id"])
            cache[info["id"]] = om
        return om

    def _parent(self) -> "Image":
        if self._parent_img is None:
            # a FRESH ioctx: Image.__init__ installs the opened image's
            # SnapContext on its ioctx, and the parent's must never
            # clobber the child's write context (silent snapshot
            # corruption otherwise)
            pio = self.io.client.ioctx(self.io.pool)
            self._parent_img = Image(pio, self.meta["parent"]["image"])
        return self._parent_img

    # -- snapshots (librbd snap_create/list/rollback/remove over the
    # pool's self-managed snaps; snapshot metadata lives in the image
    # header exactly like the reference) ----------------------------------
    def snap_create(self, name: str) -> int:
        snaps = self.meta.setdefault("snaps", {})
        if name in snaps:
            raise RadosError(-17, f"snap {name!r} exists")  # EEXIST
        snapid = self.io.selfmanaged_snap_create()
        snaps[name] = {"id": snapid, "size": self.size}
        if self.meta.get("parent"):
            # freeze the parent overlap: a later head shrink clips the
            # LIVE overlap but must never change what this snapshot
            # reads (reference: per-snap parent overlap in snap_info)
            snaps[name]["parent_overlap"] = self.meta["parent"]["size"]
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())
        if self.objmap is not None:
            # freeze the block-existence map alongside the snap so
            # snap reads route parent/child correctly forever
            self.objmap.save_snap_copy(snapid)
        return snapid

    def snap_list(self) -> List[dict]:
        return [{"name": n, **info}
                for n, info in sorted(self.meta.get("snaps", {}).items())]

    def _snap_info(self, name: str) -> dict:
        snaps = self.meta.get("snaps", {})
        if name not in snaps:
            raise RadosError(-2, f"no snap {name!r}")
        return snaps[name]

    def read_at_snap(self, name: str, off: int, length: int) -> bytes:
        info = self._snap_info(name)
        if off >= info["size"]:
            return b""
        length = min(length, info["size"] - off)
        if self.meta.get("parent"):
            return self._layered_snap_read(info, off, length)
        got = self.striper.read(self.meta["data_prefix"], length, off,
                                snapid=info["id"], size=info["size"])
        if len(got) < length:
            got += b"\0" * (length - len(got))
        return got

    def _layered_snap_read(self, info: dict, off: int,
                           length: int) -> bytes:
        """Snap read on a CLONE: route per block via the snap's frozen
        object map — blocks unwritten at snap time come from the
        parent (whose snap is immutable), written ones from this
        image's objects at that snapid."""
        bs = 1 << self.meta["order"]
        om = self._snap_objmap(info, bs)
        out = []
        pos = off
        end = off + length
        while pos < end:
            block = pos // bs
            seg_end = min(end, (block + 1) * bs)
            n = seg_end - pos
            if om.exists(block):
                got = self.striper.read(
                    self.meta["data_prefix"], n, pos,
                    snapid=info["id"], size=info["size"])
                if len(got) < n:
                    got += b"\0" * (n - len(got))
                out.append(got)
            else:
                out.append(self._read_parent(
                    pos, n, overlap=info.get("parent_overlap")))
            pos = seg_end
        return b"".join(out)

    def snap_rollback(self, name: str, chunk: int = 4 << 20) -> None:
        """Rewrite head from the snap's content (librbd snap_rollback)."""
        info = self._snap_info(name)
        self.resize(info["size"])
        for off in range(0, info["size"], chunk):
            n = min(chunk, info["size"] - off)
            self.write(off, self.read_at_snap(name, off, n))

    def snap_remove(self, name: str) -> dict:
        info = self._snap_info(name)
        if info.get("protected"):
            raise RadosError(-16, f"snap {name!r} is protected")  # EBUSY
        got = self.io.selfmanaged_snap_trim(info["id"])
        self.io.selfmanaged_snap_remove(info["id"])
        if self.objmap is not None:
            from ceph_tpu.rbd.objectmap import ObjectMap

            ObjectMap(self.io, self.name, 0,
                      snapid=info["id"]).remove()
            getattr(self, "_snap_maps", {}).pop(info["id"], None)
        del self.meta["snaps"][name]
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())
        return got

    # -- snap protection (clone precondition; reference librbd
    # snap_protect/snap_unprotect + cls_rbd children refcounting) ---------
    def _save_header(self) -> None:
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())

    def snap_protect(self, name: str) -> None:
        self._snap_info(name)["protected"] = True
        self._save_header()

    def snap_unprotect(self, name: str) -> None:
        info = self._snap_info(name)
        kids = [c for c in _children_of(self.io, self.name)
                if c.get("snap") == name]
        if kids:
            raise RadosError(-16, f"snap {name!r} has {len(kids)} "
                             "clone children")  # EBUSY
        info["protected"] = False
        self._save_header()

    def snap_is_protected(self, name: str) -> bool:
        return bool(self._snap_info(name).get("protected"))

    def list_children(self) -> List[dict]:
        return _children_of(self.io, self.name)

    def parent_info(self) -> Optional[dict]:
        return self.meta.get("parent")

    # -- exclusive lock (the cls_lock-backed feature) ---------------------
    def _take_lock(self) -> None:
        try:
            self.io.call(_header_oid(self.name), "lock", "lock",
                         json.dumps({"name": "rbd_lock",
                                     "owner": self.owner}).encode())
            self.locked = True
        except RadosError as e:
            if e.rc == -16:
                raise ImageBusy(self.name)
            raise

    def close(self) -> None:
        if self._parent_img is not None:
            self._parent_img.close()
            self._parent_img = None
        if self.locked:
            try:
                self.io.call(_header_oid(self.name), "lock", "unlock",
                             json.dumps({"name": "rbd_lock",
                                         "owner": self.owner}).encode())
            except RadosError:
                pass
            self.locked = False

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- geometry ----------------------------------------------------------
    @property
    def size(self) -> int:
        return self.meta["size"]

    def resize(self, new_size: int) -> None:
        if new_size < self.meta["size"]:
            try:
                self.striper.truncate(self.meta["data_prefix"], new_size)
            except RadosError:
                pass
            if self.meta.get("parent"):
                # a shrink destroys the range: parent data must not
                # re-appear if the image later grows back (the same
                # hazard discard() guards against) — clip the LIVE
                # parent overlap (snapshots keep their frozen one)
                self.meta["parent"]["size"] = min(
                    self.meta["parent"]["size"], new_size)
        self.meta["size"] = new_size
        self.io.write_full(_header_oid(self.name),
                           json.dumps(self.meta).encode())
        if self.objmap is not None:
            self.objmap.resize(self._num_blocks())

    # -- block IO ----------------------------------------------------------
    def write(self, off: int, data: bytes) -> int:
        if off + len(data) > self.size:
            raise RadosError(-27, "write past image end")  # EFBIG
        if self.objmap is not None and self.meta.get("parent"):
            self._cow_write(off, data)
            return len(data)
        self.striper.write(self.meta["data_prefix"], data, off=off)
        return len(data)

    def _cow_write(self, off: int, data: bytes) -> None:
        """Copy-on-write: any block touched for the first time is
        materialized as parent content overlaid with the new bytes in
        ONE write per block (the reference's copyup before the object
        write), then marked in the object map."""
        bs = 1 << self.meta["order"]
        pos = off
        end = off + len(data)
        while pos < end:
            block = pos // bs
            bstart = block * bs
            blen = min(bs, self.size - bstart)
            seg_end = min(end, bstart + blen)
            seg = data[pos - off: seg_end - off]
            if self.objmap.exists(block):
                self.striper.write(self.meta["data_prefix"], seg, off=pos)
            else:
                base = bytearray(self._read_parent(bstart, blen))
                base[pos - bstart: pos - bstart + len(seg)] = seg
                self.striper.write(self.meta["data_prefix"], bytes(base),
                                   off=bstart)
                self.objmap.set_exists(block)
            pos = seg_end

    def _read_parent(self, off: int, length: int,
                     overlap: Optional[int] = None) -> bytes:
        """Parent-snap content backing [off, off+length) (zeros past
        the overlap); parents may themselves be clones — their own
        read() recurses up the chain.  `overlap` overrides the live
        parent coverage (snap reads pass their frozen value)."""
        p = self.meta["parent"]
        psize = p["size"] if overlap is None else overlap
        if off >= psize:
            return b"\0" * length
        n = min(length, psize - off)
        got = self._parent().read_at_snap(p["snap"], off, n)
        if len(got) < length:
            got += b"\0" * (length - len(got))
        return got

    def read(self, off: int, length: int) -> bytes:
        if off >= self.size:
            return b""
        length = min(length, self.size - off)
        if self.objmap is not None and self.meta.get("parent"):
            return self._layered_read(off, length)
        try:
            got = self.striper.read(self.meta["data_prefix"], length, off)
        except RadosError as e:
            if e.rc != -2:
                raise  # real IO failure must surface, not read as zeros
            got = b""  # image has no data objects at all yet
        if len(got) < length:
            got = got + b"\0" * (length - len(got))  # sparse tail zeros
        return got

    def _layered_read(self, off: int, length: int) -> bytes:
        """Per-block dispatch on the object map: a set bit reads the
        child's objects, a clear bit reads the parent snapshot — the
        child never pays an object lookup for unwritten blocks
        (reference ObjectMap fast-diff read path)."""
        bs = 1 << self.meta["order"]
        out = []
        pos = off
        end = off + length
        while pos < end:
            block = pos // bs
            bstart = block * bs
            seg_end = min(end, bstart + bs)
            n = seg_end - pos
            if self.objmap.exists(block):
                try:
                    got = self.striper.read(self.meta["data_prefix"],
                                            n, pos)
                except RadosError as e:
                    if e.rc != -2:
                        raise
                    got = b""
                if len(got) < n:
                    got += b"\0" * (n - len(got))
                out.append(got)
            else:
                out.append(self._read_parent(pos, n))
            pos = seg_end
        return b"".join(out)

    def flatten(self) -> None:
        """Copy every parent-backed block into the child and sever the
        parent link (reference librbd flatten).  Refused while the
        clone has snapshots: their frozen object maps route unwritten
        blocks to the parent, which flatten would sever."""
        if not self.meta.get("parent"):
            return
        if self.meta.get("snaps"):
            raise RadosError(-16, "clone has snapshots; remove them "
                             "before flatten")  # EBUSY
        bs = 1 << self.meta["order"]
        for block in range(self._num_blocks()):
            if self.objmap.exists(block):
                continue
            bstart = block * bs
            blen = min(bs, self.size - bstart)
            self.striper.write(self.meta["data_prefix"],
                               self._read_parent(bstart, blen),
                               off=bstart)
            self.objmap.set_exists(block)
        parent = self.meta.pop("parent")
        self._save_header()
        _deregister_child(self.io, parent["image"], self.name)
        if self._parent_img is not None:
            self._parent_img.close()
            self._parent_img = None
        # the bitmap is meaningless for a non-clone: remove it so a
        # future same-name clone can never load stale bits
        self.objmap.remove()
        self.objmap = None  # no longer a clone: plain reads from here

    def discard(self, off: int, length: int) -> None:
        """Zero a range without materializing it in one buffer: chunked
        zero writes, and a tail discard truncates the striped data
        (the reference deallocates extents; truncate is our extent
        drop)."""
        length = min(length, self.size - off)
        if length <= 0:
            return
        if off + length >= self.size and self.objmap is None:
            # tail discard on a NON-clone: drop the extents outright.
            # A clone cannot take this shortcut — truncating child
            # objects leaves clear-bit blocks routed to the PARENT, so
            # the "discarded" range would read back parent data; the
            # zero-write path below COWs zeros over it instead.
            try:
                self.striper.truncate(self.meta["data_prefix"], off)
            except RadosError:
                pass
            return
        step = 1 << 20
        zeros = b"\0" * step
        pos = off
        while pos < off + length:
            n = min(step, off + length - pos)
            self.write(pos, zeros[:n])
            pos += n
