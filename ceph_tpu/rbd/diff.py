"""rbd export-diff / import-diff — snapshot delta streams.

Reference: src/tools/rbd's export-diff/import-diff (diff_iterate over
librbd, src/librbd/api/DiffIterate.cc): serialize the extents that
changed between two points in time (snap -> snap, or snap -> head) so
a remote image holding the FROM snapshot can be advanced to the TO
state without shipping the whole image — the incremental-backup
primitive.

Stream format (framed, crc-guarded):

    [u32 magic "RDF1"] [u32 hdr_len] [hdr json] [u32 crc32c(hdr)]
    repeat: [u8 'w'] [u64 off] [u32 len] [u32 crc] [data]
            with crc = crc32c(off || len || data) — the RECORD HEADER
            is covered too, so a flipped offset can never apply data
            at the wrong place with a "valid" payload crc
    end:    [u8 'e'] [u32 record_count]

The header carries {image, from_snap, to_snap, size}.  Regions are
discovered per block (1 << order) by comparing the two points in time;
clones' unwritten blocks read identically through the parent and emit
nothing.  import-diff VALIDATES THE WHOLE STREAM FIRST (every crc,
framing, the end record) and only then touches the image — a torn or
corrupt stream refuses before any destructive step.  It demands the
target holds FROM (same protection the reference enforces), applies
the writes, resizes to the recorded size, and snapshots TO at the
end, so chains of diffs compose.
"""

from __future__ import annotations

import json
import struct
from typing import BinaryIO, Iterator, Optional, Tuple

from ceph_tpu.core.crc import crc32c
from ceph_tpu.rbd.image import Image

_MAGIC = 0x52444631  # "RDF1"
_HDR = struct.Struct("<II")      # magic, header length
_REC = struct.Struct("<BQII")    # 'w', off, len, crc(off||len||data)
_OFFLEN = struct.Struct("<QI")
_END = struct.Struct("<BI")      # 'e', record count


def _rec_crc(off: int, data: bytes) -> int:
    return crc32c(data, crc32c(_OFFLEN.pack(off, len(data))))


def diff_iterate(img: Image, from_snap: Optional[str],
                 to_snap: Optional[str] = None,
                 ) -> Iterator[Tuple[int, bytes]]:
    """(offset, data) extents that differ between from_snap and
    to_snap (None = head), at block granularity."""
    bs = 1 << img.meta["order"]
    to_size = (img._snap_info(to_snap)["size"] if to_snap
               else img.size)
    from_size = (img._snap_info(from_snap)["size"] if from_snap
                 else 0)

    def read_to(off: int, n: int) -> bytes:
        return (img.read_at_snap(to_snap, off, n) if to_snap
                else img.read(off, n))

    for off in range(0, to_size, bs):
        n = min(bs, to_size - off)
        new = read_to(off, n)
        if from_snap and off < from_size:
            old = img.read_at_snap(from_snap, off,
                                   min(n, from_size - off))
            if len(old) < n:
                old += b"\0" * (n - len(old))
        else:
            old = b"\0" * n
        if new != old:
            yield off, new


def export_diff(img: Image, fh: BinaryIO, from_snap: Optional[str],
                to_snap: Optional[str] = None) -> int:
    """Write the delta stream; returns bytes of changed data."""
    if from_snap:
        img._snap_info(from_snap)  # ENOENT surfaces before any output
    to_size = (img._snap_info(to_snap)["size"] if to_snap
               else img.size)
    hdr = json.dumps({"image": img.name, "from_snap": from_snap,
                      "to_snap": to_snap, "size": to_size}).encode()
    fh.write(_HDR.pack(_MAGIC, len(hdr)))
    fh.write(hdr)
    fh.write(struct.pack("<I", crc32c(hdr)))
    changed = 0
    count = 0
    for off, data in diff_iterate(img, from_snap, to_snap):
        fh.write(_REC.pack(ord("w"), off, len(data),
                           _rec_crc(off, data)))
        fh.write(data)
        changed += len(data)
        count += 1
    fh.write(_END.pack(ord("e"), count))
    return changed


class DiffError(ValueError):
    pass


def _need(fh: BinaryIO, n: int, what: str) -> bytes:
    raw = fh.read(n)
    if len(raw) < n:
        raise DiffError(f"truncated stream ({what})")
    return raw


def import_diff(img: Image, fh: BinaryIO) -> dict:
    """Apply a delta stream to `img` (which must hold FROM); snapshots
    TO when named.  Returns the stream header.  The WHOLE stream is
    parsed and crc-verified before the first write — corruption
    refuses with DiffError and leaves the image untouched."""
    magic, hlen = _HDR.unpack(_need(fh, _HDR.size, "header frame"))
    if magic != _MAGIC:
        raise DiffError("bad magic: not an rbd diff stream")
    hdr_blob = _need(fh, hlen, "header body")
    (want_h,) = struct.unpack("<I", _need(fh, 4, "header crc"))
    if crc32c(hdr_blob) != want_h:
        raise DiffError("header crc mismatch")
    try:
        hdr = json.loads(hdr_blob.decode())
        size = int(hdr["size"])
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise DiffError(f"malformed header: {e!r}")
    # parse + verify EVERYTHING up front (validate-then-apply)
    records = []
    while True:
        kind = _need(fh, 1, "record kind")[0]
        if kind == ord("e"):
            (count,) = struct.unpack(
                "<I", _need(fh, 4, "end record"))
            if count != len(records):
                raise DiffError("end-record count mismatch")
            break
        if kind != ord("w"):
            raise DiffError(f"unknown record kind {kind!r}")
        off, ln, want = struct.unpack(
            "<QII", _need(fh, _REC.size - 1, "record header"))
        data = _need(fh, ln, "record data")
        if _rec_crc(off, data) != want:
            raise DiffError("torn/corrupt data record")
        if off + ln > size:
            raise DiffError("record extends past the recorded size")
        records.append((off, data))
    from_snap = hdr.get("from_snap")
    if from_snap and from_snap not in img.meta.get("snaps", {}):
        raise DiffError(
            f"target lacks start snapshot {from_snap!r}")  # reference rule
    # stream fully validated: now (and only now) touch the image
    if size != img.size:
        img.resize(size)
    applied = 0
    for off, data in records:
        img.write(off, data)
        applied += len(data)
    to_snap = hdr.get("to_snap")
    if to_snap and to_snap not in img.meta.get("snaps", {}):
        img.snap_create(to_snap)
    hdr["applied_bytes"] = applied
    return hdr
