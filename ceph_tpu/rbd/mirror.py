"""rbd-mirror daemon: continuous journal replay onto a peer image.

Reference: src/tools/rbd_mirror/ — the mirror daemon tails a primary
image's journal and replays its events onto the secondary, persisting
the replay position so a restarted daemon resumes instead of
re-applying history (the reference's MirrorPeerClientMeta commit
position).  Here the cursor lives in the SECONDARY image's header
(`mirror_cursor.<src>`), written after every applied batch — replay is
idempotent, so a crash between apply and cursor persist re-applies at
most one batch.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ceph_tpu.rbd.journal import ImageJournal


class MirrorDaemon:
    def __init__(self, src_image, dst_image,
                 interval: float = 0.1) -> None:
        self.src = src_image
        self.dst = dst_image
        self.interval = interval
        self.journal = ImageJournal(src_image)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.applied = 0

    # -- cursor persistence ------------------------------------------------
    @property
    def _cursor_key(self) -> str:
        return f"mirror_cursor.{self.src.name}"

    def _load_cursor(self) -> int:
        return int(self.dst.meta.get(self._cursor_key, 0))

    def _save_cursor(self, seq: int) -> None:
        self.dst.meta[self._cursor_key] = seq
        from ceph_tpu.rbd.image import _header_oid

        self.dst.io.write_full(_header_oid(self.dst.name),
                               json.dumps(self.dst.meta).encode())

    # -- replay ------------------------------------------------------------
    def sync_once(self) -> int:
        """One tail pass; returns events applied."""
        cursor = self._load_cursor()
        n = 0
        last = cursor
        for seq, payload in self.journal.journaler.entries(after=cursor):
            self.journal._apply_event(self.dst,
                                      json.loads(payload.decode()))
            last = seq
            n += 1
        if n:
            self._save_cursor(last)
            self.applied += n
        return n

    # -- daemon ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return

        def _loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.sync_once()
                except Exception:
                    continue  # transient (peer down): retry next tick

        self._stop.clear()
        self._thread = threading.Thread(
            target=_loop, daemon=True,
            name=f"rbd-mirror-{self.src.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
