"""rbd-mirror daemon: continuous journal replay onto a peer image.

Reference: src/tools/rbd_mirror/ — the mirror daemon tails a primary
image's journal and replays its events onto the secondary, persisting
the replay position so a restarted daemon resumes instead of
re-applying history (the reference's MirrorPeerClientMeta commit
position).  The cursor is a cls_journal CLIENT registered on the
SOURCE journal's metadata object (reference src/cls/journal client
registration — the journal knows every consumer's replay position, so
trim decisions can consult them), committed after every applied
batch — replay is idempotent, so a crash between apply and cursor
persist re-applies at most one batch.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

from ceph_tpu.rbd.journal import ImageJournal


class MirrorDaemon:
    def __init__(self, src_image, dst_image,
                 interval: float = 0.1) -> None:
        self.src = src_image
        self.dst = dst_image
        self.interval = interval
        self.journal = ImageJournal(src_image)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.applied = 0

    # -- cursor persistence (cls_journal client on the src journal) --------
    @property
    def _client_id(self) -> str:
        return f"mirror.{self.dst.name}"

    def _ensure_registered(self) -> None:
        from ceph_tpu.client.rados import RadosError

        j = self.journal.journaler
        try:
            j.io.call(j.meta_oid, "journal", "client_register",
                      json.dumps({"id": self._client_id}).encode())
        except RadosError as e:
            if e.rc != -17:  # already registered is the common case
                raise

    def _load_cursor(self) -> int:
        from ceph_tpu.client.rados import RadosError

        j = self.journal.journaler
        try:
            got = j.io.call(j.meta_oid, "journal", "get_client",
                            self._client_id.encode())
        except RadosError as e:
            if e.rc == -2:
                self._ensure_registered()
                return 0
            raise
        return int(json.loads(got.decode()).get("commit", 0))

    def _save_cursor(self, seq: int) -> None:
        j = self.journal.journaler
        j.io.call(j.meta_oid, "journal", "client_commit",
                  json.dumps({"id": self._client_id,
                              "commit": seq}).encode())

    # -- replay ------------------------------------------------------------
    def sync_once(self) -> int:
        """One tail pass; returns events applied."""
        cursor = self._load_cursor()
        n = 0
        last = cursor
        for seq, payload in self.journal.journaler.entries(after=cursor):
            self.journal._apply_event(self.dst,
                                      json.loads(payload.decode()))
            last = seq
            n += 1
        if n:
            self._save_cursor(last)
            self.applied += n
        return n

    # -- daemon ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return

        def _loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.sync_once()
                except Exception:
                    continue  # transient (peer down): retry next tick

        self._stop.clear()
        self._thread = threading.Thread(
            target=_loop, daemon=True,
            name=f"rbd-mirror-{self.src.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
