"""RBD image journal — crash-consistent write journaling + mirror replay.

Reference: src/journal/ (Journaler over "journal data" RADOS objects
with a commit position in journal metadata) and librbd's journaling
feature (librbd/journal/: every image mutation is appended as an event
BEFORE it is applied to the data objects; on open, events past the
commit position replay; rbd-mirror tails the same journal and applies
the events to a remote image).

Layout (all in the image's pool):
- `journal.<image>` : metadata object — omap {"commit": seq,
  "head": seq} (the commit-position object)
- `journal_data.<image>.<n>` : entry ring objects, appended frames
  [u64 seq][u32 len][u32 crc32c(payload)][payload], splayed by
  seq % splay (the reference's splay_width)

Events are JSON {"t": "write"|"discard"|"resize", ...} — applying an
event is idempotent, so replay after a crash (or replaying a prefix
twice on a mirror) converges.
"""

from __future__ import annotations

import json
import struct
from typing import Callable, Dict, List, Optional, Tuple

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.core.crc import crc32c

_FRAME = struct.Struct("<QII")  # seq, payload_len, crc


class Journaler:
    """Append/replay/commit over the journal objects (src/journal/
    Journaler role)."""

    def __init__(self, io: IoCtx, name: str, splay: int = 4) -> None:
        self.io = io
        self.name = name
        self.splay = splay
        self.meta_oid = f"journal.{name}"

    # -- metadata ----------------------------------------------------------
    # Every meta field lives in atomic in-PG cls counters on the meta
    # object: seq minting is counter.alloc, head/commit are monotonic
    # counter.max watermarks.  No read-modify-write anywhere, so
    # concurrent appenders/committers (journaling is not gated on the
    # image exclusive lock) can neither mint duplicate seqs nor regress
    # head/commit and hide durable entries from replay.

    def create(self) -> None:
        self.io.call(self.meta_oid, "counter", "max", b"commit 0")

    def head(self) -> int:
        return int(self.io.call(self.meta_oid, "counter", "get", b"jseq"))

    def committed(self) -> int:
        return int(self.io.call(self.meta_oid, "counter", "get", b"commit"))

    def _data_oid(self, seq: int) -> str:
        return f"journal_data.{self.name}.{seq % self.splay}"

    # -- write side --------------------------------------------------------
    def append(self, payload: bytes) -> int:
        """Durably append one entry; returns its seq.  head() (= the
        seq counter) may briefly run ahead of a mid-flight frame, so
        readers tolerate a not-yet-durable tail: entries() scans frames
        and simply doesn't see seqs whose frame hasn't landed; the crc
        guards torn tails."""
        seq = int(self.io.call(self.meta_oid, "counter", "alloc", b"jseq"))
        frame = _FRAME.pack(seq, len(payload), crc32c(payload)) + payload
        self.io.append(self._data_oid(seq), frame)
        return seq

    def commit(self, seq: int) -> None:
        """Advance the commit position (events <= seq are applied);
        atomic monotonic max, never a regression."""
        self.io.call(self.meta_oid, "counter", "max",
                     f"commit {seq}".encode())

    # -- read side ---------------------------------------------------------
    def _entries_of(self, oid: str) -> List[Tuple[int, bytes]]:
        try:
            raw = self.io.read(oid)
        except RadosError:
            return []
        out = []
        off = 0
        while off + _FRAME.size <= len(raw):
            seq, ln, want = _FRAME.unpack_from(raw, off)
            payload = raw[off + _FRAME.size: off + _FRAME.size + ln]
            if len(payload) < ln or crc32c(payload) != want:
                break  # torn tail of this ring object
            out.append((seq, payload))
            off += _FRAME.size + ln
        return out

    def entries(self, after: int = 0,
                upto: Optional[int] = None) -> List[Tuple[int, bytes]]:
        """All entries with after < seq <= upto, seq-ordered across the
        splayed objects."""
        upto = self.head() if upto is None else upto
        got: List[Tuple[int, bytes]] = []
        for n in range(self.splay):
            got.extend(e for e in self._entries_of(
                f"journal_data.{self.name}.{n}")
                if after < e[0] <= upto)
        got.sort()
        return got

    def replay(self, handler: Callable[[int, bytes], None],
               from_committed: bool = True) -> int:
        """Feed uncommitted (or all) entries to `handler`; returns the
        last seq seen (caller commits it when applied)."""
        after = self.committed() if from_committed else 0
        last = after
        for seq, payload in self.entries(after=after):
            handler(seq, payload)
            last = seq
        return last

    def trim(self) -> None:
        """Drop ring objects wholly below the commit position
        (the reference's object-set trimming; ring objects are only
        removed when every entry in them is committed)."""
        commit = self.committed()
        for n in range(self.splay):
            oid = f"journal_data.{self.name}.{n}"
            entries = self._entries_of(oid)
            if entries and all(seq <= commit for seq, _ in entries):
                try:
                    self.io.remove(oid)
                except RadosError:
                    pass

    def remove(self) -> None:
        for n in range(self.splay):
            try:
                self.io.remove(f"journal_data.{self.name}.{n}")
            except RadosError:
                pass
        try:
            self.io.remove(self.meta_oid)
        except RadosError:
            pass


class ImageJournal:
    """librbd journaling feature: append-before-apply + crash replay +
    mirror replay (librbd/journal/ + rbd-mirror roles)."""

    def __init__(self, image) -> None:
        self.image = image
        self.journaler = Journaler(image.io, image.name)
        self.journaler.create()

    # -- event plumbing ----------------------------------------------------
    @staticmethod
    def _apply_event(image, ev: dict) -> None:
        t = ev["t"]
        if t == "write":
            image.write(ev["off"], bytes.fromhex(ev["data"]))
        elif t == "discard":
            image.discard(ev["off"], ev["len"])
        elif t == "resize":
            image.resize(ev["size"])

    def log_and_apply(self, ev: dict) -> None:
        """The journaled write path: the event is durable in the journal
        BEFORE the data objects change; commit advances after apply."""
        seq = self.journaler.append(json.dumps(ev).encode())
        self._apply_event(self.image, ev)
        self.journaler.commit(seq)

    # -- image ops ---------------------------------------------------------
    def write(self, off: int, data: bytes) -> int:
        self.log_and_apply({"t": "write", "off": off,
                            "data": data.hex()})
        return len(data)

    def discard(self, off: int, length: int) -> None:
        self.log_and_apply({"t": "discard", "off": off, "len": length})

    def resize(self, size: int) -> None:
        self.log_and_apply({"t": "resize", "size": size})

    # -- recovery + mirroring ---------------------------------------------
    def replay_pending(self) -> int:
        """Crash recovery at open: re-apply events past the commit
        position (idempotent), then commit.  Returns replayed count."""
        n = 0

        def h(seq: int, payload: bytes) -> None:
            nonlocal n
            self._apply_event(self.image, json.loads(payload.decode()))
            n += 1

        last = self.journaler.replay(h)
        self.journaler.commit(last)
        return n

    def mirror_to(self, other_image, after: int = 0) -> int:
        """rbd-mirror role (one-shot): apply this journal's events
        (seq > after) to another image; returns the last seq applied —
        feed it back as `after` to tail incrementally."""
        last = after
        for seq, payload in self.journaler.entries(after=after):
            self._apply_event(other_image, json.loads(payload.decode()))
            last = seq
        return last
