"""RBD-role block images over striped RADOS objects (reference:
src/librbd/)."""

from ceph_tpu.rbd.image import RBD, Image, ImageBusy, ImageNotFound

__all__ = ["RBD", "Image", "ImageBusy", "ImageNotFound"]
