"""RBD object map — per-block existence bitmap.

Reference: src/librbd/ObjectMap.h:26 + cls_bitmap state tracking: a
bitmap with one entry per data block that says whether the block has
ever been written in THIS image.  For clones this is what makes child
reads cheap (a clear bit routes the read to the parent snapshot with
no child-object lookup at all) and child writes correct (a clear bit
triggers copy-up before the first write).

Granularity: logical blocks of ``1 << order`` bytes.  With the default
striping (stripe_count == 1) a logical block IS the backing RADOS
object, matching the reference's per-object map exactly; with fancy
striping the map tracks logical windows of the same size (documented
deviation — existence is still exact, just coarser than physical
objects).

Storage: raw bitmap bytes in ``rbd_object_map.<image>`` (the
reference's rbd_object_map.<id> object), updated with single-byte
ranged writes so flipping one block never rewrites the map.
"""

from __future__ import annotations

from ceph_tpu.client.rados import IoCtx, RadosError


def _oid(image: str, snapid=None) -> str:
    # per-snap maps mirror the reference's rbd_object_map.<id>.<snapid>
    return (f"rbd_object_map.{image}" if snapid is None
            else f"rbd_object_map.{image}@{snapid}")


class ObjectMap:
    def __init__(self, io: IoCtx, image: str, num_blocks: int,
                 snapid=None) -> None:
        self.io = io
        self.image = image
        self.snapid = snapid
        self.num_blocks = num_blocks
        try:
            raw = bytearray(io.read(_oid(image, snapid)))
        except RadosError as e:
            if e.rc != -2:
                raise  # a real IO failure must surface: an all-clear
                # map would route reads to the parent and let the next
                # write copy parent data OVER existing child objects
            raw = bytearray()
        want = (num_blocks + 7) // 8
        if len(raw) < want:
            raw.extend(b"\0" * (want - len(raw)))
        self._bits = raw

    def exists(self, block: int) -> bool:
        if not 0 <= block < self.num_blocks:
            return False
        return bool(self._bits[block >> 3] & (1 << (block & 7)))

    def set_exists(self, block: int) -> None:
        """Mark + persist one block (single-byte ranged write)."""
        byte = block >> 3
        new = self._bits[byte] | (1 << (block & 7))
        if new == self._bits[byte]:
            return
        self._bits[byte] = new
        self.io.write(_oid(self.image, self.snapid), bytes([new]),
                      off=byte)

    def resize(self, num_blocks: int) -> None:
        self.num_blocks = num_blocks
        want = (num_blocks + 7) // 8
        if len(self._bits) < want:
            pad = b"\0" * (want - len(self._bits))
            self.io.write(_oid(self.image, self.snapid), pad,
                          off=len(self._bits))
            self._bits.extend(pad)

    def save_full(self) -> None:
        self.io.write_full(_oid(self.image, self.snapid),
                           bytes(self._bits))

    def save_snap_copy(self, snapid: int) -> None:
        """Freeze the CURRENT map as the snap's map (snap_create time:
        the reference snapshots rbd_object_map alongside the image)."""
        self.io.write_full(_oid(self.image, snapid), bytes(self._bits))

    def remove(self) -> None:
        try:
            self.io.remove(_oid(self.image, self.snapid))
        except RadosError:
            pass
