"""Monitor wire messages (election, paxos, commands, subscriptions).

Reference: src/messages/MMonElection.h, MMonPaxos.h, MMonCommand.h,
MMonSubscribe.h, MOSDMap.h, MOSDBoot.h, MOSDFailure.h.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register


@register
class MMonElection(Message):
    TYPE = 30
    PROPOSE = 1
    ACK = 2
    VICTORY = 3

    def __init__(self, op: int = 0, epoch: int = 0, rank: int = -1) -> None:
        super().__init__()
        self.op = op
        self.epoch = epoch
        self.rank = rank

    def encode_payload(self, e: Encoder) -> None:
        e.u8(self.op).u32(self.epoch).s32(self.rank)

    def decode_payload(self, d: Decoder) -> None:
        self.op = d.u8()
        self.epoch = d.u32()
        self.rank = d.s32()


@register
class MMonPaxos(Message):
    """Multi-instance Paxos (reference MMonPaxos ops: collect/last/
    begin/accept/commit/lease)."""

    TYPE = 31
    COLLECT = 1   # phase 1a (leader -> peons)
    LAST = 2      # phase 1b (peon -> leader, with last accepted)
    BEGIN = 3     # phase 2a (leader proposes value for version)
    ACCEPT = 4    # phase 2b
    COMMIT = 5    # learn
    LEASE = 6     # leader extends read lease
    CATCHUP_REQ = 7  # peon -> leader: inc had no base, need the full map
    CATCHUP = 8      # leader -> peon: full current map
    SYNC_REQ = 9     # lagging mon: send me your service-state snapshot
    SYNC = 10        # reply: JSON snapshot of every PaxosService state

    def __init__(self, op: int = 0, pn: int = 0, version: int = 0,
                 value: bytes = b"", first_committed: int = 0,
                 last_committed: int = 0,
                 uncommitted_pn: int = 0,
                 uncommitted_v: int = 0,
                 uncommitted_value: bytes = b"") -> None:
        super().__init__()
        self.op = op
        self.pn = pn
        self.version = version
        self.value = value
        self.first_committed = first_committed
        self.last_committed = last_committed
        self.uncommitted_pn = uncommitted_pn
        self.uncommitted_v = uncommitted_v
        self.uncommitted_value = uncommitted_value

    def encode_payload(self, e: Encoder) -> None:
        e.u8(self.op).u64(self.pn).u64(self.version).blob(self.value)
        e.u64(self.first_committed).u64(self.last_committed)
        e.u64(self.uncommitted_pn).u64(self.uncommitted_v)
        e.blob(self.uncommitted_value)

    def decode_payload(self, d: Decoder) -> None:
        self.op = d.u8()
        self.pn = d.u64()
        self.version = d.u64()
        self.value = d.blob()
        self.first_committed = d.u64()
        self.last_committed = d.u64()
        self.uncommitted_pn = d.u64()
        self.uncommitted_v = d.u64()
        self.uncommitted_value = d.blob()


@register
class MMonCommand(Message):
    """JSON command (the `ceph` CLI path, reference MMonCommand)."""

    TYPE = 32

    def __init__(self, cmd: Optional[dict] = None) -> None:
        super().__init__()
        self.cmd = cmd or {}

    def encode_payload(self, e: Encoder) -> None:
        e.string(json.dumps(self.cmd))

    def decode_payload(self, d: Decoder) -> None:
        self.cmd = json.loads(d.string())


@register
class MMonCommandReply(Message):
    TYPE = 33

    def __init__(self, code: int = 0, out: Optional[dict] = None) -> None:
        super().__init__()
        self.code = code
        self.out = out or {}

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.code).string(json.dumps(self.out))

    def decode_payload(self, d: Decoder) -> None:
        self.code = d.s32()
        self.out = json.loads(d.string())


@register
class MMonSubscribe(Message):
    """Subscribe to map updates (reference MMonSubscribe: what/since)."""

    TYPE = 34

    def __init__(self, what: str = "osdmap", since: int = 0) -> None:
        super().__init__()
        self.what = what
        self.since = since

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.what).u32(self.since)

    def decode_payload(self, d: Decoder) -> None:
        self.what = d.string()
        self.since = d.u32()


@register
class MOSDMapMsg(Message):
    """osdmap push (reference MOSDMap): either the full map (`data`,
    first subscribe / out-of-window) or a chain of incrementals
    (`incs`, applied in order) — O(delta) bytes per map change."""

    TYPE = 35

    def __init__(self, epoch: int = 0, data: bytes = b"") -> None:
        super().__init__()
        self.epoch = epoch
        self.data = data
        self.incs = []  # type: list[bytes]

    def encode_payload(self, e: Encoder) -> None:
        e.u32(self.epoch).blob(self.data)
        e.seq(self.incs, lambda enc, b: enc.blob(b))

    def decode_payload(self, d: Decoder) -> None:
        self.epoch = d.u32()
        self.data = d.blob()
        self.incs = (d.seq(lambda dd: dd.blob())
                     if d.remaining_in_frame() else [])


@register
class MOSDBoot(Message):
    """osd -> mon: I'm up at this address (reference MOSDBoot)."""

    TYPE = 36

    def __init__(self, osd_id: int = -1, ip: str = "", port: int = 0,
                 hb_ip: str = "", hb_port: int = 0) -> None:
        super().__init__()
        self.osd_id = osd_id
        self.ip = ip
        self.port = port
        self.hb_ip = hb_ip
        self.hb_port = hb_port

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.osd_id).string(self.ip).u32(self.port)
        e.string(self.hb_ip).u32(self.hb_port)

    def decode_payload(self, d: Decoder) -> None:
        self.osd_id = d.s32()
        self.ip = d.string()
        self.port = d.u32()
        self.hb_ip = d.string()
        self.hb_port = d.u32()


@register
class MOSDFailure(Message):
    """osd -> mon: peer missed heartbeats (reference MOSDFailure;
    decided by OSDMonitor::prepare_failure, OSDMonitor.cc:2643)."""

    TYPE = 37

    def __init__(self, target: int = -1, failed_for: float = 0.0) -> None:
        super().__init__()
        self.target = target
        self.failed_for = failed_for

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.target).f64(self.failed_for)

    def decode_payload(self, d: Decoder) -> None:
        self.target = d.s32()
        self.failed_for = d.f64()


@register
class MAuth(Message):
    """client/daemon -> mon: cephx handshake (reference MAuth over
    src/auth/cephx/CephxProtocol.h ops)."""

    TYPE = 38
    GET_CHALLENGE = 1
    REQUEST = 2

    def __init__(self, op: int = 0, name: str = "",
                 client_challenge: bytes = b"", proof: bytes = b"") -> None:
        super().__init__()
        self.op = op
        self.name = name
        self.client_challenge = client_challenge
        self.proof = proof

    def encode_payload(self, e: Encoder) -> None:
        e.u8(self.op).string(self.name)
        e.blob(self.client_challenge).blob(self.proof)

    def decode_payload(self, d: Decoder) -> None:
        self.op = d.u8()
        self.name = d.string()
        self.client_challenge = d.blob()
        self.proof = d.blob()


@register
class MAuthReply(Message):
    """mon -> client: challenge or (sealed session key + ticket)."""

    TYPE = 39

    def __init__(self, result: int = 0, challenge: bytes = b"",
                 sealed_client: bytes = b"",
                 ticket_blob: bytes = b"") -> None:
        super().__init__()
        self.result = result
        self.challenge = challenge
        self.sealed_client = sealed_client
        self.ticket_blob = ticket_blob

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.result).blob(self.challenge)
        e.blob(self.sealed_client).blob(self.ticket_blob)

    def decode_payload(self, d: Decoder) -> None:
        self.result = d.s32()
        self.challenge = d.blob()
        self.sealed_client = d.blob()
        self.ticket_blob = d.blob()


@register
class MPGStats(Message):
    """Per-OSD PG stats report (reference MPGStats, the mgr/mon stats
    feed behind `ceph pg dump` and the PG health checks).  Stats are
    TRANSIENT on the mon (mgr-style), never paxos-committed."""

    TYPE = 40

    def __init__(self, osd: int = -1, epoch: int = 0,
                 pgs: Optional[list] = None, used_bytes: int = 0,
                 total_bytes: int = 0, stats: Optional[list] = None,
                 slow_ops: int = 0, heartbeat_misses: int = 0) -> None:
        super().__init__()
        self.osd = osd
        self.epoch = epoch
        # [(pool, ps, state, num_objects, last_update_epoch,
        #   last_update_version, is_primary)] — the legacy thin rows,
        # still carried so pre-PGStat consumers keep working
        self.pgs = pgs or []
        # store fullness (ObjectStore::statfs — the nearfull/full feed)
        self.used_bytes = used_bytes
        self.total_bytes = total_bytes
        # v2 tail: rich PGStat rows (osd/types.py) + daemon health
        # signals — slow-ring depth (SLOW_OPS) and the cumulative
        # heartbeat-miss counter (OSD_SLOW_HEARTBEAT)
        self.stats = stats or []
        self.slow_ops = slow_ops
        self.heartbeat_misses = heartbeat_misses

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.osd).u32(self.epoch)
        e.seq(self.pgs, lambda en, p: (
            en.s64(p[0]), en.u32(p[1]), en.string(p[2]), en.u64(p[3]),
            en.u32(p[4]), en.u64(p[5]), en.u8(1 if p[6] else 0)))
        e.u64(self.used_bytes).u64(self.total_bytes)
        e.seq(self.stats, lambda en, s: s.encode(en))
        e.u32(self.slow_ops).u64(self.heartbeat_misses)

    def decode_payload(self, d: Decoder) -> None:
        from ceph_tpu.osd.types import PGStat

        self.osd = d.s32()
        self.epoch = d.u32()
        self.pgs = d.seq(lambda dd: (
            dd.s64(), dd.u32(), dd.string(), dd.u64(), dd.u32(),
            dd.u64(), bool(dd.u8())))
        self.used_bytes = d.u64()
        self.total_bytes = d.u64()
        # v2 tail (absent in pre-telemetry blobs)
        if d.remaining_in_frame():
            self.stats = d.seq(lambda dd: PGStat.decode(dd))
            self.slow_ops = d.u32()
            self.heartbeat_misses = d.u64()


@register
class MMDSBoot(Message):
    """mds -> mon: rank R serves at this address (reference MMDSBeacon
    boot, src/messages/MMDSBeacon.h — the FSMap feed).

    `nonce` identifies the boot INCARNATION (the reference beacon's
    gid/seq role): beacons are resent until committed AND ride
    lossless sessions, so a replayed stale beacon can arrive after an
    `mds fail` — the FSMap must not let it resurrect the failed
    incarnation.  Decodes nonce=0 from pre-round-5 blobs (corpus
    back-compat)."""

    TYPE = 45

    def __init__(self, rank: int = -1, ip: str = "", port: int = 0,
                 boot_nonce: int = 0) -> None:
        super().__init__()
        self.rank = rank
        self.ip = ip
        self.port = port
        # NOT named `nonce`: the messenger stamps msg.nonce with its
        # own session nonce on every send (messenger.py), which would
        # clobber this field
        self.boot_nonce = boot_nonce

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.rank).string(self.ip).u32(self.port)
        e.u64(self.boot_nonce)

    def decode_payload(self, d: Decoder) -> None:
        self.rank = d.s32()
        self.ip = d.string()
        self.port = d.u32()
        self.boot_nonce = (d.u64() if d.remaining_in_frame() >= 8
                           else 0)
