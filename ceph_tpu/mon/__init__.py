"""Cluster control plane (L7): Paxos-replicated map service.

Reference: src/mon/ — Monitor + Paxos (Paxos.cc) + leader election
(Elector.cc) + per-map services (OSDMonitor.cc) + MonClient.  The
OSDMap is the Paxos-committed value; OSDs boot/report-failures through
the mon and everyone subscribes to map updates.
"""

from ceph_tpu.mon.monitor import Monitor, MonMap  # noqa: F401
from ceph_tpu.mon.client import MonClient  # noqa: F401
