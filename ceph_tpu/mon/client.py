"""MonClient — commands, subscriptions, boot/failure reporting.

Reference: src/mon/MonClient.{h,cc}: daemons and clients find the
quorum via the monmap, send commands (retrying toward the leader on
redirect), subscribe to map updates, and (for OSDs) report boot and
peer failures.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ceph_tpu.msg.message import EntityName, Message
from ceph_tpu.core.lockdep import make_lock
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger
from ceph_tpu.mon import messages as mm
from ceph_tpu.mon.monitor import MonMap
from ceph_tpu.osd import map_codec, map_inc

Addr = Tuple[str, int]


class MonClient(Dispatcher):
    """Attaches to an existing Messenger (daemons share one)."""

    def __init__(self, msgr: Messenger, monmap: MonMap) -> None:
        self.msgr = msgr
        self.monmap = monmap
        self._tid = 0
        self._lock = make_lock("monclient")
        self._closed = threading.Event()
        self._waiters: Dict[int, list] = {}
        self.on_osdmap: Optional[Callable] = None
        self.osdmap = None  # the client's current map (inc base)
        self._last_epoch = 0
        msgr.add_dispatcher(self)

    def close(self) -> None:
        """Wake any in-flight command retry loop immediately — both
        the redirect backoff and the per-RPC reply waits; the owning
        daemon shuts the shared messenger itself."""
        self._closed.set()
        with self._lock:
            waiters = list(self._waiters.values())
        for w in waiters:
            w[0].set()  # reply stays None; callers see closed and bail

    # -- dispatch ---------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, (mm.MMonCommandReply, mm.MAuthReply)):
            with self._lock:
                w = self._waiters.get(msg.tid)
            if w is not None:
                w[1] = msg
                w[0].set()
            return True
        if isinstance(msg, mm.MOSDMapMsg):
            # pushes arrive concurrently from every subscribed mon:
            # compare-and-set under the lock so an older epoch can never
            # be delivered after a newer one
            newmap = None
            resub = False
            with self._lock:
                if msg.epoch > self._last_epoch and self.on_osdmap:
                    if msg.data:
                        newmap = map_codec.decode_osdmap(msg.data)
                    elif msg.incs and self.osdmap is not None:
                        try:
                            newmap = self.osdmap
                            for blob in msg.incs:
                                inc = map_inc.Incremental.decode(blob)
                                if inc.epoch <= newmap.epoch:
                                    continue  # another mon's push
                                    # already covered this prefix
                                newmap = inc.apply(newmap)
                        except Exception:
                            newmap = None
                        if newmap is not None \
                                and newmap.epoch <= self._last_epoch:
                            return True  # chain was entirely stale
                    if newmap is not None:
                        self._last_epoch = newmap.epoch
                        self.osdmap = newmap
                    else:
                        # inc chain didn't apply: ask for a full map
                        resub = True
            if newmap is not None:
                self.on_osdmap(newmap)
            elif resub:
                self._resubscribe(since=0)
            return True
        return False

    def _resubscribe(self, since: int) -> None:
        ip, port = self.msgr.addr
        for rank in self.monmap.live_ranks():
            self.msgr.send_message(
                mm.MMonSubscribe(f"osdmap:{ip}:{port}", since),
                self.monmap.addrs[rank])

    # -- commands ---------------------------------------------------------
    def command(self, cmd: dict, timeout: float = 10.0) -> Tuple[int, dict]:
        """Send to rank 0; follow 'not leader' redirects."""
        tries = 0
        rank = 0
        while tries < 2 * self.monmap.size:
            if self._closed.is_set():
                return -108, {"error": "mon client shut down"}
            rep = self._command_to(rank, cmd, timeout / 2)
            if rep is None:
                rank = (rank + 1) % self.monmap.size
                tries += 1
                continue
            if rep.code == -11 and "leader" in rep.out:
                leader = rep.out["leader"]
                rank = leader if leader >= 0 else (
                    (rank + 1) % self.monmap.size)
                tries += 1
                # election settling; interruptible so an owner tearing
                # the messenger down doesn't strand a command retry
                if self._closed.wait(0.2):
                    return -108, {"error": "mon client shut down"}
                continue
            return rep.code, rep.out
        return -110, {"error": "mon command timed out"}

    def _command_to(self, rank: int, cmd: dict,
                    timeout: float) -> Optional[mm.MMonCommandReply]:
        return self._rpc_to(rank, mm.MMonCommand(cmd), timeout)

    # -- authentication ---------------------------------------------------
    def authenticate(self, name: str, secret: bytes,
                     timeout: float = 10.0):
        """Cephx handshake: challenge -> proof -> ticket.  Returns a
        CephxClient whose build_authorizer() feeds Messenger.set_auth
        (reference MonClient's auth phase + CephxClientHandler)."""
        import secrets as _secrets

        from ceph_tpu.auth import AuthError, CephxClient

        cx = CephxClient(name, secret)
        last = "no mon answered"
        for rank in self.monmap.live_ranks():
            rep = self._rpc_to(rank, mm.MAuth(
                mm.MAuth.GET_CHALLENGE, name), timeout / 2)
            if rep is None or rep.result != 0:
                last = f"mon.{rank}: challenge refused"
                continue
            cc = _secrets.token_bytes(16)
            proof = cx.make_proof(rep.challenge, cc)
            rep2 = self._rpc_to(rank, mm.MAuth(
                mm.MAuth.REQUEST, name, cc, proof), timeout / 2)
            if rep2 is None or rep2.result != 0:
                last = f"mon.{rank}: proof rejected"
                continue
            cx.accept_reply(rep2.sealed_client, rep2.ticket_blob)
            return cx
        raise AuthError(f"authentication failed for {name!r}: {last}")

    def _rpc_to(self, rank: int, msg: Message, timeout: float):
        with self._lock:
            self._tid += 1
            tid = self._tid
            ev = threading.Event()
            self._waiters[tid] = [ev, None]
        msg.tid = tid
        self.msgr.send_message(msg, self.monmap.addrs[rank])
        ok = ev.wait(timeout)
        with self._lock:
            w = self._waiters.pop(tid, None)
        return w[1] if ok and w else None

    # -- subscriptions ----------------------------------------------------
    def subscribe_osdmap(self, cb: Callable, since: int = 0,
                         base=None) -> None:
        """cb(OSDMap) fires on every newer committed map.  `base` (the
        caller's current map) seeds the incremental-apply chain so
        pushes after `since` arrive as O(delta) incs."""
        self.on_osdmap = cb
        if base is not None:
            self.osdmap = base
            self._last_epoch = base.epoch
        self._resubscribe(since)

    # -- osd daemon hooks -------------------------------------------------
    def send_boot(self, osd_id: int,
                  hb_addr: Optional[Addr] = None) -> None:
        ip, port = self.msgr.addr
        hb_ip, hb_port = hb_addr if hb_addr else ("", 0)
        for rank in self.monmap.live_ranks():
            self.msgr.send_message(
                mm.MOSDBoot(osd_id, ip, port, hb_ip, hb_port),
                self.monmap.addrs[rank])

    def report_failure(self, target: int, failed_for: float = 0.0) -> None:
        for rank in self.monmap.live_ranks():
            self.msgr.send_message(mm.MOSDFailure(target, failed_for),
                                   self.monmap.addrs[rank])

    def send_pg_stats(self, osd_id: int, epoch: int, pgs: list,
                      used_bytes: int = 0, total_bytes: int = 0,
                      slow_ops: int = 0,
                      heartbeat_misses: int = 0) -> None:
        """MPGStats feed (every mon keeps a transient mgr-style copy).

        ``pgs`` may be rich PGStat rows (osd/types.py) or legacy
        7-tuples; rich rows also populate the legacy field so old
        consumers keep reading the thin shape."""
        stats = [p for p in pgs if hasattr(p, "as_legacy")]
        legacy = [p.as_legacy() if hasattr(p, "as_legacy") else p
                  for p in pgs]
        for rank in self.monmap.live_ranks():
            self.msgr.send_message(
                mm.MPGStats(osd_id, epoch, legacy, used_bytes,
                            total_bytes, stats=stats, slow_ops=slow_ops,
                            heartbeat_misses=heartbeat_misses),
                self.monmap.addrs[rank])
