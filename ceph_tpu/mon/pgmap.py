"""PGMapService — the mon's transient cluster-telemetry digest.

Reference: src/mon/PGMap.{h,cc} + the mgr's MgrStatMonitor role — the
per-OSD MPGStats feed is aggregated into ONE cluster view: per-pool
``df``, pg-state counts, degraded/misplaced/unfound object totals, and
rate-derived client IOPS/BW + recovery objects/s.  Like the reference
PGMap (and unlike every PaxosService), nothing here is paxos-committed:
every mon keeps its own copy fed by the same reports, and a mon restart
simply re-learns the digest from the next report interval.

Rates come from a shared ``core.perf.SnapshotRing`` of cumulative
cluster totals: each ingested report folds its windowed deltas into the
cumulative counters and pushes a snapshot, and ``digest()`` differences
ring endpoints over ``mon_stats_rate_window`` — so `ceph -s`, cephtop's
cluster pane, and the bench telemetry aux (which all read this digest)
agree by construction.  The mgr ProgressModule's ETA deliberately does
NOT use this windowed ring: it divides an event's cumulative recovered
count by elapsed-since-start (a smoother estimator for a monotone
clamp), so its implied rate can differ from the digest's windowed one
during non-constant-rate recovery.

Stuck-PG tracking: every per-PG row carries ``state_since`` — the stamp
of the last observed state CHANGE (not the last report), so
``stuck_pgs()`` can answer "state unchanged past mon_pg_stuck_threshold"
with honest stuck-since evidence.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.core.lockdep import make_lock
from ceph_tpu.core.perf import SnapshotRing
from ceph_tpu.osd.types import PGId, PGStat

# cumulative cluster counters the rate ring tracks: client io folds
# from primary rows only (replica rows describe the same logical io),
# recovery io from EVERY row (it lands on whichever osd did the work —
# pull-based self-recovery or push receipt — and per-osd counters are
# disjoint, so a recovering replica's rate must not be dropped)
_CLIENT_KEYS = ("cl_wr_ops", "cl_wr_bytes", "cl_rd_ops", "cl_rd_bytes")
_REC_KEYS = ("rec_ops", "rec_bytes")
_RATE_KEYS = _CLIENT_KEYS + _REC_KEYS


class _OsdReport:
    """Latest report from one OSD (stamp + rich rows + health signals)."""

    __slots__ = ("stamp", "epoch", "stats", "used", "total", "slow_ops",
                 "heartbeat_misses", "prev_heartbeat_misses")

    def __init__(self) -> None:
        self.stamp = 0.0
        self.epoch = 0
        self.stats: List[PGStat] = []
        self.used = 0
        self.total = 0
        self.slow_ops = 0
        self.heartbeat_misses = 0
        self.prev_heartbeat_misses = 0


class PGMapService:
    """Aggregates MPGStats reports; serves the `ceph -s`/`df`/health
    digest.  Thread-safe: ingest runs on the mon's dispatch path,
    digest() on command threads."""

    def __init__(self, conf, now_fn=time.time, pool_size_fn=None,
                 osd_up_fn=None) -> None:
        self.conf = conf
        self._now = now_fn
        # pool_id -> replica width (replicated size / EC k+m), from the
        # owning mon's pool table: degraded counts missing COPIES, so
        # the ratio's denominator must be objects x width, not objects
        self._pool_size = pool_size_fn
        # osd -> is the map's view of it UP?  A down-marked osd's last
        # report stays "fresh" for up to stale_s, but its testimony is
        # void: its own missing-set became acting-set holes the primary
        # now counts, and summing both would double-count the debt for
        # the whole staleness window
        self._osd_up = osd_up_fn
        self._lock = make_lock("mon.pgmap")
        self.reports: Dict[int, _OsdReport] = {}
        # pgid -> {stat, reported_by, stamp, state_since}: the
        # primary's row wins; replicas only fill gaps
        self.pg: Dict[PGId, dict] = {}
        # cumulative cluster io totals + the rate ring over them
        self._totals = {k: 0 for k in _RATE_KEYS}
        self.ring = SnapshotRing(capacity=256)

    # -- feed -------------------------------------------------------------
    def ingest(self, osd: int, epoch: int, stats: List[PGStat],
               used: int, total: int, slow_ops: int = 0,
               heartbeat_misses: int = 0,
               stamp: Optional[float] = None) -> None:
        now = self._now() if stamp is None else stamp
        with self._lock:
            rep = self.reports.get(osd)
            if rep is None:
                rep = self.reports[osd] = _OsdReport()
                # first report: the cumulative counter's history is not
                # growth — a mon restart/failover must not read every
                # past miss as a live OSD_SLOW_HEARTBEAT
                rep.heartbeat_misses = heartbeat_misses
            rep.prev_heartbeat_misses = rep.heartbeat_misses
            rep.stamp = now
            rep.epoch = epoch
            rep.stats = list(stats)
            rep.used, rep.total = used, total
            rep.slow_ops = slow_ops
            rep.heartbeat_misses = heartbeat_misses
            for s in stats:
                row = self.pg.get(s.pgid)
                if row is None or s.primary or (
                        not row["stat"].primary
                        and row["reported_by"] == osd):
                    since = now
                    if row is not None and row["stat"].state == s.state:
                        since = row["state_since"]
                    self.pg[s.pgid] = {"stat": s, "reported_by": osd,
                                       "stamp": now,
                                       "state_since": since}
                if s.primary:
                    for k in _CLIENT_KEYS:
                        self._totals[k] += getattr(s, k)
                for k in _REC_KEYS:
                    self._totals[k] += getattr(s, k)
            self.ring.push(dict(self._totals), stamp=now)

    # -- views ------------------------------------------------------------
    def _up(self, osd: int) -> bool:
        """The map's view of a reporter; True when no osd_up_fn is
        wired (standalone/test construction keeps old semantics)."""
        if self._osd_up is None:
            return True
        try:
            return bool(self._osd_up(osd))
        except Exception:
            return True

    def _fresh_rows(self, now: float, stale_s: float) -> List[dict]:
        return [row for row in self.pg.values()
                if now - row["stamp"] <= stale_s]

    def digest(self) -> dict:
        """The PGMap digest behind `ceph -s` / `ceph df` / the
        Prometheus cluster gauges."""
        now = self._now()
        stale_s = float(self.conf.get("mon_pg_stats_stale_s"))
        window = float(self.conf.get("mon_stats_rate_window"))
        with self._lock:
            rows = self._fresh_rows(now, stale_s)
            pg_states: Dict[str, int] = {}
            pools: Dict[int, dict] = {}
            tot = {"objects": 0, "bytes": 0, "degraded": 0,
                   "misplaced": 0, "unfound": 0, "log_entries": 0,
                   "scrub_errors": 0}
            damaged_pgs = 0
            for row in rows:
                s: PGStat = row["stat"]
                if not s.primary:
                    continue
                if s.scrub_errors:
                    tot["scrub_errors"] += s.scrub_errors
                    damaged_pgs += 1
                pg_states[s.state] = pg_states.get(s.state, 0) + 1
                pool = pools.setdefault(
                    s.pgid[0], {"objects": 0, "bytes": 0, "degraded": 0,
                                "misplaced": 0, "unfound": 0, "pgs": 0})
                pool["objects"] += s.num_objects
                pool["bytes"] += s.num_bytes
                pool["misplaced"] += s.misplaced
                pool["unfound"] += s.unfound
                pool["pgs"] += 1
                tot["objects"] += s.num_objects
                tot["bytes"] += s.num_bytes
                tot["misplaced"] += s.misplaced
                tot["unfound"] += s.unfound
                tot["log_entries"] += s.log_size
            # degraded sums over EVERY fresh live reporter's rows, NOT
            # the primary-wins map: after a revive the missing copies
            # live in the recovering REPLICA's own pg.missing, which
            # only its non-primary row carries (the primary reads
            # holes=0 the moment the peer is back up).  The osd-side
            # formula keeps live rows disjoint — only the primary
            # counts acting-set holes, every row counts only its OWN
            # missing — and down-marked reporters are skipped (their
            # missing became the holes the primary already counts).
            for osd, r in self.reports.items():
                if now - r.stamp > stale_s or not self._up(osd):
                    continue
                for s in r.stats:
                    if s.degraded:
                        tot["degraded"] += s.degraded
                        pools.setdefault(
                            s.pgid[0],
                            {"objects": 0, "bytes": 0, "degraded": 0,
                             "misplaced": 0, "unfound": 0, "pgs": 0}
                        )["degraded"] += s.degraded
            # fullness from fresh live reporters only: a dead osd's
            # capacity is gone, and its last statfs must not inflate
            # cluster totals for the whole staleness window (let alone
            # forever — reports are never pruned)
            used = sum(r.used for osd, r in self.reports.items()
                       if now - r.stamp <= stale_s and self._up(osd))
            total = sum(r.total for osd, r in self.reports.items()
                        if now - r.stamp <= stale_s and self._up(osd))
            slow = {osd: r.slow_ops for osd, r in self.reports.items()
                    if r.slow_ops and now - r.stamp <= stale_s}
        # degraded counts missing COPIES (n*holes per PG), so the ratio
        # denominator is objects x pool width; without a pool table the
        # width defaults to 1 and the ratio clamps at 1.0 rather than
        # report >100% damage
        copies = 0
        for pid, pool in pools.items():
            width = 1
            if self._pool_size is not None:
                width = self._pool_size(pid) or 1
            copies += pool["objects"] * width
        return {
            "pg_states": dict(sorted(pg_states.items())),
            "num_pgs": sum(pg_states.values()),
            "pools": pools,
            "objects": tot["objects"],
            "bytes": tot["bytes"],
            "pg_log_entries": tot["log_entries"],
            "degraded_objects": tot["degraded"],
            "total_copies": copies,
            "degraded_ratio": round(
                min(1.0, tot["degraded"] / (copies or 1)), 4),
            "misplaced_objects": tot["misplaced"],
            "unfound_objects": tot["unfound"],
            # scrub damage attribution (primary rows): inconsistent
            # objects the latest scrubs left unrepaired -> PG_DAMAGED
            "scrub_errors": tot["scrub_errors"],
            "damaged_pgs": damaged_pgs,
            "used_bytes": used,
            "total_bytes": total,
            "slow_ops": slow,
            "io": {
                "client_read_ops_per_s": round(
                    self.ring.rate("cl_rd_ops", window, now=now), 2),
                "client_write_ops_per_s": round(
                    self.ring.rate("cl_wr_ops", window, now=now), 2),
                "client_read_bytes_per_s": round(
                    self.ring.rate("cl_rd_bytes", window, now=now), 1),
                "client_write_bytes_per_s": round(
                    self.ring.rate("cl_wr_bytes", window, now=now), 1),
                "recovery_objects_per_s": round(
                    self.ring.rate("rec_ops", window, now=now), 2),
                "recovery_bytes_per_s": round(
                    self.ring.rate("rec_bytes", window, now=now), 1),
            },
        }

    def pg_rows(self, fresh_only: bool = False) -> List[dict]:
        """Rich `pg dump` rows (primary-reported rows win).  With
        ``fresh_only`` rows past mon_pg_stats_stale_s are dropped — the
        same filter digest() applies, so health-check DETAIL built from
        these rows names the same PG set the summaries count.

        A row's ``degraded`` is the CROSS-REPORT sum for that pg (same
        disjoint-rows derivation as digest()): the winning primary row
        reads holes=0 the moment a dead peer is marked up, while the
        revived replica's catch-up debt lives in its own non-primary
        row — a consumer watching one row (the mgr ProgressModule's
        recovery events, `pg dump`) must not see the debt vanish at
        revive and declare recovery complete while objects are still
        being pulled."""
        now = self._now()
        stale_s = float(self.conf.get("mon_pg_stats_stale_s"))
        with self._lock:
            deg_by_pg: Dict[PGId, int] = {}
            for osd, r in self.reports.items():
                if now - r.stamp > stale_s or not self._up(osd):
                    continue
                for s in r.stats:
                    deg_by_pg[s.pgid] = \
                        deg_by_pg.get(s.pgid, 0) + s.degraded
            out = []
            for pgid in sorted(self.pg):
                row = self.pg[pgid]
                if fresh_only and now - row["stamp"] > stale_s:
                    continue
                s: PGStat = row["stat"]
                out.append({
                    "pgid": f"{pgid[0]}.{pgid[1]}",
                    "state": s.state,
                    "num_objects": s.num_objects,
                    "num_bytes": s.num_bytes,
                    "log_size": s.log_size,
                    # cross-report sum; the winning row's own value
                    # only when every reporter went stale/down
                    "degraded": deg_by_pg.get(pgid, s.degraded),
                    "misplaced": s.misplaced,
                    "unfound": s.unfound,
                    "last_update": [s.last_update.epoch,
                                    s.last_update.version],
                    "reported_by": row["reported_by"],
                    "primary": s.primary,
                    "state_since": row["state_since"],
                    "scrub_errors": s.scrub_errors,
                    "last_scrub": s.last_scrub,
                    "last_deep_scrub": s.last_deep_scrub,
                })
            return out

    def not_deep_scrubbed(self, warn_age_s: Optional[float] = None
                          ) -> List[dict]:
        """Primary PGs whose last deep scrub is older than the warn
        age (never-deep-scrubbed stamps read as infinitely old).
        Empty when the check is disabled (warn age <= 0, the conf
        default) — always-on deep scrub is the OSD scheduler's job;
        this is the mon-side evidence it actually ran."""
        if warn_age_s is None:
            warn_age_s = float(self.conf.get(
                "mon_warn_not_deep_scrubbed_s"))
        if warn_age_s <= 0:
            return []
        now = self._now()
        stale_s = float(self.conf.get("mon_pg_stats_stale_s"))
        with self._lock:
            out = []
            for pgid in sorted(self.pg):
                row = self.pg[pgid]
                s: PGStat = row["stat"]
                if not s.primary or now - row["stamp"] > stale_s:
                    continue
                if now - s.last_deep_scrub >= warn_age_s:
                    out.append({
                        "pgid": f"{pgid[0]}.{pgid[1]}",
                        "last_deep_scrub": s.last_deep_scrub,
                        "age_s": round(
                            now - s.last_deep_scrub, 1)
                        if s.last_deep_scrub else None,
                    })
            return out

    def stuck_pgs(self, threshold_s: Optional[float] = None) -> List[dict]:
        """PGs sitting in a non-active state past the stuck threshold,
        with honest stuck-since stamps (state-CHANGE tracked, not
        last-report)."""
        if threshold_s is None:
            threshold_s = float(self.conf.get("mon_pg_stuck_threshold"))
        now = self._now()
        stale_s = float(self.conf.get("mon_pg_stats_stale_s"))
        with self._lock:
            out = []
            for pgid in sorted(self.pg):
                row = self.pg[pgid]
                s: PGStat = row["stat"]
                if now - row["stamp"] > stale_s:
                    continue  # stale reporters get MON_STALE_PG_REPORTS
                if s.state.startswith("active"):
                    # active+degraded/+recovering serve client io — a
                    # long recovery is PG_DEGRADED/OBJECT_DEGRADED's
                    # story, not "stuck in a non-active state"
                    continue
                stuck_for = now - row["state_since"]
                if stuck_for >= threshold_s:
                    out.append({"pgid": f"{pgid[0]}.{pgid[1]}",
                                "state": s.state,
                                "stuck_for_s": round(stuck_for, 1)})
            return out

    def stale_osds(self, live_osds, stale_s: Optional[float] = None
                   ) -> List[Tuple[int, float]]:
        """Up OSDs whose reports went stale: (osd, seconds since the
        last report).  An osd that NEVER reported doesn't count — it
        may still be booting; the map's down-marking owns that case."""
        if stale_s is None:
            stale_s = float(self.conf.get("mon_pg_stats_stale_s"))
        now = self._now()
        with self._lock:
            out = []
            for osd in live_osds:
                rep = self.reports.get(osd)
                if rep is not None and rep.stamp and \
                        now - rep.stamp > stale_s:
                    out.append((osd, round(now - rep.stamp, 1)))
            return out

    def slow_heartbeat_osds(self) -> List[int]:
        """OSDs whose heartbeat-miss counter grew between their two
        most recent reports (the PR-7 heartbeat_misses feed): live
        evidence of peers starving heartbeats right now, not a stale
        historical total."""
        now = self._now()
        stale_s = float(self.conf.get("mon_pg_stats_stale_s"))
        with self._lock:
            return sorted(
                osd for osd, r in self.reports.items()
                if now - r.stamp <= stale_s
                and r.heartbeat_misses > r.prev_heartbeat_misses)
