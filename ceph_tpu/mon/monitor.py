"""Monitor: leader election + multi-instance Paxos + OSDMonitor service.

Reference: src/mon/Monitor.{h,cc}, Elector.cc (rank-deference election),
Paxos.cc (leader-driven collect/begin/accept/commit with unique proposal
numbers), OSDMonitor.cc (osdmap mutations: boot, failure reports with
min-reporter counting per prepare_failure :2643 / check_failure :2537,
down→out aging, pool + EC-profile commands), MonitorDBStore.h (the
paxos log lives in a local KV).

Shape kept: the elected leader serializes all map mutations through
Paxos; every committed version is a full encoded OSDMap (incremental
deltas are a later optimization); all mons push committed maps to their
subscribers, so clients may subscribe anywhere while only the leader
accepts mutations.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.crush import map as cmap
from ceph_tpu.msg.message import EntityName, Message
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger
from ceph_tpu.mon import messages as mm
from ceph_tpu.osd import map_codec, map_inc
from ceph_tpu.osd.osdmap import OSDMap, PGPool, POOL_ERASURE, POOL_REPLICATED
from ceph_tpu.store.kv import LogKV, MemDB, WriteBatch

Addr = Tuple[str, int]

# commit a full map (not a delta) every Nth epoch: a replay anchor that
# bounds incremental chains (reference: OSDMonitor's periodic full_X)
FULL_EVERY = 32

STATE_ELECTING = "electing"
STATE_LEADER = "leader"
STATE_PEON = "peon"


class MonMap:
    """Versioned mon roster: rank -> address (reference MonMap).
    Mutations go through the MonmapMonitor paxos service, which
    REPLACES a monitor's monmap rather than mutating a (possibly
    shared) instance."""

    def __init__(self, addrs: List[Optional[Addr]], epoch: int = 1) -> None:
        # a removed rank leaves a None HOLE: ranks are identity (baked
        # into entity names and running sessions), so they never shift
        self.addrs = [tuple(a) if a is not None else None for a in addrs]
        self.epoch = epoch

    @property
    def size(self) -> int:
        return len(self.addrs)  # rank slots, incl. holes

    def live_ranks(self) -> List[int]:
        return [r for r, a in enumerate(self.addrs) if a is not None]

    def quorum(self) -> int:
        return len(self.live_ranks()) // 2 + 1

    def to_dict(self) -> dict:
        return {"epoch": self.epoch,
                "addrs": [list(a) if a is not None else None
                          for a in self.addrs]}

    @classmethod
    def from_dict(cls, d: dict) -> "MonMap":
        return cls([tuple(a) if a is not None else None
                    for a in d["addrs"]], epoch=d["epoch"])

    def with_added(self, addr: Addr) -> "MonMap":
        return MonMap(self.addrs + [tuple(addr)], epoch=self.epoch + 1)

    def with_removed(self, rank: int) -> "MonMap":
        addrs = list(self.addrs)
        addrs[rank] = None
        return MonMap(addrs, epoch=self.epoch + 1)


class Monitor(Dispatcher):
    def __init__(self, ctx, rank: int, monmap: MonMap,
                 kv=None, initial_map: Optional[OSDMap] = None,
                 bind_port: int = 0, keyring=None) -> None:
        self.ctx = ctx
        self.rank = rank
        self.monmap = monmap
        # cephx auth service (reference AuthMonitor/CephxServiceHandler):
        # active when a keyring is provided; the MAuth exchange itself
        # rides unauthenticated mon connections (as in the reference's
        # connection-negotiation phase)
        self.auth_server = None
        if keyring is not None:
            from ceph_tpu.auth import CephxServer

            self.auth_server = CephxServer(keyring)
        self.kv = kv if kv is not None else MemDB()
        self.msgr = Messenger(ctx, EntityName("mon", rank),
                              bind_port=bind_port)
        self.msgr.add_dispatcher(self)
        if self.auth_server is not None:
            # the mon's own dial-backs (map pushes to daemons/clients)
            # carry a self-minted ticket verifiable by the service key
            self.msgr.set_auth(
                provider=lambda target="": self.auth_server.mint_authorizer(
                    f"mon.{rank}", target=target))
        self._log = ctx.log.dout("mon")
        self._plog = ctx.log.dout("paxos")
        from ceph_tpu.core.lockdep import make_lock

        self.lock = make_lock(f"mon{rank}")

        # election state
        self.state = STATE_ELECTING
        self.election_epoch = 0
        self.leader = -1
        self._acks: Set[int] = set()
        self._last_lease = time.monotonic()

        # paxos state (persisted)
        self.last_pn = 0
        self.accepted_pn = 0
        self.last_committed = 0
        self.uncommitted: Optional[Tuple[int, int, bytes]] = None
        self._accept_votes: Dict[int, Set[int]] = {}
        self._collect_acks: Dict[int, mm.MMonPaxos] = {}  # peon rank -> LAST
        self._collect_pn = 0          # pn of the in-flight collect round
        self._collect_complete = True  # no collect in flight
        self._proposing = False
        self._propose_queue: List[bytes] = []

        # osdmonitor state
        self.osdmap = initial_map
        # transient per-OSD PG stats (mgr-style, NOT paxos-committed;
        # reference: the MPGStats feed behind `ceph pg dump`)
        self.pg_stats: Dict[int, Tuple[float, list]] = {}
        self.osd_fullness: Dict[int, Tuple[int, int]] = {}
        # the PGMap digest (reference PGMap/MgrStatMonitor role):
        # aggregates the rich PGStat rows into per-pool df, pg-state
        # counts, degraded totals, and rate-derived io numbers —
        # transient like pg_stats, re-learned from the next reports
        from ceph_tpu.mon.pgmap import PGMapService

        def _pool_size(pid: int) -> Optional[int]:
            m = self.osdmap
            p = m.pools.get(pid) if m is not None else None
            return p.size if p is not None else None

        def _osd_up(osd: int) -> bool:
            m = self.osdmap
            return bool(m is not None and 0 <= osd < m.max_osd
                        and m.is_up(osd))

        self.pgmap = PGMapService(ctx.conf, pool_size_fn=_pool_size,
                                  osd_up_fn=_osd_up)
        self.failure_reports: Dict[int, Dict[int, float]] = {}
        self.down_stamp: Dict[int, float] = {}
        self.subscribers: Dict[Addr, int] = {}  # addr -> last epoch sent
        # epoch -> (prev_epoch, inc bytes): the window subscribers can be
        # caught up from with O(delta) pushes
        self._recent_incs: Dict[int, Tuple[int, bytes]] = {}
        self.ec_profiles: Dict[str, str] = {
            "default": "plugin=isa k=2 m=1 technique=reed_sol_van",
        }

        # PaxosService family (reference src/mon/PaxosService.h):
        # Config/Log/Health/Auth monitors multiplexed onto this paxos
        from ceph_tpu.mon import services as mon_services

        self.services = mon_services.build_services(self)

        # mutations accumulate into ONE pending map (the reference's
        # pending_inc): concurrent boots/failures/commands each cloning
        # the committed map would otherwise clobber each other
        self._pending_map: Optional[OSDMap] = None
        self._pending_crush: bytes = b""  # cached crush encoding
        self._stop = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self.kv.open()
        # boot load holds the mon lock: the paxos counters it seeds
        # are guarded state everywhere else, and the tick/election
        # threads start a few lines down
        with self.lock:
            self._load()
        self.msgr.start()
        self._tick_thread = threading.Thread(
            target=self._tick_loop, daemon=True, name=f"mon{self.rank}-tick")
        self._tick_thread.start()
        if self.ctx.admin is not None:
            # cluster pane for tools/cephtop.py --cluster: the `ceph
            # -s` digest + health over the admin socket, per-rank
            # prefixed like the per-daemon osd.N commands
            self.ctx.admin.register(
                f"mon.{self.rank} status", self._admin_status,
                "health + PGMap digest (the `ceph -s` payload)")
        self.start_election()

    def _admin_status(self, cmd: dict) -> dict:
        status, checks = self.services["health"].gather()
        return {"health": status,
                "checks": {k: v.get("summary", "") for k, v in
                           sorted(checks.items())},
                "digest": self.pgmap.digest()}

    def shutdown(self) -> None:
        self._stop.set()
        if self._tick_thread:
            self._tick_thread.join(timeout=5)
        self.msgr.shutdown()
        self.kv.close()

    @property
    def addr(self) -> Addr:
        return self.msgr.addr

    def _peers(self) -> List[int]:
        return [r for r in self.monmap.live_ranks() if r != self.rank]

    def _send_mon(self, rank: int, msg: Message) -> None:
        addr = (self.monmap.addrs[rank]
                if rank < self.monmap.size else None)
        if addr is None:
            return  # removed rank (monmap hole)
        self.msgr.send_message(msg, addr)

    # -- persistence ------------------------------------------------------
    def _load(self) -> None:
        pn = self.kv.get("paxos", "last_pn")
        self.last_pn = int(pn) if pn else 0
        ap = self.kv.get("paxos", "accepted_pn")
        self.accepted_pn = int(ap) if ap else 0
        lc = self.kv.get("paxos", "last_committed")
        self.last_committed = int(lc) if lc else 0
        if self.last_committed:
            # latest_full is only written at FULL anchors (writing the
            # O(cluster) image every commit would defeat the O(delta)
            # commit path); boot = anchor + replay of the committed
            # incrementals since it
            full = self.kv.get("mon", "latest_full")
            fv = self.kv.get("mon", "latest_full_v")
            if full:
                self.osdmap = map_codec.decode_osdmap(full)
            start = int(fv) if fv else 0
            from ceph_tpu.mon.services import SVC_TAG

            # track how far replay actually got: the boot anchor below
            # must never claim versions it did not fold in
            self._replayed_v = start
            for v in range(start + 1, self.last_committed + 1):
                data = self.kv.get("paxos_values", str(v))
                if not data:
                    self._replayed_v = v
                    continue
                if data[0] == SVC_TAG:
                    self._replayed_v = v
                    continue  # service state reloads from its own kv rows
                try:
                    newmap = map_inc.decode_value(data, self.osdmap)
                    if (self.osdmap is None
                            or newmap.epoch > self.osdmap.epoch):
                        self.osdmap = newmap
                    self._replayed_v = v
                except map_inc.NeedFullMap:
                    break  # stale base: catch up from peers once live
                except Exception:
                    self._replayed_v = v
                    continue  # pre-framing legacy value
        # restore an accepted-but-uncommitted proposal: our promise must
        # survive restart or a new leader's collect can miss a value the
        # old leader already committed elsewhere (Paxos.cc handle_collect
        # sharing uncommitted state)
        upn = self.kv.get("paxos", "uncommitted_pn")
        uv = self.kv.get("paxos", "uncommitted_v")
        uval = self.kv.get("paxos", "uncommitted_value")
        if upn and uv and uval is not None and int(uv) > self.last_committed:
            self.uncommitted = (int(upn), int(uv), uval)
        prof = self.kv.get("mon", "ec_profiles")
        if prof:
            self.ec_profiles = json.loads(prof.decode())
        for svc in self.services.values():
            svc.load()
        if self.osdmap is not None and not self.kv.get("mon",
                                                       "latest_full"):
            # anchor the boot image: every later commit may be an
            # incremental, and incrementals replay on top of an anchor
            # — without this a FULL-quorum restart of a cluster that
            # only ever committed deltas loses the osdmap entirely
            # (no peer has a base to serve CATCHUP from).  Stamped
            # with the version replay actually REACHED (stamping
            # last_committed after a partial replay would permanently
            # skip the unapplied tail on every later boot).
            b = WriteBatch()
            b.set("mon", "latest_full", map_codec.encode_osdmap(
                self.osdmap))
            b.set("mon", "latest_full_v",
                  str(getattr(self, "_replayed_v", 0)).encode())
            self.kv.submit(b)

    def _persist(self, **kv_updates) -> None:
        b = WriteBatch()
        for key, val in kv_updates.items():
            if isinstance(val, bytes):
                b.set("paxos", key, val)
            else:
                b.set("paxos", key, str(val).encode())
        self.kv.submit(b)

    def _persist_value(self, version: int, value: bytes,
                       clear_uncommitted: bool = True,
                       extra: Optional[WriteBatch] = None) -> None:
        b = WriteBatch()
        if extra is not None:
            b.ops.extend(extra.ops)
        b.set("paxos_values", str(version), value)
        b.set("paxos", "last_committed", str(version).encode())
        if clear_uncommitted:
            # the promise is fulfilled; drop it so a restart doesn't
            # resurrect it
            b.rmkey("paxos", "uncommitted_pn")
            b.rmkey("paxos", "uncommitted_v")
            b.rmkey("paxos", "uncommitted_value")
        self.kv.submit(b)

    # -- election (Elector.cc shape) --------------------------------------
    def start_election(self) -> None:
        with self.lock:
            self.state = STATE_ELECTING
            self.election_epoch += 1
            self.leader = -1
            self._acks = {self.rank}
            epoch = self.election_epoch
        for r in self._peers():
            self._send_mon(r, mm.MMonElection(
                mm.MMonElection.PROPOSE, epoch, self.rank))
        # single-mon cluster wins immediately
        self._maybe_win()
        self._timer(1.0, self._election_timeout, epoch)

    def _timer(self, delay: float, fn, *args) -> None:
        t = threading.Timer(delay, fn, args=args)
        t.daemon = True  # never pin the process on a pending retry
        t.start()

    def _election_timeout(self, epoch: int) -> None:
        with self.lock:
            if (self.state == STATE_ELECTING
                    and self.election_epoch == epoch
                    and not self._stop.is_set()):
                pass  # retry
            else:
                return
        self._maybe_win(force_retry=True)

    def _maybe_win(self, force_retry: bool = False) -> None:
        with self.lock:
            if self.state != STATE_ELECTING:
                return
            if len(self._acks) >= self.monmap.quorum():
                self.state = STATE_LEADER
                self.leader = self.rank
                epoch = self.election_epoch
            elif force_retry:
                self.lock.release()
                try:
                    self.start_election()
                finally:
                    self.lock.acquire()
                return
            else:
                return
        self._log(1, f"mon.{self.rank} won election e{epoch}")
        for r in self._peers():
            self._send_mon(r, mm.MMonElection(
                mm.MMonElection.VICTORY, epoch, self.rank))
        self._leader_collect()

    def _handle_election(self, conn: Connection, msg: mm.MMonElection) -> None:
        restart = False
        with self.lock:
            if msg.op == mm.MMonElection.PROPOSE:
                if msg.rank < self.rank:
                    # deference: lower rank outranks us
                    if msg.epoch > self.election_epoch:
                        self.election_epoch = msg.epoch
                    self.state = STATE_ELECTING
                    ack = mm.MMonElection(mm.MMonElection.ACK,
                                          msg.epoch, self.rank)
                    self._send_mon(msg.rank, ack)
                else:
                    # we outrank the proposer: assert ourselves with a
                    # fresher epoch (reference Elector nag)
                    if self.state != STATE_ELECTING or (
                        msg.epoch >= self.election_epoch
                    ):
                        self.election_epoch = max(self.election_epoch,
                                                  msg.epoch)
                        restart = True
                if restart:
                    pass
            elif msg.op == mm.MMonElection.ACK:
                win = False
                if (self.state == STATE_ELECTING
                        and msg.epoch == self.election_epoch):
                    self._acks.add(msg.rank)
                    win = len(self._acks) >= self.monmap.quorum()
                if win:
                    self.lock.release()
                    try:
                        self._maybe_win()
                    finally:
                        self.lock.acquire()
                return
            elif msg.op == mm.MMonElection.VICTORY:
                if msg.rank > self.rank:
                    # refuse a worse leader: crossed victories in the
                    # first round otherwise leave the cluster split on
                    # a higher-ranked winner — re-assert with a newer
                    # epoch so the usurper stands down and acks us
                    self.election_epoch = max(self.election_epoch,
                                              msg.epoch)
                    restart = True
                else:
                    self.state = STATE_PEON
                    self.leader = msg.rank
                    self.election_epoch = max(self.election_epoch, msg.epoch)
                    self._last_lease = time.monotonic()
                    self._proposing = False
                    self._accept_votes.clear()
                    self._propose_queue.clear()
        if restart:
            self.start_election()

    # -- paxos ------------------------------------------------------------
    def _new_pn(self) -> int:
        self.last_pn = ((self.last_pn // 100) + 1) * 100 + self.rank
        self._persist(last_pn=self.last_pn)
        return self.last_pn

    def _leader_collect(self) -> None:
        """Phase 1 after winning: learn peons' state, recover in-flight
        proposals (Paxos.cc collect).  Phase 2 is gated on LAST acks from
        a full quorum (counting self) — proceeding with fewer can propose
        over a value an unreached peon already accepted (Paxos.cc
        handle_last's num_last accounting)."""
        with self.lock:
            if self.state != STATE_LEADER:
                return
            pn = self._new_pn()
            self.accepted_pn = pn
            self._persist(accepted_pn=pn)
            self._collect_acks = {}
            self._collect_pn = pn
            self._collect_complete = False
            # a proposal in flight when the election interrupted us is
            # dead; recovery happens via the collect phase (uncommitted
            # re-propose), so reset the pipeline or it wedges forever
            self._proposing = False
            self._accept_votes.clear()
            msg = mm.MMonPaxos(mm.MMonPaxos.COLLECT, pn,
                               last_committed=self.last_committed)
        for r in self._peers():
            self._send_mon(r, msg)
        # a single-mon quorum (just us) proceeds immediately
        self._maybe_collect_done()
        self._timer(1.0, self._collect_timeout, pn)

    def _collect_timeout(self, pn: int) -> None:
        with self.lock:
            if (self.state != STATE_LEADER or self._collect_complete
                    or self._collect_pn != pn or self._stop.is_set()):
                return
        self._plog(1, "collect quorum timeout; retrying with fresh pn")
        self._leader_collect()

    def _maybe_collect_done(self) -> None:
        with self.lock:
            if self.state != STATE_LEADER or self._collect_complete:
                return
            acks = list(self._collect_acks.values())
            # NACK: a peon promised a higher pn than ours — re-collect
            # with a fresh pn above it
            top = max((a.pn for a in acks), default=0)
            if top > self.accepted_pn:
                self.last_pn = max(self.last_pn, top)
                self._persist(last_pn=self.last_pn)
                self._collect_complete = True
                retry = True
            elif len(acks) + 1 >= self.monmap.quorum():
                self._collect_complete = True
                retry = False
            else:
                return  # keep waiting for more LASTs
        if retry:
            self._leader_collect()
            return
        with self.lock:
            # adopt the newest uncommitted value from the quorum
            best = None
            for a in acks:
                if a.uncommitted_v and a.uncommitted_v > self.last_committed:
                    if best is None or a.uncommitted_pn > best.uncommitted_pn:
                        best = a
            if self.uncommitted and (
                self.uncommitted[1] > self.last_committed
            ) and (best is None
                   or self.uncommitted[0] >= best.uncommitted_pn):
                redo = self.uncommitted[2]
            elif best is not None:
                redo = best.uncommitted_value
            else:
                redo = None
        if redo is not None:
            self._log(1, "re-proposing uncommitted value after election")
            self.propose(redo)
        else:
            self._pump_proposals()

    def _handle_paxos(self, conn: Connection, msg: mm.MMonPaxos) -> None:
        op = msg.op
        if op == mm.MMonPaxos.COLLECT:
            with self.lock:
                if msg.pn > self.accepted_pn:
                    self.accepted_pn = msg.pn
                    self._persist(accepted_pn=msg.pn)
                # remember the highest pn ever seen so a future election
                # on THIS mon starts above it (else a new leader's pn can
                # undercut the old one's and every BEGIN is ignored)
                if msg.pn > self.last_pn:
                    self.last_pn = msg.pn
                    self._persist(last_pn=self.last_pn)
                # reply carries OUR accepted_pn: if it exceeds msg.pn the
                # collector learns its pn is stale (classic NACK)
                rep = mm.MMonPaxos(
                    mm.MMonPaxos.LAST, self.accepted_pn,
                    last_committed=self.last_committed)
                if self.uncommitted:
                    rep.uncommitted_pn = self.uncommitted[0]
                    rep.uncommitted_v = self.uncommitted[1]
                    rep.uncommitted_value = self.uncommitted[2]
                # help a behind leader catch up
                if msg.last_committed < self.last_committed:
                    data = self.kv.get("paxos_values",
                                       str(self.last_committed))
                    rep.version = self.last_committed
                    rep.value = data or b""
            conn.send(rep)
            return
        if op == mm.MMonPaxos.LAST:
            with self.lock:
                if self.state != STATE_LEADER or self._collect_complete:
                    return  # stale ack from a finished/abandoned round
                if msg.version > self.last_committed and msg.value:
                    self._learn(msg.version, msg.value)
                # ignore leftovers of an older collect (their pn is below
                # the round's); key by rank so resends don't double-count
                if msg.pn >= self._collect_pn:
                    rank = msg.src.num if msg.src else -1
                    self._collect_acks[rank] = msg
            self._maybe_collect_done()
            return
        if op == mm.MMonPaxos.BEGIN:
            with self.lock:
                if msg.pn > self.last_pn:
                    self.last_pn = msg.pn
                    self._persist(last_pn=self.last_pn)
                if msg.pn < self.accepted_pn:
                    return  # stale proposer
                self.uncommitted = (msg.pn, msg.version, msg.value)
                self._persist(uncommitted_pn=msg.pn,
                              uncommitted_v=msg.version,
                              uncommitted_value=msg.value)
                rep = mm.MMonPaxos(mm.MMonPaxos.ACCEPT, msg.pn,
                                   version=msg.version)
            conn.send(rep)
            return
        if op == mm.MMonPaxos.ACCEPT:
            fire = False
            with self.lock:
                votes = self._accept_votes.get(msg.version)
                if votes is not None:
                    votes.add(msg.src.num if msg.src else -1)
                    if len(votes) >= self.monmap.quorum():
                        del self._accept_votes[msg.version]
                        fire = True
            if fire:
                self._commit(msg.version)
            return
        if op == mm.MMonPaxos.COMMIT:
            with self.lock:
                if msg.version > self.last_committed:
                    self._learn(msg.version, msg.value)
            self._push_maps()
            return
        if op == mm.MMonPaxos.LEASE:
            with self.lock:
                self._last_lease = time.monotonic()
                if msg.version > self.last_committed and msg.value:
                    self._learn(msg.version, msg.value)
            return
        if op == mm.MMonPaxos.CATCHUP_REQ:
            # a peer learned an incremental it has no base for: hand it
            # the full current map (the reference's store-sync role)
            with self.lock:
                if self.osdmap is None:
                    return
                rep = mm.MMonPaxos(
                    mm.MMonPaxos.CATCHUP, self.accepted_pn,
                    version=self.last_committed,
                    value=map_inc.encode_full_value(self.osdmap))
            conn.send(rep)
            return
        if op == mm.MMonPaxos.CATCHUP:
            with self.lock:
                if msg.value:
                    try:
                        newmap = map_inc.decode_value(msg.value, None)
                    except Exception:
                        return
                    if (self.osdmap is None
                            or newmap.epoch > self.osdmap.epoch):
                        self._adopt_map(newmap, msg.value, msg.version)
            self._push_maps()
            return
        if op == mm.MMonPaxos.SYNC_REQ:
            # full-store-sync role (reference Monitor::sync_*): a mon
            # that jumped a paxos gap pulls every service's state
            with self.lock:
                snap = {name: s for name, s in (
                    (n, svc.snapshot())
                    for n, svc in self.services.items()) if s is not None}
                rep = mm.MMonPaxos(mm.MMonPaxos.SYNC, self.accepted_pn,
                                   version=self.last_committed,
                                   value=json.dumps(snap).encode())
            conn.send(rep)
            return
        if op == mm.MMonPaxos.SYNC:
            with self.lock:
                # only adopt a snapshot at least as new as our paxos head
                if msg.version < self.last_committed or not msg.value:
                    return
                try:
                    snap = json.loads(msg.value.decode())
                except ValueError:
                    return
                batch = WriteBatch()
                for name, s in snap.items():
                    svc = self.services.get(name)
                    if svc is not None:
                        try:
                            svc.restore(s, batch)
                        except Exception as e:  # pragma: no cover
                            self._plog(0, f"sync restore {name}: {e}")
                if batch.ops:
                    self.kv.submit(batch)
            return

    def _learn(self, version: int, value: bytes) -> None:
        # a promise for a HIGHER version than what we just learned is
        # still live (e.g. we accepted v6, then catch up on v5 during a
        # collect): wiping it could erase the only surviving copy of a
        # value the old leader already committed
        keep = (self.uncommitted is not None
                and self.uncommitted[1] > version)
        if version > self.last_committed + 1:
            # we are JUMPING a gap: the skipped versions may carry
            # PaxosService values we'll never see — pull a full service
            # snapshot from whoever is ahead (reference store sync)
            req = mm.MMonPaxos(mm.MMonPaxos.SYNC_REQ, self.accepted_pn,
                               version=self.last_committed)
            targets = ([self.leader]
                       if self.leader >= 0 and self.leader != self.rank
                       else self._peers())
            for r in targets:
                self._send_mon(r, req)
        from ceph_tpu.mon import services as mon_services

        if value and value[0] == mon_services.SVC_TAG:
            # PaxosService payload: the service's state rows land in the
            # SAME KV batch as the paxos value, so a crash can never
            # leave a committed value unapplied (the reference applies
            # service state in the paxos transaction,
            # PaxosService::propose_pending)
            batch = WriteBatch()
            try:
                payload = mon_services.decode_payload(value)
                svc = self.services.get(payload.get("svc", ""))
                if svc is not None:
                    svc.apply(payload, batch)
            except Exception as e:  # pragma: no cover
                self._plog(0, f"failed to apply service value: {e}")
            self._persist_value(version, value, clear_uncommitted=not keep,
                                extra=batch)
            self.last_committed = version
            if not keep:
                self.uncommitted = None
            return
        self._persist_value(version, value, clear_uncommitted=not keep)
        self.last_committed = version
        if not keep:
            self.uncommitted = None
        try:
            newmap = map_inc.decode_value(value, self.osdmap)
        except map_inc.NeedFullMap:
            # incremental with no matching base (we skipped commits):
            # fetch the full map — from the leader when we're a peon,
            # from every peer when we ARE the (freshly elected, stale)
            # leader; any mon with a newer map answers CATCHUP.  The
            # request is retried from the tick loop until a map at
            # least this new is adopted: a one-shot send is silently
            # dropped by a peer that is itself mid-restart (osdmap
            # still None), which stalled full-quorum recovery forever.
            self._catchup_want = max(
                getattr(self, "_catchup_want", 0), version)
            self._send_catchup_req()
            return
        except Exception as e:  # pragma: no cover
            self._plog(0, f"failed to decode committed map: {e}")
            return
        self._adopt_map(newmap, value, version)

    def _send_catchup_req(self) -> None:
        req = mm.MMonPaxos(mm.MMonPaxos.CATCHUP_REQ, self.accepted_pn,
                           version=self.last_committed)
        if self.leader >= 0 and self.leader != self.rank:
            self._send_mon(self.leader, req)
        else:
            for r in self._peers():
                self._send_mon(r, req)

    def _adopt_map(self, newmap: OSDMap, value: bytes,
                   version: int) -> None:
        self.osdmap = newmap
        if version >= getattr(self, "_catchup_want", 0):
            self._catchup_want = 0
        if value and value[0] == map_inc.INC_TAG:
            inc = map_inc.Incremental.decode(value[1:])
            self._recent_incs[inc.epoch] = (inc.prev_epoch, value[1:])
            while len(self._recent_incs) > 1024:
                del self._recent_incs[min(self._recent_incs)]
        else:
            # FULL anchor: persist the boot image + the version it
            # corresponds to (boot replays later incs on top of it)
            b = WriteBatch()
            b.set("mon", "latest_full", value[1:] if value
                  else map_codec.encode_osdmap(newmap))
            b.set("mon", "latest_full_v", str(version).encode())
            self.kv.submit(b)
        if (self._pending_map is not None
                and self.osdmap.epoch >= self._pending_map.epoch):
            self._pending_map = None  # fully caught up

    def propose(self, value: bytes) -> None:
        """Leader-only: serialize one value through phase 2."""
        with self.lock:
            if self.state != STATE_LEADER:
                return
            if self._proposing or not self._collect_complete:
                # queue until phase 1 has heard a quorum of LASTs —
                # proposing earlier can overwrite a value an unreached
                # peon already accepted for this version
                self._propose_queue.append(value)
                return
            self._proposing = True
            version = self.last_committed + 1
            pn = self.accepted_pn
            self.uncommitted = (pn, version, value)
            # the leader is an acceptor too: its own accept must survive
            # restart just like a peon's (ADVICE: promise lost on restart)
            self._persist(uncommitted_pn=pn, uncommitted_v=version,
                          uncommitted_value=value)
            self._accept_votes[version] = {self.rank}
            msg = mm.MMonPaxos(mm.MMonPaxos.BEGIN, pn, version, value)
        for r in self._peers():
            self._send_mon(r, msg)
        if len(self.monmap.live_ranks()) == 1:
            self._commit(version)

    def _commit(self, version: int) -> None:
        with self.lock:
            if not self.uncommitted or self.uncommitted[1] != version:
                self._proposing = False
                return
            value = self.uncommitted[2]
            self._learn(version, value)
            self._proposing = False
            msg = mm.MMonPaxos(mm.MMonPaxos.COMMIT, self.accepted_pn,
                               version, value)
        for r in self._peers():
            self._send_mon(r, msg)
        self._push_maps()
        self._pump_proposals()

    def _pump_proposals(self) -> None:
        with self.lock:
            if self._propose_queue and not self._proposing:
                nxt = self._propose_queue.pop(0)
            else:
                return
        self.propose(nxt)

    # -- ticks: leases, failure aging -------------------------------------
    def _tick_loop(self) -> None:
        iv = self.ctx.conf.get("mon_tick_interval")
        lease = self.ctx.conf.get("mon_lease")
        while not self._stop.wait(iv):
            with self.lock:
                state = self.state
            with self.lock:
                if getattr(self, "_catchup_want", 0):
                    # still missing a map base: keep asking (see _learn)
                    self._send_catchup_req()
            if state == STATE_LEADER:
                # snapshot pn/version/value under ONE lock hold: the
                # old code read last_committed once for the header and
                # again for the kv fetch, so a commit landing between
                # the two sent a lease whose value belonged to a
                # different version than its header claimed
                with self.lock:
                    pn = self.accepted_pn
                    ver = self.last_committed
                    data = self.kv.get("paxos_values", str(ver))
                msg = mm.MMonPaxos(mm.MMonPaxos.LEASE, pn, version=ver)
                msg.value = data or b""
                for r in self._peers():
                    self._send_mon(r, msg)
                self._osd_tick()
                try:
                    # health transition edges -> cluster log (leader
                    # only: peons would double-log through paxos)
                    self.services["health"].tick()
                except Exception as e:
                    self._log(1, f"health tick failed: {e!r}")
            elif state == STATE_PEON:
                with self.lock:
                    expired = (time.monotonic() - self._last_lease
                               > 2 * lease)
                if expired:
                    self._log(1, f"mon.{self.rank}: leader lease expired")
                    self.start_election()

    def _osd_tick(self) -> None:
        """down -> out aging (reference tick_osds / down_out_interval)."""
        interval = self.ctx.conf.get("mon_osd_down_out_interval")
        now = time.time()
        with self.lock:
            if self.osdmap is None:
                return
            stale = [osd for osd, stamp in self.down_stamp.items()
                     if (not self.osdmap.is_up(osd)
                         and self.osdmap.osd_weight[osd] != 0
                         and now - stamp > interval)]
            if stale:
                def mut(nm: OSDMap) -> None:
                    for osd in stale:
                        nm.set_osd_out(osd)

                self._mutate_map(mut)

    # -- osdmonitor -------------------------------------------------------
    def _clone_map(self) -> OSDMap:
        assert self.osdmap is not None
        return map_codec.decode_osdmap(map_codec.encode_osdmap(self.osdmap))

    def _mutate_map(self, fn) -> bool:
        """Apply `fn(pending_map)` and propose the result as an
        INCREMENTAL delta (full map every FULL_EVERY epochs as a replay
        anchor).  Must be called with self.lock held; returns False if
        there is no map."""
        if self.osdmap is None:
            return False
        if self._pending_map is None:
            self._pending_map = self._clone_map()
            self._pending_map.epoch = self.osdmap.epoch
            self._pending_crush = map_inc.crush_bytes(self._pending_map)
        prev = map_inc.clone_map(self._pending_map)
        prev_crush = self._pending_crush
        fn(self._pending_map)
        self._pending_map.epoch += 1
        new_crush = map_inc.crush_bytes(self._pending_map)
        self._pending_crush = new_crush
        if self._pending_map.epoch % FULL_EVERY == 0:
            value = map_inc.encode_full_value(self._pending_map)
        else:
            value = map_inc.encode_inc_value(map_inc.diff_maps(
                prev, self._pending_map,
                old_crush=prev_crush, new_crush=new_crush))
        self.propose(value)
        return True

    def _propose_map(self, newmap: OSDMap) -> None:
        # legacy single-shot path (commands built on _mutate_map now)
        with self.lock:
            newmap.epoch = (self.osdmap.epoch if self.osdmap else 0) + 1
        self.propose(map_inc.encode_full_value(newmap))

    def _handle_boot(self, msg: mm.MOSDBoot) -> None:
        with self.lock:
            if self.state != STATE_LEADER or self.osdmap is None:
                return
            if (self.osdmap.is_up(msg.osd_id)
                    and self.osdmap.osd_addrs.get(msg.osd_id)
                    == (msg.ip, msg.port)
                    and self.osdmap.osd_hb_addrs.get(msg.osd_id)
                    == (msg.hb_ip, msg.hb_port)):
                return  # duplicate boot retry; already reflected
            if not (0 <= msg.osd_id < self.osdmap.max_osd):
                return

            def mut(nm: OSDMap) -> None:
                nm.set_osd_up(msg.osd_id)
                if nm.osd_weight[msg.osd_id] == 0:
                    nm.set_osd_in(msg.osd_id)
                nm.osd_addrs[msg.osd_id] = (msg.ip, msg.port)
                if msg.hb_port:
                    nm.osd_hb_addrs[msg.osd_id] = (msg.hb_ip, msg.hb_port)

            self.failure_reports.pop(msg.osd_id, None)
            self.down_stamp.pop(msg.osd_id, None)
            self._log(1, f"osd.{msg.osd_id} booted at {msg.ip}:{msg.port}")
            self._mutate_map(mut)

    def _handle_failure(self, msg: mm.MOSDFailure) -> None:
        """prepare_failure: require min distinct reporters within grace
        accounting (OSDMonitor.cc:2643/:2537)."""
        reporter = msg.src.num if msg.src else -1
        with self.lock:
            if self.state != STATE_LEADER or self.osdmap is None:
                return
            if not self.osdmap.is_up(msg.target):
                return  # already down
            reports = self.failure_reports.setdefault(msg.target, {})
            reports[reporter] = time.time()
            need = self.ctx.conf.get("mon_osd_min_down_reporters")
            if len(reports) < need:
                return
            self.down_stamp[msg.target] = time.time()
            del self.failure_reports[msg.target]
            self._log(1, f"marking osd.{msg.target} down "
                      f"({len(reports)} reporters)")
            self._mutate_map(lambda nm: nm.set_osd_down(msg.target))

    # -- subscriptions ----------------------------------------------------
    def _inc_chain(self, last: int, epoch: int) -> Optional[List[bytes]]:
        """Incrementals taking a subscriber from `last` to `epoch`, or
        None if the window doesn't reach (send full instead)."""
        if last <= 0:
            return None
        chain: List[bytes] = []
        e = epoch
        while e > last:
            got = self._recent_incs.get(e)
            if got is None:
                return None
            prev, blob = got
            chain.append(blob)
            e = prev
        return list(reversed(chain)) if e == last else None

    def _push_maps(self) -> None:
        """Subscribers get O(delta) incremental pushes; the full map
        only on first subscribe or when they fell out of the window
        (reference OSDMonitor::send_incremental)."""
        sends: List[Tuple[Addr, mm.MOSDMapMsg]] = []
        with self.lock:
            if self.osdmap is None:
                return
            epoch = self.osdmap.epoch
            full = None
            for a, last in list(self.subscribers.items()):
                if last >= epoch:
                    continue
                chain = self._inc_chain(last, epoch)
                if chain is None:
                    if full is None:
                        full = map_codec.encode_osdmap(self.osdmap)
                    msg = mm.MOSDMapMsg(epoch, full)
                else:
                    msg = mm.MOSDMapMsg(epoch, b"")
                    msg.incs = chain
                sends.append((a, msg))
                self.subscribers[a] = epoch
        for a, msg in sends:
            self.msgr.send_message(msg, a)

    # -- commands ---------------------------------------------------------
    def _handle_command(self, conn: Connection,
                        msg: mm.MMonCommand) -> None:
        with self.lock:
            if self.state != STATE_LEADER:
                rep = mm.MMonCommandReply(-11, {"error": "not leader",
                                                "leader": self.leader})
                rep.tid = msg.tid
                conn.send(rep)
                return
        code, out = self._do_command(msg.cmd)
        rep = mm.MMonCommandReply(code, out)
        rep.tid = msg.tid
        conn.send(rep)

    def _do_command(self, cmd: dict) -> Tuple[int, dict]:
        prefix = cmd.get("prefix", "")
        if prefix == "status":
            # `ceph -s`: map summary + health + the PGMap digest
            # (pg states, degraded totals, client/recovery io rates)
            digest = self.pgmap.digest()
            status, _checks = self.services["health"].gather()
            with self.lock:
                m = self.osdmap
                n_up = int(m.osd_state_up.sum()) if m is not None else 0
                return 0, {
                    "health": status,
                    "quorum_leader": self.leader,
                    "election_epoch": self.election_epoch,
                    "osdmap_epoch": m.epoch if m else 0,
                    "num_osds": m.max_osd if m else 0,
                    "num_up_osds": n_up,
                    "pg_states": digest["pg_states"],
                    "num_pgs": digest["num_pgs"],
                    "degraded_objects": digest["degraded_objects"],
                    "degraded_ratio": digest["degraded_ratio"],
                    "misplaced_objects": digest["misplaced_objects"],
                    "unfound_objects": digest["unfound_objects"],
                    "io": digest["io"],
                    "pools": {p.name or str(pid): pid
                              for pid, p in (m.pools if m else {}).items()},
                }
        if prefix == "osd dump":
            with self.lock:
                m = self.osdmap
                if m is None:
                    return -2, {"error": "no osdmap"}
                return 0, {
                    "epoch": m.epoch,
                    "max_osd": m.max_osd,
                    "osds": [
                        {"osd": i, "up": bool(m.osd_state_up[i]),
                         "in": int(m.osd_weight[i]) > 0,
                         "weight": int(m.osd_weight[i]) / 0x10000,
                         "addr": list(m.osd_addrs.get(i, ("", 0)))}
                        for i in range(m.max_osd)
                    ],
                    "pools": [
                        {"pool": pid, "name": p.name,
                         "type": p.pool_type, "size": p.size,
                         "pg_num": p.pg_num,
                         "erasure_code_profile": p.erasure_code_profile}
                        for pid, p in m.pools.items()
                    ],
                }
        if prefix == "osd erasure-code-profile set":
            name = cmd["name"]
            profile = cmd["profile"]
            with self.lock:
                self.ec_profiles[name] = profile
                b = WriteBatch()
                b.set("mon", "ec_profiles",
                      json.dumps(self.ec_profiles).encode())
                self.kv.submit(b)
            return 0, {}
        if prefix == "osd erasure-code-profile ls":
            with self.lock:
                return 0, {"profiles": dict(self.ec_profiles)}
        if prefix == "osd pool create":
            return self._cmd_pool_create(cmd)
        if prefix in ("osd out", "osd in", "osd down"):
            osd = int(cmd["id"])
            with self.lock:
                if self.osdmap is None:
                    return -2, {"error": "no osdmap"}

                def mut(nm: OSDMap) -> None:
                    if prefix == "osd out":
                        nm.set_osd_out(osd)
                    elif prefix == "osd in":
                        nm.set_osd_in(osd)
                    else:
                        nm.set_osd_down(osd)

                if prefix == "osd down":
                    self.down_stamp[osd] = time.time()
                self._mutate_map(mut)
            return 0, {}
        if prefix == "osd df":
            with self.lock:
                rows = []
                for osd in sorted(self.osd_fullness):
                    used, total = self.osd_fullness[osd]
                    rows.append({
                        "osd": osd, "used_bytes": used,
                        "total_bytes": total,
                        "utilization": round(used / total, 4)
                        if total else 0.0})
                return 0, {"nodes": rows}
        if prefix == "df":
            # cluster + per-pool usage (the `ceph df` surface) from
            # the PGMap digest: objects AND stored bytes per pool,
            # degraded/unfound carried so `df` shows damage too
            digest = self.pgmap.digest()
            with self.lock:
                used = sum(u for u, _ in self.osd_fullness.values())
                total = sum(t for _, t in self.osd_fullness.values())
                pools = []
                if self.osdmap is not None:
                    for pid, p in sorted(self.osdmap.pools.items()):
                        row = digest["pools"].get(
                            pid, {"objects": 0, "bytes": 0,
                                  "degraded": 0, "misplaced": 0,
                                  "unfound": 0, "pgs": 0})
                        pools.append({"name": p.name, "id": pid,
                                      "objects": row["objects"],
                                      "stored_bytes": row["bytes"],
                                      "degraded": row["degraded"],
                                      "unfound": row["unfound"],
                                      "pgs": row["pgs"]})
                return 0, {"total_bytes": total, "used_bytes": used,
                           "avail_bytes": max(0, total - used),
                           "pools": pools}
        if prefix in ("pg scrub", "pg deep-scrub", "pg repair"):
            # relay to the PG's primary OSD (the reference mon builds an
            # MOSDScrub for `ceph pg repair`, src/mon/MonCmds.h) — the
            # actual scrub/repair runs there asynchronously
            try:
                pool_id, ps = (int(x) for x in str(cmd["pgid"]).split("."))
            except (KeyError, ValueError):
                return -22, {"error": "need pgid as <pool>.<ps>"}
            with self.lock:
                if self.osdmap is None:
                    return -2, {"error": "no osdmap"}
                _, _, _, primary = self.osdmap.pg_to_up_acting(
                    (pool_id, ps))
                addr = self.osdmap.osd_addrs.get(primary)
            if primary < 0 or not addr:
                return -11, {"error": "pg has no live primary"}
            # distinct actions for all THREE prefixes: `pg deep-scrub`
            # used to collapse to a shallow scrub here (the only
            # byte-reading verification an operator could reach was a
            # full repair) — the primary now receives the deep action
            # and runs the chunked byte-verifying scrub
            action = {"pg repair": "repair",
                      "pg deep-scrub": "deep-scrub"}.get(prefix, "scrub")
            from ceph_tpu.osd import messages as om
            self.msgr.send_message(
                om.MPGCommand((pool_id, ps), 0, action), tuple(addr))
            return 0, {"instructed": f"osd.{primary}", "action": action}
        if prefix == "pg dump":
            # rich rows straight off the PGMap (primary-reported rows
            # win; replicas fill gaps — the ingest rule)
            rows = self.pgmap.pg_rows()
            return 0, {"num_pg_stats": len(rows), "pg_stats": rows}
        if prefix == "osd pool set":
            var, val = cmd["var"], int(cmd["val"])
            if var not in ("pg_num", "pgp_num", "size", "min_size"):
                return -22, {"error": f"cannot set {var!r}"}
            with self.lock:
                if self.osdmap is None:
                    return -2, {"error": "no osdmap"}
                name_or_id = cmd["pool"]
                by_name = {p.name: pid
                           for pid, p in self.osdmap.pools.items()}
                pid = by_name.get(name_or_id,
                                  int(name_or_id)
                                  if str(name_or_id).isdigit() else -1)
                pool = self.osdmap.pools.get(pid)
                if pool is None:
                    return -2, {"error": f"no pool {name_or_id!r}"}
                if var == "pg_num" and val < pool.pg_num:
                    return -22, {"error": "pg_num may only grow"}
                if var == "pgp_num" and val > pool.pg_num:
                    return -22, {"error": "pgp_num cannot exceed pg_num"}

                def mut(nm: OSDMap) -> None:
                    setattr(nm.pools[pid], var, val)

                self._mutate_map(mut)
            return 0, {"pool_id": pid, var: val}
        if prefix == "osd reweight":
            osd = int(cmd["id"])
            weight = float(cmd["weight"])
            with self.lock:
                self._mutate_map(
                    lambda nm: nm.reweight_osd(osd, int(weight * 0x10000)))
            return 0, {}
        for svc in self.services.values():
            got = svc.command(cmd)
            if got is not None:
                return got
        return -22, {"error": f"unknown command {prefix!r}"}

    def _cmd_pool_create(self, cmd: dict) -> Tuple[int, dict]:
        name = cmd["pool"]
        pg_num = int(cmd.get("pg_num",
                             self.ctx.conf.get("osd_pool_default_pg_num")))
        kind = cmd.get("pool_type", "replicated")
        box: Dict[str, object] = {}
        with self.lock:
            if self.osdmap is None:
                return -2, {"error": "no osdmap"}
            base = self._pending_map or self.osdmap
            for pid, p in base.pools.items():
                if p.name == name:
                    # reference behavior: creating an existing pool is
                    # SUCCESS (matters for re-runs over durable mon
                    # state: "pool already exists")
                    return 0, {"pool_id": pid, "existed": True}
            if kind == "erasure":
                profile_name = cmd.get("erasure_code_profile", "default")
                profile = self.ec_profiles.get(profile_name)
                if profile is None:
                    return -2, {"error": f"no profile {profile_name!r}"}
            else:
                profile = ""

            def mut(nm: OSDMap) -> None:
                pool_id = max(nm.pools, default=0) + 1
                referenced = {i for b in nm.crush.buckets.values()
                              for i in b.items if i < 0}
                roots = [bid for bid in nm.crush.buckets
                         if bid not in referenced]
                root = roots[0] if roots else max(nm.crush.buckets)
                if kind == "erasure":
                    kd = dict(part.split("=", 1)
                              for part in profile.split() if "=" in part)
                    size = int(kd.get("k", 2)) + int(kd.get("m", 1))
                    rule = nm.crush.add_simple_rule(
                        f"{name}_rule", root, 1, mode="indep")
                    pool = PGPool(pool_id, POOL_ERASURE, size=size,
                                  min_size=int(kd.get("k", 2)),
                                  pg_num=pg_num, pgp_num=pg_num,
                                  crush_rule=rule,
                                  erasure_code_profile=profile)
                else:
                    size = int(cmd.get(
                        "size", self.ctx.conf.get("osd_pool_default_size")))
                    rule = nm.crush.add_simple_rule(
                        f"{name}_rule", root, 1, mode="firstn")
                    pool = PGPool(pool_id, POOL_REPLICATED, size=size,
                                  min_size=max(1, size - size // 2),
                                  pg_num=pg_num, pgp_num=pg_num,
                                  crush_rule=rule)
                pool.name = name
                nm.pools[pool_id] = pool
                box["pool_id"] = pool_id

            self._mutate_map(mut)
        return 0, {"pool_id": box.get("pool_id")}

    # -- dispatch ---------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, mm.MMonElection):
            self._handle_election(conn, msg)
            return True
        if isinstance(msg, mm.MMonPaxos):
            self._handle_paxos(conn, msg)
            return True
        if isinstance(msg, mm.MMonCommand):
            self._handle_command(conn, msg)
            return True
        if isinstance(msg, mm.MMonSubscribe):
            return self._handle_subscribe(conn, msg)
        if isinstance(msg, mm.MOSDBoot):
            self._handle_boot(msg)
            return True
        if isinstance(msg, mm.MMDSBoot):
            # FSMap feed (reference MMDSBeacon -> MDSMonitor)
            with self.lock:
                if self.state == STATE_LEADER:
                    self.services["mdsmap"].handle_boot(
                        msg.rank, (msg.ip, msg.port),
                        getattr(msg, "boot_nonce", 0))
            return True
        if isinstance(msg, mm.MPGStats):
            with self.lock:
                self.pg_stats[msg.osd] = (time.time(), msg.pgs)
                self.osd_fullness[msg.osd] = (msg.used_bytes,
                                              msg.total_bytes)
            stats = msg.stats
            if not stats and msg.pgs:
                # legacy thin report (a pre-telemetry daemon): rows
                # synthesize with zeroed io/degraded fields so the
                # digest still counts its pg states
                from ceph_tpu.osd.types import EVersion, PGStat

                stats = [PGStat(pgid=(p[0], p[1]), state=p[2],
                                primary=p[6], num_objects=p[3],
                                last_update=EVersion(p[4], p[5]))
                         for p in msg.pgs]
            self.pgmap.ingest(msg.osd, msg.epoch, stats,
                              msg.used_bytes, msg.total_bytes,
                              slow_ops=msg.slow_ops,
                              heartbeat_misses=msg.heartbeat_misses)
            return True
        if isinstance(msg, mm.MOSDFailure):
            self._handle_failure(msg)
            return True
        if isinstance(msg, mm.MAuth):
            self._handle_auth(conn, msg)
            return True
        return False

    def _handle_auth(self, conn: Connection, msg: mm.MAuth) -> None:
        from ceph_tpu.auth import AuthError

        rep = mm.MAuthReply(result=-1)
        if self.auth_server is not None:
            try:
                if msg.op == mm.MAuth.GET_CHALLENGE:
                    rep = mm.MAuthReply(
                        result=0,
                        challenge=self.auth_server.get_challenge(msg.name))
                elif msg.op == mm.MAuth.REQUEST:
                    sealed, ticket = self.auth_server.handle_request(
                        msg.name, msg.client_challenge, msg.proof)
                    rep = mm.MAuthReply(result=0, sealed_client=sealed,
                                        ticket_blob=ticket)
            except AuthError as e:
                self._log(1, f"auth denied for {msg.name!r}: {e}")
                rep = mm.MAuthReply(result=-13)  # EACCES
        rep.tid = msg.tid
        conn.send(rep)

    def _handle_subscribe(self, conn: Connection,
                          msg: mm.MMonSubscribe) -> bool:
        # subscribers are identified by their LISTENING address, carried
        # in `what` as "osdmap:<ip>:<port>" (the accepted socket's
        # ephemeral port is useless for dialing back)
        parts = msg.what.split(":")
        if len(parts) == 3 and parts[0] == "osdmap":
            addr = (parts[1], int(parts[2]))
            with self.lock:
                self.subscribers[addr] = msg.since
            self._push_maps()
            return True
        return True
