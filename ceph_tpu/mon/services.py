"""PaxosService family: Config/Log/Health/Auth monitors.

Reference: src/mon/PaxosService.{h,cc} — each cluster service keeps its
own versioned state machine, but ALL of them serialize their commits
through the monitor's single Paxos instance.  Same inversion here: a
service mutation is proposed as a tagged value (SVC_TAG + JSON payload)
on the same paxos stream that carries OSDMap commits; every mon —
leader and peons alike — applies it in `_learn`, so service state is
exactly as replicated and exactly as durable as the map itself.

Services (each cites its reference counterpart):
- ConfigMonitor  (src/mon/ConfigMonitor.cc): centralized config db,
  `config set/rm/get/dump`, applied to the local daemon config when the
  section matches (the reference pushes config to subscribed daemons;
  here daemons read it via `config get` / the mon applies it locally).
- LogMonitor    (src/mon/LogMonitor.cc): the cluster log — `log` adds
  an entry through paxos, `log last` reads the tail; bounded retention.
- HealthMonitor (src/mon/HealthMonitor.cc): health checks derived from
  the osdmap (down/out OSDs) plus persisted mutes; `health` returns
  HEALTH_OK/WARN + the check list.
- AuthMonitor   (src/mon/AuthMonitor.cc): entity key db on top of the
  cephx keyring — `auth get-or-create/get/ls/rm`; new keys replicate
  through paxos so every mon's CephxServer can validate them.

Commit semantics: mutating commands return after the value is QUEUED on
the leader's paxos (on a single-mon cluster that is synchronous commit,
matching the tests; on multi-mon the commit lands one accept round
later) — the same asynchrony the map-mutation path already has.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.store.kv import WriteBatch

# paxos-value tag for service payloads; map values use 0/1
# (ceph_tpu/osd/map_inc.py FULL_TAG/INC_TAG)
SVC_TAG = 0xD5


def encode_payload(svc: str, payload: dict) -> bytes:
    return bytes([SVC_TAG]) + json.dumps(
        {"svc": svc, **payload}, sort_keys=True).encode()


def decode_payload(value: bytes) -> dict:
    return json.loads(value[1:].decode())


class PaxosService:
    """One service state machine multiplexed onto the mon's Paxos."""

    name = ""

    def __init__(self, mon) -> None:
        self.mon = mon
        self.kv = mon.kv

    def load(self) -> None:
        """Restore committed state from the mon's KV."""

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        """Apply one committed payload — runs on EVERY mon.  All KV
        persistence goes into `batch`, which the monitor submits
        atomically WITH the paxos value (a crash can never separate a
        committed value from its effect)."""

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        """Handle a mon command; None = not mine."""
        return None

    def health_checks(self) -> Dict[str, dict]:
        """Contribution to `health` output."""
        return {}

    def snapshot(self) -> Optional[dict]:
        """JSON-serializable committed state for mon store sync (the
        reference's full-store-sync role: a mon that jumped a paxos
        version gap pulls every service's state wholesale)."""
        return None

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        """Adopt a snapshot (persistence into `batch`)."""

    def propose(self, payload: dict) -> None:
        self.mon.propose(encode_payload(self.name, payload))


class ConfigMonitor(PaxosService):
    name = "config"

    def __init__(self, mon) -> None:
        super().__init__(mon)
        self.db: Dict[str, Dict[str, str]] = {}  # section -> key -> value

    def load(self) -> None:
        raw = self.kv.get("svc_config", "db")
        self.db = json.loads(raw.decode()) if raw else {}

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        op = payload["op"]
        who, key = payload["who"], payload.get("key", "")
        if op == "set":
            self.db.setdefault(who, {})[key] = payload["value"]
        elif op == "rm":
            self.db.get(who, {}).pop(key, None)
        batch.set("svc_config", "db", json.dumps(self.db).encode())
        # hot-apply to this mon's own runtime config when addressed
        # (reference: daemons apply pushed config via md_config_t)
        if who in ("global", "mon", f"mon.{self.mon.rank}"):
            try:
                if op == "set":
                    self.mon.ctx.conf.set_val(key, payload["value"])
            except Exception:
                pass  # unknown/invalid key stays db-only

    def snapshot(self) -> Optional[dict]:
        return {"db": self.db}

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        self.db = {k: dict(v) for k, v in snap["db"].items()}
        batch.set("svc_config", "db", json.dumps(self.db).encode())

    def get_effective(self, who: str) -> Dict[str, str]:
        """global < type < type.id precedence (ConfigMonitor.cc
        get_config shape)."""
        out: Dict[str, str] = dict(self.db.get("global", {}))
        if "." in who:
            kind = who.split(".", 1)[0]
            out.update(self.db.get(kind, {}))
        out.update(self.db.get(who, {}))
        return out

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        prefix = cmd.get("prefix", "")
        if prefix == "config set":
            self.propose({"op": "set", "who": cmd["who"],
                          "key": cmd["name"], "value": str(cmd["value"])})
            return 0, {}
        if prefix == "config rm":
            self.propose({"op": "rm", "who": cmd["who"], "key": cmd["name"]})
            return 0, {}
        if prefix == "config get":
            return 0, {"config": self.get_effective(cmd["who"])}
        if prefix == "config dump":
            return 0, {"config": {k: dict(v) for k, v in self.db.items()}}
        return None


class LogMonitor(PaxosService):
    name = "logm"
    KEEP = 500

    def __init__(self, mon) -> None:
        super().__init__(mon)
        self.entries: List[dict] = []  # {stamp, who, level, msg}

    def load(self) -> None:
        raw = self.kv.get("svc_log", "entries")
        self.entries = json.loads(raw.decode()) if raw else []

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        self.entries.append({
            "stamp": payload.get("stamp", 0.0),
            "who": payload.get("who", "?"),
            "level": payload.get("level", "info"),
            "msg": payload.get("msg", ""),
        })
        del self.entries[:-self.KEEP]
        batch.set("svc_log", "entries", json.dumps(self.entries).encode())

    def snapshot(self) -> Optional[dict]:
        return {"entries": self.entries}

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        self.entries = list(snap["entries"])[-self.KEEP:]
        batch.set("svc_log", "entries", json.dumps(self.entries).encode())

    def log(self, who: str, msg: str, level: str = "info") -> None:
        """Daemon-facing API (the reference's LogClient -> MLog path)."""
        self.propose({"who": who, "msg": msg, "level": level,
                      "stamp": time.time()})

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        prefix = cmd.get("prefix", "")
        if prefix == "log":
            self.propose({"who": cmd.get("who", "client"),
                          "msg": str(cmd.get("logtext", "")),
                          "level": cmd.get("level", "info"),
                          "stamp": time.time()})
            return 0, {}
        if prefix == "log last":
            n = int(cmd.get("num", 20))
            return 0, {"lines": self.entries[-n:]}
        return None


class HealthMonitor(PaxosService):
    name = "health"

    def __init__(self, mon) -> None:
        super().__init__(mon)
        self.muted: Dict[str, bool] = {}
        # transition tracking (tick(), leader-side): previous overall
        # status + live check set, so HEALTH_OK <-> WARN <-> ERR edges
        # and check appear/clear events land in the cluster log
        self._last_status = "HEALTH_OK"
        self._last_checks: set = set()

    def load(self) -> None:
        raw = self.kv.get("svc_health", "muted")
        self.muted = json.loads(raw.decode()) if raw else {}

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        if payload["op"] == "mute":
            self.muted[payload["check"]] = True
        elif payload["op"] == "unmute":
            self.muted.pop(payload["check"], None)
        batch.set("svc_health", "muted", json.dumps(self.muted).encode())

    def snapshot(self) -> Optional[dict]:
        return {"muted": self.muted}

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        self.muted = dict(snap["muted"])
        batch.set("svc_health", "muted", json.dumps(self.muted).encode())

    def gather(self) -> Tuple[str, Dict[str, dict]]:
        """HEALTH_OK/HEALTH_WARN + checks, derived live from the map +
        every service's contributions (HealthMonitor.cc check shape)."""
        checks: Dict[str, dict] = {}
        m = self.mon.osdmap
        if m is not None:
            down = [i for i in range(m.max_osd)
                    if not bool(m.osd_state_up[i])]
            if down:
                checks["OSD_DOWN"] = {
                    "severity": "HEALTH_WARN",
                    "summary": f"{len(down)} osds down",
                    "detail": [f"osd.{i} is down" for i in down],
                }
            out = [i for i in range(m.max_osd)
                   if int(m.osd_weight[i]) == 0]
            if out:
                checks["OSD_OUT"] = {
                    "severity": "HEALTH_WARN",
                    "summary": f"{len(out)} osds out",
                    "detail": [f"osd.{i} is out" for i in out],
                }
        # PG states from the PGMap digest (primary-reported rows;
        # stale reports — conf mon_pg_stats_stale_s, not a hardcoded
        # cutoff — are EXCLUDED here and surfaced as their own check
        # below instead of silently vanishing)
        pgmap = self.mon.pgmap
        digest = pgmap.digest()
        degraded, peering, damaged = [], [], []
        # fresh_only: the detail must name the same staleness-filtered
        # PG set the digest summaries count — a dead reporter's stale
        # rows belong to MON_STALE_PG_REPORTS, not these lists
        for row in pgmap.pg_rows(fresh_only=True):
            if not row["primary"]:
                continue
            if row.get("scrub_errors"):
                damaged.append(f"{row['pgid']} ({row['scrub_errors']} "
                               f"scrub errors)")
            if "degraded" in row["state"]:
                degraded.append(f"{row['pgid']} ({row['degraded']} "
                                f"objects degraded)")
            elif row["state"] == "peering":
                peering.append(row["pgid"])
        n_deg_pgs = sum(n for s, n in digest["pg_states"].items()
                        if "degraded" in s)
        if n_deg_pgs:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{n_deg_pgs} pgs degraded",
                "detail": sorted(degraded)[:10],
            }
        if digest["pg_states"].get("peering"):
            checks["PG_PEERING"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{digest['pg_states']['peering']} pgs peering",
                "detail": sorted(peering)[:10],
            }
        if digest["degraded_objects"]:
            pct = digest["degraded_ratio"] * 100.0
            checks["OBJECT_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{digest['degraded_objects']}/"
                           f"{digest['total_copies']} object copies "
                           f"degraded ({pct:.1f}%)",
                "detail": [f"recovery rate "
                           f"{digest['io']['recovery_objects_per_s']} "
                           f"objects/s"],
            }
        if digest["unfound_objects"]:
            checks["OBJECT_UNFOUND"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{digest['unfound_objects']} objects "
                           f"unfound (no live source)",
                "detail": [],
            }
        if digest.get("scrub_errors"):
            # scrub found damage repair has not cleared: possible data
            # corruption (the reference's PG_DAMAGED / OSD_SCRUB_ERRORS)
            checks["PG_DAMAGED"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{digest['scrub_errors']} scrub errors on "
                           f"{digest['damaged_pgs']} pgs — possible "
                           f"data damage",
                "detail": sorted(damaged)[:10],
            }
        not_deep = pgmap.not_deep_scrubbed()
        if not_deep:
            checks["PG_NOT_DEEP_SCRUBBED"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(not_deep)} pgs not deep-scrubbed "
                           f"in time",
                "detail": [
                    f"pg {r['pgid']} last deep-scrubbed "
                    + (f"{r['age_s']}s ago" if r["age_s"] is not None
                       else "never") for r in not_deep[:10]],
            }
        stuck = pgmap.stuck_pgs()
        if stuck:
            checks["PG_STUCK"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(stuck)} pgs stuck in non-active "
                           f"states",
                "detail": [f"pg {r['pgid']} stuck {r['state']} for "
                           f"{r['stuck_for_s']}s" for r in stuck[:10]],
            }
        if digest["slow_ops"]:
            n_slow = sum(digest["slow_ops"].values())
            checks["SLOW_OPS"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{n_slow} slow ops on "
                           f"{len(digest['slow_ops'])} daemons",
                "detail": [f"osd.{osd}: {n} slow ops"
                           for osd, n in sorted(
                               digest["slow_ops"].items())],
            }
        slow_hb = pgmap.slow_heartbeat_osds()
        if slow_hb:
            checks["OSD_SLOW_HEARTBEAT"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(slow_hb)} osds observing heartbeat "
                           f"grace overruns",
                "detail": [f"osd.{o} reported fresh heartbeat misses"
                           for o in slow_hb],
            }
        if m is not None:
            live = [i for i in range(m.max_osd)
                    if bool(m.osd_state_up[i])]
            stale_reps = pgmap.stale_osds(live)
            if stale_reps:
                checks["MON_STALE_PG_REPORTS"] = {
                    "severity": "HEALTH_WARN",
                    "summary": f"{len(stale_reps)} up osds have stale "
                               f"pg stats (degraded pgs may be "
                               f"invisible)",
                    "detail": [f"osd.{o}: last report {age}s ago"
                               for o, age in stale_reps],
                }
        # store fullness (reference OSDMap full/nearfull flags)
        nearfull, full = [], []
        for osd, (used, total) in self.mon.osd_fullness.items():
            if not total:
                continue
            ratio = used / total
            if ratio >= 0.95:
                full.append(f"osd.{osd} ({ratio:.0%})")
            elif ratio >= 0.85:
                nearfull.append(f"osd.{osd} ({ratio:.0%})")
        if full:
            checks["OSD_FULL"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{len(full)} osds full",
                "detail": sorted(full),
            }
        if nearfull:
            checks["OSD_NEARFULL"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(nearfull)} osds nearfull",
                "detail": sorted(nearfull),
            }
        for svc in self.mon.services.values():
            if svc is not self:
                checks.update(svc.health_checks())
        live = {k: v for k, v in checks.items() if k not in self.muted}
        rank = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}
        status = "HEALTH_OK"
        for c in live.values():
            if rank.get(c["severity"], 0) > rank[status]:
                status = c["severity"]
        return status, checks

    def tick(self) -> None:
        """Leader-side transition detector (called from the mon tick):
        HEALTH_OK <-> WARN <-> ERR edges and individual check
        appear/clear events land in the LogMonitor cluster log, so
        `log last` reconstructs the health history of an incident —
        muted checks don't log (that is what mute is for)."""
        status, checks = self.gather()
        live = {k for k in checks if k not in self.muted}
        logm = self.mon.services.get("logm")
        if logm is None:
            return
        if status != self._last_status:
            changed = sorted((live ^ self._last_checks) & live)
            why = ""
            if changed:
                why = " (" + "; ".join(
                    f"{k}: {checks[k]['summary']}" for k in changed) + ")"
            logm.log(f"mon.{self.mon.rank}",
                     f"cluster health {self._last_status} -> "
                     f"{status}{why}",
                     level="warn" if status != "HEALTH_OK" else "info")
        for k in sorted(live - self._last_checks):
            logm.log(f"mon.{self.mon.rank}",
                     f"health check {k} raised: "
                     f"{checks[k]['summary']}", level="warn")
        for k in sorted(self._last_checks - live):
            logm.log(f"mon.{self.mon.rank}",
                     f"health check {k} cleared", level="info")
        self._last_status = status
        self._last_checks = live

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        prefix = cmd.get("prefix", "")
        if prefix == "health":
            status, checks = self.gather()
            return 0, {"status": status, "checks": checks,
                       "muted": sorted(self.muted)}
        if prefix == "health detail":
            # every check with full detail; muted checks stay LISTED
            # (flagged) but never count toward the overall status
            status, checks = self.gather()
            out = {}
            for k, v in sorted(checks.items()):
                row = dict(v)
                row["muted"] = k in self.muted
                out[k] = row
            return 0, {"status": status, "checks": out,
                       "muted": sorted(self.muted)}
        if prefix == "health mute":
            self.propose({"op": "mute", "check": cmd["check"]})
            return 0, {}
        if prefix == "health unmute":
            self.propose({"op": "unmute", "check": cmd["check"]})
            return 0, {}
        return None


class AuthMonitor(PaxosService):
    name = "auth"

    def snapshot(self) -> Optional[dict]:
        if self.mon.auth_server is None:
            return None
        return {"keyring": self.mon.auth_server.keyring.dump()}

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        if self.mon.auth_server is None:
            return
        from ceph_tpu.auth.keyring import Keyring

        stored = Keyring.loads(snap["keyring"])
        kr = self.mon.auth_server.keyring
        for name in stored.names():
            kr.add(name, stored.get(name))
        batch.set("svc_auth", "keyring", kr.dump().encode())

    def load(self) -> None:
        raw = self.kv.get("svc_auth", "keyring")
        if raw and self.mon.auth_server is not None:
            from ceph_tpu.auth.keyring import Keyring

            stored = Keyring.loads(raw.decode())
            kr = self.mon.auth_server.keyring
            for name in stored.names():
                kr.add(name, stored.get(name))

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        if self.mon.auth_server is None:
            return
        kr = self.mon.auth_server.keyring
        if payload["op"] == "add":
            kr.add(payload["entity"], bytes.fromhex(payload["secret"]))
        elif payload["op"] == "rm" and payload["entity"] in list(kr.names()):
            kr._keys.pop(payload["entity"], None)
        batch.set("svc_auth", "keyring", kr.dump().encode())

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        prefix = cmd.get("prefix", "")
        if prefix not in ("auth get-or-create", "auth get", "auth ls",
                          "auth rm"):
            return None
        if self.mon.auth_server is None:
            return -95, {"error": "auth disabled (no keyring)"}
        kr = self.mon.auth_server.keyring
        if prefix == "auth get-or-create":
            entity = cmd["entity"]
            secret = kr.get(entity)
            if secret is None:
                from ceph_tpu.auth.keyring import generate_secret

                secret = generate_secret()
                self.propose({"op": "add", "entity": entity,
                              "secret": secret.hex()})
            return 0, {"entity": entity, "key": secret.hex()}
        if prefix == "auth get":
            secret = kr.get(cmd["entity"])
            if secret is None:
                return -2, {"error": f"no key for {cmd['entity']}"}
            return 0, {"entity": cmd["entity"], "key": secret.hex()}
        if prefix == "auth ls":
            return 0, {"entities": sorted(kr.names())}
        if prefix == "auth rm":
            self.propose({"op": "rm", "entity": cmd["entity"]})
            return 0, {}
        return None





class MonmapMonitor(PaxosService):
    """Mon-roster changes through paxos (src/mon/MonmapMonitor.cc).

    `mon add` appends a rank; `mon rm` leaves a None hole (ranks are
    identity — see MonMap).  Every mon applies the new roster on
    commit, so quorum math changes cluster-wide in one paxos round; a
    NEWLY added mon is then started by the operator with the new map
    and catches up through the ordinary collect/CATCHUP path.
    """

    name = "monmap"

    def load(self) -> None:
        raw = self.kv.get("svc_monmap", "map")
        if raw:
            from ceph_tpu.mon.monitor import MonMap

            stored = MonMap.from_dict(json.loads(raw.decode()))
            if stored.epoch > self.mon.monmap.epoch:
                self.mon.monmap = stored

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        from ceph_tpu.mon.monitor import MonMap

        new = MonMap.from_dict(payload["monmap"])
        if new.epoch > self.mon.monmap.epoch:
            self.mon.monmap = new
        batch.set("svc_monmap", "map",
                  json.dumps(payload["monmap"]).encode())

    def snapshot(self) -> Optional[dict]:
        return {"monmap": self.mon.monmap.to_dict()}

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        from ceph_tpu.mon.monitor import MonMap

        new = MonMap.from_dict(snap["monmap"])
        if new.epoch > self.mon.monmap.epoch:
            self.mon.monmap = new
        batch.set("svc_monmap", "map",
                  json.dumps(snap["monmap"]).encode())

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        prefix = cmd.get("prefix", "")
        if prefix == "mon dump":
            return 0, {"monmap": self.mon.monmap.to_dict(),
                       "leader": self.mon.leader}
        if prefix == "mon add":
            addr = (cmd["addr"][0], int(cmd["addr"][1]))
            new = self.mon.monmap.with_added(addr)
            self.propose({"monmap": new.to_dict()})
            return 0, {"rank": new.size - 1, "epoch": new.epoch}
        if prefix == "mon rm":
            rank = int(cmd["rank"])
            if rank >= self.mon.monmap.size or \
                    self.mon.monmap.addrs[rank] is None:
                return -2, {"error": f"no mon rank {rank}"}
            live = len(self.mon.monmap.live_ranks())
            if live <= 1:
                return -22, {"error": "refusing to remove the last mon"}
            new = self.mon.monmap.with_removed(rank)
            self.propose({"monmap": new.to_dict()})
            return 0, {"epoch": new.epoch}
        return None


class MDSMonitor(PaxosService):
    """The FSMap role (reference src/mon/MDSMonitor.cc + FSMap): a
    paxos-committed roster of MDS ranks and their addresses.  MDS
    daemons boot through the mon (MMDSBoot), clients discover the
    rank->addr table with `fs status`, and `mds fail` marks a rank
    down (its clients fail over when a replacement boots)."""

    name = "mdsmap"

    def __init__(self, mon) -> None:
        super().__init__(mon)
        self.epoch = 0
        self.ranks: Dict[str, dict] = {}  # str(rank) -> {addr, up}

    def load(self) -> None:
        raw = self.kv.get("svc_mdsmap", "db")
        if raw:
            got = json.loads(raw.decode())
            self.epoch = got["epoch"]
            self.ranks = got["ranks"]

    def _persist(self, batch: WriteBatch) -> None:
        batch.set("svc_mdsmap", "db", json.dumps(
            {"epoch": self.epoch, "ranks": self.ranks}).encode())

    def apply(self, payload: dict, batch: WriteBatch) -> None:
        op = payload["op"]
        rank = str(payload["rank"])
        if op == "boot":
            self.ranks[rank] = {"addr": payload["addr"], "up": True,
                                "nonce": payload.get("nonce", 0)}
        elif op == "fail":
            if rank in self.ranks:
                self.ranks[rank]["up"] = False
        self.epoch += 1
        self._persist(batch)

    def snapshot(self) -> Optional[dict]:
        return {"epoch": self.epoch, "ranks": self.ranks}

    def restore(self, snap: dict, batch: WriteBatch) -> None:
        self.epoch = snap["epoch"]
        self.ranks = {k: dict(v) for k, v in snap["ranks"].items()}
        self._persist(batch)

    def handle_boot(self, rank: int, addr, nonce: int = 0) -> None:
        cur = self.ranks.get(str(rank))
        if cur and cur.get("up") and tuple(cur["addr"]) == tuple(addr):
            # duplicate boot retry — but only for the SAME incarnation.
            # An MDS that restarted on the same address carries a fresh
            # nonce and must re-register it: suppressing it would leave
            # the OLD nonce stored, so a later `mds fail` could be
            # undone by the new incarnation's retried beacons (their
            # nonce wouldn't match the stored one and the replay guard
            # below wouldn't hold them back)
            if not nonce or cur.get("nonce") == nonce:
                return
        if (cur and not cur.get("up") and nonce
                and cur.get("nonce") == nonce):
            # a REPLAYED/resent beacon of the very incarnation that was
            # failed (beacons are resent until committed and ride
            # lossless sessions): it must not resurrect the rank — only
            # a NEW boot incarnation (fresh nonce) re-registers
            return
        self.propose({"op": "boot", "rank": rank, "addr": list(addr),
                      "nonce": nonce})

    def command(self, cmd: dict) -> Optional[Tuple[int, dict]]:
        prefix = cmd.get("prefix", "")
        if prefix == "fs status":
            return 0, {"epoch": self.epoch,
                       "ranks": {r: dict(v)
                                 for r, v in sorted(self.ranks.items())}}
        if prefix == "mds fail":
            rank = str(cmd["rank"])
            if rank not in self.ranks:
                return -2, {"error": f"no mds rank {rank}"}
            self.propose({"op": "fail", "rank": int(rank)})
            return 0, {}
        return None

    def health_checks(self) -> Dict[str, dict]:
        down = [r for r, v in self.ranks.items() if not v.get("up")]
        if down:
            return {"MDS_RANK_DOWN": {
                "severity": "HEALTH_WARN",
                "summary": f"mds ranks down: {sorted(down)}"}}
        return {}


def build_services(mon) -> Dict[str, PaxosService]:
    svcs = [ConfigMonitor(mon), LogMonitor(mon), HealthMonitor(mon),
            AuthMonitor(mon), MonmapMonitor(mon), MDSMonitor(mon)]
    return {s.name: s for s in svcs}
