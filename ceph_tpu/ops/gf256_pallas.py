"""Pallas TPU kernel for GF(2^8) coefficient-matrix multiply.

The SWAR xor network (ops/gf256_swar.py) is the right computation —
~14 VPU ops per input byte, no MXU dependency — but when XLA lowers it
as a graph of full-size jnp ops it materializes the doubled-power
intermediates to HBM, capping measured on-chip throughput at ~8-15 GB/s
(round-4 hardware session).  This module runs the SAME network inside a
single Pallas kernel: each grid step DMAs one (k, S, 128) tile of the
packed u32 planes into VMEM, evaluates the whole network on-register,
and writes the (R, S, 128) output tile — HBM traffic is exactly
read-k + write-R planes, the roofline the engine is supposed to hit.

Layout: bytes are packed four-per-u32 word (the SWAR invariant), and
words are shaped (T, 128) per plane so every VPU op sees native
(sublane, lane) tiles — a 1-D (W,) layout measured ~2x slower.

The kernel takes a u32 seed scalar XOR'd into every loaded word.  The
product path passes 0 (a no-op on the data); benchmarks pass the
iteration index so consecutive in-jit iterations cannot be hoisted as
loop-invariant (the axon tunnel's 94 ms round-trip makes per-dispatch
timing meaningless, so benches must loop inside one jit).

Reference role: the per-arch SIMD encode kernels behind
``ec_encode_data`` (src/erasure-code/isa/ErasureCodeIsa.cc:128) and
gf-complete's SSSE3/AVX regions (src/erasure-code/jerasure/
CMakeLists.txt:12-38).
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.tpu.devwatch import (instrumented_jit,
                                   instrumented_pallas_call)

LANES = 128
DEFAULT_TILE = 512  # sublane rows per grid step: (k, 512, 128) u32 = 2 MiB for k=8


def _compiler_params(pltpu, **kw):
    # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def _net_matrix_meta(matrix: np.ndarray):
    mat = [[int(c) for c in row] for row in matrix]
    R, k = matrix.shape
    need_bits = [0] * k
    for row in mat:
        for j, c in enumerate(row):
            need_bits[j] |= c
    max_bit = [nb.bit_length() for nb in need_bits]
    return mat, R, k, max_bit


def _double_word(p, mul_shift: bool):
    """Multiply every packed byte by x in GF(2^8) (poly 0x11d).

    mul_shift=True replaces the u32 multiply `carry * 0x1D` with the
    equivalent shift/xor chain (0x1D = bits 0,2,3,4) — on some VPU
    generations integer multiply is multi-cycle, so both forms are
    autotune candidates.
    """
    low7 = jnp.uint32(0x7F7F7F7F)
    ones = jnp.uint32(0x01010101)
    carry = (p >> 7) & ones
    if mul_shift:
        red = carry ^ (carry << 2) ^ (carry << 3) ^ (carry << 4)
    else:
        red = carry * jnp.uint32(0x1D)
    return ((p & low7) << 1) ^ red


def _make_kernel(matrix: np.ndarray, mul_shift: bool = False) -> Callable:
    """Kernel over refs: (seed u32[1] SMEM, x u32[k,S,128], o u32[R,S,128])."""
    mat, R, k, max_bit = _net_matrix_meta(matrix)

    def kernel(seed_ref, x_ref, o_ref):
        seed = seed_ref[0]
        acc = [None] * R
        for j in range(k):
            p = x_ref[j] ^ seed
            for b in range(max(max_bit[j], 1)):
                if b > 0:
                    p = _double_word(p, mul_shift)
                for i in range(R):
                    if (mat[i][j] >> b) & 1:
                        acc[i] = p if acc[i] is None else acc[i] ^ p
        zero = jnp.zeros_like(x_ref[0])
        for i in range(R):
            o_ref[i] = acc[i] if acc[i] is not None else zero

    return kernel


@functools.lru_cache(maxsize=64)
def _compiled(matrix_bytes: bytes, shape: Tuple[int, int], tile: int,
              interpret: bool, mul_shift: bool = False,
              donate: bool = False, dimsem: str = "arbitrary") -> Callable:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(shape)
    R, k = shape
    kernel = _make_kernel(matrix, mul_shift)
    # donation: only a square code (R == k, e.g. a decode recovery
    # matrix) has an output the same shape as the input, so only then
    # can the input buffer be aliased (the StripeBatchQueue decode path
    # that keeps live HBM ~one batch deep)
    alias = {1: 0} if (donate and R == k and not interpret) else {}

    def run(words3: jax.Array, seed: jax.Array) -> jax.Array:
        kk, T, L = words3.shape
        assert kk == k and L == LANES and T % tile == 0, (kk, T, L)
        return instrumented_pallas_call(
            kernel, family="gf256_pallas",
            out_shape=jax.ShapeDtypeStruct((R, T, LANES), jnp.uint32),
            grid=(T // tile,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((k, tile, LANES), lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((R, tile, LANES), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
            compiler_params=_compiler_params(
                pltpu, dimension_semantics=(dimsem,)),
            input_output_aliases=alias,
            interpret=interpret,
        )(seed, words3)

    return (instrumented_jit(run, family="gf256_pallas",
                             donate_argnums=(0,)) if alias
            else instrumented_jit(run, family="gf256_pallas"))


def encode_planes(matrix: np.ndarray, words3, seed=None, *,
                  tile: int = DEFAULT_TILE, interpret: bool | None = None,
                  mul_shift: bool = False, donate: bool = False,
                  dimsem: str = "arbitrary"):
    """Apply GF(2^8) matrix (R x k) to packed planes u32 [k, T, 128].

    T must be a multiple of `tile` (callers control the batch shape; the
    StripeBatchQueue and the bench both produce power-of-two tiles).
    Returns u32 [R, T, 128].  `interpret` defaults to True off-TPU so
    the same code path is testable on the CPU backend.  donate=True
    hands the input buffer to XLA when the code is square (R == k);
    the caller must not reuse it afterwards.
    """
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if seed is None:
        seed = jnp.zeros((1,), jnp.uint32)
    # cephlint: disable=no-d2h-on-hot-path — `matrix` is the k x m
    # COEFFICIENT matrix (metadata-scale, host numpy by construction
    # two lines up); tobytes() keys the jit cache, no device buffer
    # is touched
    fn = _compiled(matrix.tobytes(), matrix.shape, tile, interpret,
                   mul_shift, donate, dimsem)
    # sanctioned h2d upload of the pre-packed words, not a payload
    # fetch back to host  # cephlint: disable=no-d2h-on-hot-path
    return fn(jnp.asarray(words3, dtype=jnp.uint32), seed)


def pack_planes(x: np.ndarray) -> np.ndarray:
    """Host helper: uint8 [k, n] -> u32 [k, T, 128] (n % 512 == 0)."""
    k, n = x.shape
    assert n % (4 * LANES) == 0, n
    return np.ascontiguousarray(x).view("<u4").reshape(k, -1, LANES)


def unpack_planes(words3: np.ndarray) -> np.ndarray:
    """Host helper: u32 [R, T, 128] -> uint8 [R, n]."""
    w = np.ascontiguousarray(np.asarray(words3), dtype=np.uint32)
    return w.view(np.uint8).reshape(w.shape[0], -1)


# ---------------------------------------------------------------------------
# Interleaved layout: planes stored (T, k, 128) so each grid step's
# input block is ONE contiguous DMA (the (k, T, 128) layout issues k
# strided slab reads per step).  Same network, same bytes.
# ---------------------------------------------------------------------------

def _make_kernel_interleaved(matrix: np.ndarray,
                             mul_shift: bool = False) -> Callable:
    """Kernel over refs: (seed u32[1], x u32[S,k,128], o u32[S,R,128])."""
    mat, R, k, max_bit = _net_matrix_meta(matrix)

    def kernel(seed_ref, x_ref, o_ref):
        seed = seed_ref[0]
        acc = [None] * R
        for j in range(k):
            p = x_ref[:, j, :] ^ seed
            for b in range(max(max_bit[j], 1)):
                if b > 0:
                    p = _double_word(p, mul_shift)
                for i in range(R):
                    if (mat[i][j] >> b) & 1:
                        acc[i] = p if acc[i] is None else acc[i] ^ p
        zero = jnp.zeros_like(x_ref[:, 0, :])
        for i in range(R):
            o_ref[:, i, :] = acc[i] if acc[i] is not None else zero

    return kernel


@functools.lru_cache(maxsize=64)
def _compiled_interleaved(matrix_bytes: bytes, shape: Tuple[int, int],
                          tile: int, interpret: bool,
                          mul_shift: bool = False) -> Callable:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    matrix = np.frombuffer(matrix_bytes, dtype=np.uint8).reshape(shape)
    R, k = shape
    kernel = _make_kernel_interleaved(matrix, mul_shift)

    @functools.partial(instrumented_jit, family="gf256_pallas")
    def run(words3: jax.Array, seed: jax.Array) -> jax.Array:
        T, kk, L = words3.shape
        assert kk == k and L == LANES and T % tile == 0, (T, kk, L)
        return instrumented_pallas_call(
            kernel, family="gf256_pallas",
            out_shape=jax.ShapeDtypeStruct((T, R, LANES), jnp.uint32),
            grid=(T // tile,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((tile, k, LANES), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((tile, R, LANES), lambda i: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            compiler_params=_compiler_params(
                pltpu, dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(seed, words3)

    return run


def encode_planes_interleaved(matrix: np.ndarray, words3, seed=None, *,
                              tile: int = DEFAULT_TILE,
                              interpret: bool | None = None,
                              mul_shift: bool = False):
    """Apply GF(2^8) matrix (R x k) to interleaved planes u32
    [T, k, 128] -> u32 [T, R, 128].  T must be a multiple of `tile`."""
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if seed is None:
        seed = jnp.zeros((1,), jnp.uint32)
    fn = _compiled_interleaved(matrix.tobytes(), matrix.shape, tile,
                               interpret, mul_shift)
    return fn(jnp.asarray(words3, dtype=jnp.uint32), seed)
