"""Shared in-jit loop measurement harness for engine benchmarks.

The axon tunnel (~94 ms RTT; block_until_ready not a true sync) makes
per-dispatch timing meaningless, so every EC engine benchmark measures
the same way: iterations loop INSIDE one jit, each iteration XORs an
anti-hoisting seed into the input (so XLA cannot hoist the encode as
loop-invariant), outputs fold into an xor accumulator, and only a u32
digest is fetched.  bench.py, tools/tpu_minibench.py and
tools/tpu_tune.py all use THIS helper — the measurement protocol lives
in one place (review finding: four hand copies drift).
"""

from __future__ import annotations

import time


LANES = 128


def gen_planes(k: int, T: int, interleaved: bool = False):
    """Device-resident deterministic batch: u32 planes (k,T,128) (or
    (T,k,128) interleaved) from iota -> splitmix mix32.  The numpy twin
    for oracle pins is mix32.mix_np over the same iota — keeping the
    generator HERE (one copy) is what makes bench/minibench/tune
    numbers and their pins comparable."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ceph_tpu.ops.mix32 import mix_jnp

    shape = (T, k, LANES) if interleaved else (k, T, LANES)

    @jax.jit
    def g():
        return mix_jnp(lax.iota(jnp.uint32, k * T * LANES).reshape(shape))

    return g()


def xla_swar_engine(net, R: int):
    """enc(words3, seed) for the XLA-graph SWAR network `net` over
    planar (k, T, 128) batches -> (R, T, 128)."""
    def enc(w3, seed):
        k, T, _ = w3.shape
        return net((w3 ^ seed[0]).reshape(k, -1)).reshape(R, T, LANES)

    return enc


def seeded_loop_runner(enc, out_shape, iters: int):
    """jit'd runner: enc(words, seed_u32[1]) -> u32[out_shape] folded
    over `iters` seeded iterations; returns a scalar digest."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(w3):
        def body(i, acc):
            s = jnp.full((1,), i, jnp.uint32)
            return acc ^ enc(w3, s)
        o = lax.fori_loop(0, iters, body, jnp.zeros(out_shape, jnp.uint32))
        return jnp.sum(o & 0xFF)

    return run


def timed_best(run, w3, reps: int = 2) -> float:
    """Compile+warm once (digest fetch = the only true sync on this
    rig), then best-of-`reps` wall seconds."""
    int(run(w3))
    best = 1e18
    for _ in range(reps):
        t0 = time.perf_counter()
        int(run(w3))
        best = min(best, time.perf_counter() - t0)
    return best


def loop_rate_gbps(enc, w3, out_shape, iters: int, object_bytes: int,
                   reps: int = 2) -> float:
    """GB/s of `enc` over `iters` in-jit iterations on batch `w3`."""
    dt = timed_best(seeded_loop_runner(enc, out_shape, iters), w3, reps)
    return iters * object_bytes / dt / 1e9
