"""Shared in-jit loop measurement harness for engine benchmarks.

The axon tunnel (~94 ms RTT; block_until_ready not a true sync) makes
per-dispatch timing meaningless, so every EC engine benchmark measures
the same way: iterations loop INSIDE one jit, each iteration XORs an
anti-hoisting seed into the input (so XLA cannot hoist the encode as
loop-invariant), outputs fold into an xor accumulator, and only a u32
digest is fetched.  bench.py, tools/tpu_minibench.py and
tools/tpu_tune.py all use THIS helper — the measurement protocol lives
in one place (review finding: four hand copies drift).
"""

from __future__ import annotations

import time


def seeded_loop_runner(enc, out_shape, iters: int):
    """jit'd runner: enc(words, seed_u32[1]) -> u32[out_shape] folded
    over `iters` seeded iterations; returns a scalar digest."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(w3):
        def body(i, acc):
            s = jnp.full((1,), i, jnp.uint32)
            return acc ^ enc(w3, s)
        o = lax.fori_loop(0, iters, body, jnp.zeros(out_shape, jnp.uint32))
        return jnp.sum(o & 0xFF)

    return run


def timed_best(run, w3, reps: int = 2) -> float:
    """Compile+warm once (digest fetch = the only true sync on this
    rig), then best-of-`reps` wall seconds."""
    int(run(w3))
    best = 1e18
    for _ in range(reps):
        t0 = time.perf_counter()
        int(run(w3))
        best = min(best, time.perf_counter() - t0)
    return best


def loop_rate_gbps(enc, w3, out_shape, iters: int, object_bytes: int,
                   reps: int = 2) -> float:
    """GB/s of `enc` over `iters` in-jit iterations on batch `w3`."""
    dt = timed_best(seeded_loop_runner(enc, out_shape, iters), w3, reps)
    return iters * object_bytes / dt / 1e9
