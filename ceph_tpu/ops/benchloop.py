"""Shared in-jit loop measurement harness for engine benchmarks.

The axon tunnel (~94 ms RTT; block_until_ready not a true sync) makes
per-dispatch timing meaningless, so every EC engine benchmark measures
the same way: iterations loop INSIDE one jit, each iteration XORs an
anti-hoisting seed into the input (so XLA cannot hoist the encode as
loop-invariant), each iteration's output reduces to a SCALAR digest
accumulated across the loop (sum_digest_runner; the xor-fold variant
seeded_loop_runner survives for comparisons but adds a full-size
accumulator pass a pallas_call cannot fuse away), and only that digest
is fetched.  bench.py, tools/tpu_minibench.py and tools/tpu_tune.py
all measure through THIS module — the protocol lives in one place
(review finding: four hand copies drift).

Round-5 finding (PROBE2/PROBE3 artifacts): at FIXED small iteration
counts every engine "measured" (iters x size)/RTT — wall time was one
tunnel round trip no matter the work, so the number was the tunnel's,
not the chip's (the round-4 artifacts' 5-12 GB/s EC rates and the
27 GB/s session-2 observation were all this).  `calibrated_rate` is
the fix: grow the in-jit iteration count until one dispatch's wall
clock dwarfs the RTT, capped below the ~100 s axon worker-crash
threshold.  With it, the same kernels measure 180-290 GB/s.
"""

from __future__ import annotations

import functools
import time

from ceph_tpu.tpu.devwatch import instrumented_jit


LANES = 128


def gen_planes(k: int, T: int, interleaved: bool = False):
    """Device-resident deterministic batch: u32 planes (k,T,128) (or
    (T,k,128) interleaved) from iota -> splitmix mix32.  The numpy twin
    for oracle pins is mix32.mix_np over the same iota — keeping the
    generator HERE (one copy) is what makes bench/minibench/tune
    numbers and their pins comparable."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ceph_tpu.ops.mix32 import mix_jnp

    shape = (T, k, LANES) if interleaved else (k, T, LANES)

    @functools.partial(instrumented_jit, family="benchloop")
    def g():
        return mix_jnp(lax.iota(jnp.uint32, k * T * LANES).reshape(shape))

    return g()


def xla_swar_engine(net, R: int):
    """enc(words3, seed) for the XLA-graph SWAR network `net` over
    planar (k, T, 128) batches -> (R, T, 128)."""
    def enc(w3, seed):
        k, T, _ = w3.shape
        return net((w3 ^ seed[0]).reshape(k, -1)).reshape(R, T, LANES)

    return enc


def seeded_loop_runner(enc, out_shape, iters: int):
    """jit'd runner: enc(words, seed_u32[1]) -> u32[out_shape] folded
    over `iters` seeded iterations; returns a scalar digest."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @functools.partial(instrumented_jit, family="benchloop")
    def run(w3):
        def body(i, acc):
            s = jnp.full((1,), i, jnp.uint32)
            return acc ^ enc(w3, s)
        o = lax.fori_loop(0, iters, body, jnp.zeros(out_shape, jnp.uint32))
        return jnp.sum(o & 0xFF)

    return run


def timed_best(run, w3, reps: int = 2) -> float:
    """Compile+warm once (digest fetch = the only true sync on this
    rig), then best-of-`reps` wall seconds."""
    int(run(w3))
    best = 1e18
    for _ in range(reps):
        t0 = time.perf_counter()
        int(run(w3))
        best = min(best, time.perf_counter() - t0)
    return best


def loop_rate_gbps(enc, w3, out_shape, iters: int, object_bytes: int,
                   reps: int = 2) -> float:
    """GB/s of `enc` over `iters` in-jit iterations on batch `w3`."""
    dt = timed_best(seeded_loop_runner(enc, out_shape, iters), w3, reps)
    return iters * object_bytes / dt / 1e9


def sum_digest_runner(enc, iters: int):
    """jit'd runner: per-iteration scalar digest (sum of out & 0xff)
    accumulated as a scalar.  Cheaper than the xor-fold runner for
    pallas engines: the fold's full-size accumulator pass cannot be
    fused into a pallas_call the way XLA fuses it into its own graph."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @functools.partial(instrumented_jit, family="benchloop")
    def run(w3):
        def body(i, acc):
            s = jnp.full((1,), i, jnp.uint32)
            return acc + jnp.sum(enc(w3, s) & 0xFF, dtype=jnp.uint32)
        return lax.fori_loop(0, iters, body, jnp.uint32(0))

    return run


def calibrate_loop(make_run, *, start_iters: int = 16,
                   target_s: float = 1.5, cap_s: float = 25.0,
                   max_iters: int = 1 << 20):
    """(iters, wall_s): grow an in-jit iteration count until one
    dispatch's wall clock reaches `target_s` — the only honest timing
    on a tunnel whose RTT swallows fixed-iteration runs whole (see
    module docstring).  `make_run(iters)` returns a zero-arg callable
    whose invocation runs + truly syncs (fetches) one dispatch.
    The projected next dispatch is clamped to `cap_s` (the axon worker
    crashes ~100 s dispatches) and `max_iters`."""
    target_s = min(target_s, cap_s)  # a target past the cap can't halt
    iters = int(start_iters)
    while True:
        run = make_run(iters)
        run()  # compile + warm (fetch = the only true sync)
        t0 = time.perf_counter()
        run()
        dt = time.perf_counter() - t0
        if dt >= target_s or iters >= max_iters:
            return iters, dt
        ips = iters / max(dt, 1e-4)  # iters/s, floor-biased by the RTT
        want_s = min(target_s * 1.3, cap_s)
        nxt = max(iters * 2, int(ips * want_s))
        # real dispatch-wall clamp on BOTH growth arms (the doubling
        # arm can outrun the projection when target_s approaches cap_s)
        iters = min(max_iters, nxt, max(iters, int(ips * cap_s)))


def calibrated_rate(enc, w3, object_bytes: int, *, start_iters: int = 16,
                    target_s: float = 1.5, cap_s: float = 25.0,
                    max_iters: int = 1 << 20, runner=sum_digest_runner):
    """(gbps, iters, wall_s) for an engine over batch `w3` under the
    calibrated protocol (see calibrate_loop)."""
    def make_run(iters):
        run = runner(enc, iters)
        return lambda: int(run(w3))

    iters, dt = calibrate_loop(make_run, start_iters=start_iters,
                               target_s=target_s, cap_s=cap_s,
                               max_iters=max_iters)
    return object_bytes * iters / dt / 1e9, iters, dt
