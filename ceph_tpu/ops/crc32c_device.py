"""crc32c on device, batched over stripe planes — fused with encode.

The reference computes ECUtil::HashInfo per-shard crcs on the CPU from
host bufferlists (src/osd/ECUtil.h:101-122).  With payloads device-
resident, a host crc would force a d2h fetch of every chunk — the
exact tunnel tax the staging pipeline removes — so the crc runs ON the
device, in the same coalesced batch as the GF matmul, and only the
4-byte digests cross back (metadata, not payload).

Formulation: CRC-32C is a GF(2) polynomial remainder; the classic
table method is a per-byte affine update ``c' = T[(c ^ b) & 0xff] ^
(c >> 8)``.  Batched the TPU way: every (job, shard) chunk of the
coalesced batch becomes one ROW of a [rows, cols] lane matrix, and
slicing-by-8 tables (T0..T7, 256-entry u32 gathers) consume 8 bytes of
EVERY row per ``fori_loop`` step — a whole [jobs x (k+m)] batch crcs
in ``cols/8`` vectorized steps.  Per-row length masking handles the
pow2 padding and non-aligned tails; per-row init values chain running
crcs.  (No per-row offsets inside the kernel: a vmapped
``dynamic_slice`` at per-lane offsets lowers to an O(batch) gather per
step on CPU XLA — measured quadratic; the row layout keeps each step
O(rows).)

Bit-exactness against ``core.crc.crc32c`` (the native slicing-by-8
kernel) is asserted in tier-1 (tests/test_device_datapath.py) across
lengths 0..4KiB including ragged tails and chained calls.

Pure-numpy fallback when jax is absent — same tables, same math — so
the queue's fused path works on codec-less rigs too.
"""

from __future__ import annotations

import functools

import numpy as np

from ceph_tpu.tpu.devwatch import instrumented_jit

_POLY = np.uint32(0x82F63B78)


def _make_tables(n: int = 8) -> np.ndarray:
    """Slicing-by-N tables: T[0] is the classic byte table; T[k+1][i]
    advances T[k][i] one more zero byte."""
    t0 = np.arange(256, dtype=np.uint32)
    for _ in range(8):
        t0 = np.where(t0 & 1, (t0 >> 1) ^ _POLY, t0 >> 1)
    out = np.empty((n, 256), dtype=np.uint32)
    out[0] = t0
    for k in range(1, n):
        prev = out[k - 1]
        out[k] = t0[prev & 0xFF] ^ (prev >> np.uint32(8))
    return out


_TABLES = _make_tables()

try:  # pragma: no branch
    import jax
    import jax.numpy as jnp
    from jax import lax

    _HAVE_JAX = True
except Exception:  # pragma: no cover — codec-less rig
    _HAVE_JAX = False


def _round_up_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


if _HAVE_JAX:

    @functools.lru_cache(maxsize=64)
    def _rows_kernel(R: int, C: int):
        """Compiled crc pass over a [R, C] row batch with per-row
        (length, init).  Cached per shape: callers pad both axes to
        pow2, so the compile set stays small (same discipline as the
        encode matmul shapes)."""
        tables = jnp.asarray(_TABLES)
        W = C // 8

        def kernel(rows, lens, inits):
            c0 = inits ^ jnp.uint32(0xFFFFFFFF)
            nwords = lens // 8

            def word_step(w, c):
                blk = lax.dynamic_slice_in_dim(
                    rows, 8 * w, 8, axis=1).astype(jnp.uint32)
                x = (c ^ (blk[:, 0] | (blk[:, 1] << 8)
                          | (blk[:, 2] << 16) | (blk[:, 3] << 24)))
                nc = (tables[7][x & 0xFF]
                      ^ tables[6][(x >> 8) & 0xFF]
                      ^ tables[5][(x >> 16) & 0xFF]
                      ^ tables[4][(x >> 24) & 0xFF]
                      ^ tables[3][blk[:, 4]]
                      ^ tables[2][blk[:, 5]]
                      ^ tables[1][blk[:, 6]]
                      ^ tables[0][blk[:, 7]])
                return jnp.where(w < nwords, nc, c)

            c = lax.fori_loop(0, W, word_step, c0)

            def tail_step(t, c):
                pos = jnp.minimum(8 * nwords + t, C - 1)
                b = jnp.take_along_axis(
                    rows, pos[:, None], axis=1)[:, 0].astype(jnp.uint32)
                nc = tables[0][(c ^ b) & 0xFF] ^ (c >> 8)
                return jnp.where(8 * nwords + t < lens, nc, c)

            c = lax.fori_loop(0, 8, tail_step, c)
            return c ^ jnp.uint32(0xFFFFFFFF)

        return instrumented_jit(kernel, family="crc32c_device")


def _rows_numpy(rows: np.ndarray, lens, inits) -> np.ndarray:
    """Fallback when jax is absent: per-row NATIVE crc (core.crc reads
    the row views zero-copy).  A whole-matrix python byte loop here
    collapsed EC write throughput orders of magnitude on jax-less rigs
    — the native slicing-by-8 kernel is the right host engine, and the
    rig is all-host anyway."""
    from ceph_tpu.core.crc import crc32c as _host_crc

    out = np.empty(len(lens), dtype=np.uint32)
    for r, (ln, init) in enumerate(zip(lens, inits)):
        out[r] = _host_crc(rows[r, :int(ln)], int(init))
    return out


def crc32c_lanes(rows: np.ndarray, lens, inits=None) -> np.ndarray:
    """crc32c of ``rows[i, :lens[i]]`` for every row, in one batched
    device pass.  ``rows`` uint8 [R, C]; returns u32 [R]."""
    R, C = int(rows.shape[0]), int(rows.shape[1])
    # cephlint: disable=no-d2h-on-hot-path — per-lane lengths/inits:
    # u32 metadata arrays, not payload
    lens = np.asarray(lens, dtype=np.int32)
    inits = (np.zeros(R, dtype=np.uint32) if inits is None
             else np.asarray(inits, dtype=np.uint32))  # cephlint: disable=no-d2h-on-hot-path — metadata
    if R == 0:
        return np.empty(0, dtype=np.uint32)
    if not _HAVE_JAX:
        return _rows_numpy(rows, lens, inits)
    if C % 8:
        rows = np.concatenate(
            [rows, np.zeros((R, 8 - C % 8), dtype=np.uint8)], axis=1)
        C = int(rows.shape[1])
    # cephlint: disable=no-d2h-on-hot-path — the digest fetch: 4 bytes
    # per lane of METADATA crossing back, the point of the fused crc
    return np.asarray(_rows_kernel(R, C)(rows, lens, inits))


def crc32c_rows(full: np.ndarray, offs, lens, inits=None) -> np.ndarray:
    """Per-(job, shard) running crc32c over a coalesced plane batch.

    ``full``: uint8 [S, P] (data planes stacked over coding planes, P
    the padded batch width).  ``offs``/``lens``: J per-job column
    extents within the batch.  Returns u32 [J, S]: the crc of shard
    ``s`` of job ``j`` — exactly what each shard's HashInfo wants,
    fetched as metadata (4 bytes/shard) instead of payload.

    Rows are laid out (job-major) with both axes padded to pow2 so the
    compile set stays bounded; the relayout is part of the same device
    batch as the GF matmul (on CPU rigs it is a host move inside the
    already-counted upload — no extra crossing)."""
    # cephlint: disable=no-d2h-on-hot-path — column extents: metadata
    offs = np.asarray(offs, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)  # cephlint: disable=no-d2h-on-hot-path — metadata
    J, S = len(offs), int(full.shape[0])
    if J == 0:
        return np.empty((0, S), dtype=np.uint32)
    if inits is None:
        inits = np.zeros(J, dtype=np.uint32)
    else:
        inits = np.asarray(inits, dtype=np.uint32)  # cephlint: disable=no-d2h-on-hot-path — metadata
    Jp = _round_up_pow2(J)
    C = max(64, _round_up_pow2(int(lens.max(initial=1))))
    rows = np.zeros((Jp * S, C), dtype=np.uint8)
    rlens = np.zeros(Jp * S, dtype=np.int32)
    rinits = np.zeros(Jp * S, dtype=np.uint32)
    for j in range(J):
        o, ln = int(offs[j]), int(lens[j])
        rows[j * S:(j + 1) * S, :ln] = full[:, o:o + ln]
        rlens[j * S:(j + 1) * S] = ln
        rinits[j * S:(j + 1) * S] = inits[j]
    out = crc32c_lanes(rows, rlens, rinits)
    return out.reshape(Jp, S)[:J]


# pow2-bucketed single-buffer entry (tests, tools, ad-hoc checksums)
_PAD_MIN = 64


def crc32c_dev(data, crc: int = 0) -> int:
    """Device crc32c of one buffer; chain by passing the prior value.
    Pads to a pow2 length bucket so ad-hoc lengths reuse compiles."""
    if isinstance(data, np.ndarray):
        arr = data.reshape(-1).view(np.uint8)
    else:
        arr = np.frombuffer(data, dtype=np.uint8)
    n = arr.size
    C = max(_PAD_MIN, _round_up_pow2(n))
    rows = np.zeros((1, C), dtype=np.uint8)
    rows[0, :n] = arr
    return int(crc32c_lanes(rows, [n], [crc])[0])
