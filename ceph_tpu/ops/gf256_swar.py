"""GF(2^8) coefficient-matrix multiply as a SWAR xor network — the fast
erasure-code engine.

The round-1 engine lowered RS codes to an int8 bit-plane matmul on the
MXU.  Profiling showed the kernel was VPU-bound on the bit
extraction/packing around the matmul (each byte occupies a whole 32-bit
lane during extraction), capping throughput far below HBM.  This engine
keeps the bytes PACKED — four per 32-bit lane — and evaluates the code
as a fixed xor/shift network (SWAR: SIMD-within-a-register):

- doubling a packed word (multiply every byte by x in GF(2^8), poly
  0x11d): ``((v << 1) & 0xfefefefe) ^ (((v >> 7) & 0x01010101) * 0x1d)``
- multiply by a constant c: xor of the doubled powers selected by c's
  set bits (the powers are shared across all m output rows)
- the whole (m x k) coefficient matrix unrolls, at trace time, into
  ~`7k` doublings + `popcount(matrix)` xors per word — ~14 VPU ops per
  input byte, an order of magnitude less VPU work than bit-plane
  extraction, and no MXU dependency at all.

This mirrors what the reference's SIMD backends do per-architecture
(gf-complete's CLMUL/SSSE3 regions, src/erasure-code/jerasure/
CMakeLists.txt:12-38; ISA-L's asm kernels behind ec_encode_data,
src/erasure-code/isa/ErasureCodeIsa.cc:128) — but expressed once in
jnp, fused by XLA, and identical on TPU and CPU.

Scope: any code expressed as a GF(2^8) COEFFICIENT matrix (reed_sol,
isa vandermonde/cauchy, lrc, shec, clay).  Bit-matrix techniques
(liberation family) keep the general GF(2) engine in ops.gf2_matmul.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.tpu.devwatch import instrumented_jit

# set when this rig's compiler rejects the Pallas kernel (remote-compile
# failure): the process then routes every encode via the XLA graph path
_pallas_broken = False
_native_rs = None  # None = unresolved, False = unavailable


def _native_rs_encode():
    """Resolve the native SIMD encode once per process (the resolver
    may shell out to make when the lib is unbuilt — never per call)."""
    global _native_rs
    if _native_rs is None:
        try:
            from ceph_tpu import _native

            _native.lib()  # force build/load now, not per call
            _native_rs = _native.rs_encode_simd
        except Exception:  # pragma: no cover — no native lib built
            _native_rs = False
    return _native_rs or None

_LOW7 = np.uint32(0x7F7F7F7F)
_HI = np.uint32(0x80808080)
_ONES = np.uint32(0x01010101)
_RED = np.uint32(0x1D)  # poly 0x11d reduction byte


def _double(v: jax.Array) -> jax.Array:
    """Multiply every packed byte by x (i.e. 2) in GF(2^8)."""
    carry = (v >> 7) & _ONES
    return ((v & _LOW7) << 1) ^ (carry * _RED)


def _build_network(matrix: np.ndarray) -> Callable[[jax.Array], jax.Array]:
    """Unroll (R x k) GF(2^8) coefficients into a packed-word function.

    Returns f(words: u32 [k, W]) -> u32 [R, W].
    """
    R, k = matrix.shape
    mat = [[int(c) for c in row] for row in matrix]
    # which powers of two each column actually needs (skip dead doublings)
    need_bits = [0] * k
    for row in mat:
        for j, c in enumerate(row):
            need_bits[j] |= c
    max_bit = [nb.bit_length() for nb in need_bits]

    def apply(words: jax.Array) -> jax.Array:
        acc = [None] * R
        for j in range(k):
            p = words[j]
            for b in range(max(max_bit[j], 1)):
                if b > 0:
                    p = _double(p)
                for i in range(R):
                    if (mat[i][j] >> b) & 1:
                        acc[i] = p if acc[i] is None else acc[i] ^ p
        zero = jnp.zeros_like(words[0])
        return jnp.stack([a if a is not None else zero for a in acc])

    return apply


_cache: Dict[Tuple[bytes, Tuple[int, int]], Callable] = {}


def _compiled(matrix: np.ndarray, donate: bool = False,
              family: str = "gf256_swar") -> Callable:
    # cephlint: disable=no-d2h-on-hot-path — coefficient-matrix cache
    # key: `matrix` is metadata-scale host numpy, not a device buffer
    key = (matrix.tobytes(), matrix.shape, donate, family)
    fn = _cache.get(key)
    if fn is None:
        net = _build_network(matrix)

        def run(x: jax.Array) -> jax.Array:
            k, n = x.shape
            words = jax.lax.bitcast_convert_type(
                x.reshape(k, n // 4, 4), jnp.uint32
            )
            out = net(words)
            return jax.lax.bitcast_convert_type(out, jnp.uint8).reshape(
                matrix.shape[0], n
            )

        # donate=True aliases the input planes for scratch: once
        # encoded, the source buffer is dead weight, so HBM holds ~one
        # batch instead of two.  Only for callers handing over a fresh
        # per-batch buffer (the StripeBatchQueue pipeline) — a donated
        # buffer cannot be reused by the caller afterwards.
        # the caller's devwatch family (default "gf256_swar") tags the
        # compile so shape-bucket discipline and the steady guard
        # attribute it to the right kernel class (clay's coupled-layer
        # matmuls run under "gf256_clay")
        fn = (instrumented_jit(run, family=family,
                               donate_argnums=(0,)) if donate
              else instrumented_jit(run, family=family))
        _cache[key] = fn
    return fn


def _compiled_words(matrix: np.ndarray,
                    family: str = "gf256_swar") -> Callable:
    """jit of the network over PRE-PACKED u32 words [k, W] -> [R, W]
    (no device-side bitcasts — see gf_matmul_bytes' CPU path)."""
    # cephlint: disable=no-d2h-on-hot-path — coefficient-matrix cache
    # key: `matrix` is metadata-scale host numpy, not a device buffer
    key = (matrix.tobytes(), matrix.shape, "words", family)
    fn = _cache.get(key)
    if fn is None:
        fn = _cache[key] = instrumented_jit(
            _build_network(matrix), family=family)
    return fn


def gf_matmul_bytes(matrix: np.ndarray, x, donate: bool = False,
                    family: str = "gf256_swar"):
    """Apply a GF(2^8) coefficient matrix (R x k) to byte rows [k, n].

    n is padded to a word multiple internally; returns uint8 [R, n]
    (a jax array on accelerators; MAY be a host ndarray view on the
    CPU backend — every consumer treats the result as array-like).
    `donate` hands the input buffer to XLA (see _compiled) — pass True
    only when `x` is a fresh buffer this call may consume.  On the CPU
    host-view path below, donate is a NO-OP (the input is a host
    ndarray the caller keeps owning); the contract only bites on
    accelerators.

    CPU backend + host input: XLA-CPU lowers the u8<->u32
    bitcast_convert_type pair catastrophically (measured SLOWER than
    the entire xor network), while a numpy .view(uint32) reinterprets
    for free — so the packing/unpacking happens host-side and the
    device program is the pure u32 network (~6x end-to-end on CPU).
    TPU keeps the device-side bitcasts: they are layout no-ops there
    and the data stays resident.
    """
    # cephlint: disable=no-d2h-on-hot-path — coefficient matrix:
    # metadata-scale, host-built; no payload crosses here
    matrix = np.asarray(matrix, dtype=np.uint8)
    if isinstance(x, np.ndarray) and jax.default_backend() == "cpu":
        x = np.ascontiguousarray(x, dtype=np.uint8)
        k, n = x.shape
        # native AVX2 split-nibble kernel (csrc/gf256_simd.cc): beats
        # the jit'd network at EVERY size on the CPU backend, and at
        # small ops (the 4 KiB BASELINE row) the ~25 us jax dispatch
        # alone capped the old path at ~0.1 GB/s — a ctypes call is
        # ~2 us (round-5 fix for VERDICT r4 item 5).  Availability is
        # resolved ONCE: a missing lib must not re-run the make probe
        # per call (review finding).
        enc = _native_rs_encode()
        if enc is not None:
            return enc(matrix, x)
        pad = (-n) % 4
        if pad:
            x = np.pad(x, ((0, 0), (0, pad)))
        words = x.view(np.uint32)
        # explicit CPU-backend host path (branch condition above):
        # the data never left host memory, np.asarray is a view
        # materialization, not a device fetch
        # cephlint: disable=no-d2h-on-hot-path
        out32 = np.asarray(_compiled_words(matrix, family)(words))
        out = out32.view(np.uint8)
        return out[:, :n] if pad else out
    # sanctioned h2d upload of the encode input, not a fetch
    # cephlint: disable=no-d2h-on-hot-path
    x = jnp.asarray(x, dtype=jnp.uint8)
    k, n = x.shape
    if ((jax.default_backend() == "tpu"
         or os.environ.get("CEPH_TPU_FORCE_PALLAS") == "1")
            and n % 512 == 0
            and os.environ.get("CEPH_TPU_NO_PALLAS") != "1"):
        # TPU fast path: the Pallas VMEM-tiled kernel (the XLA graph
        # lowering materializes the network's intermediates to HBM —
        # measured ~2-3x slower on hardware).  Same bytes, pinned
        # equal by tests/test_gf256_pallas.py (incl. this wrapper's
        # bitcast round-trip).  donate passes through: the kernel
        # aliases the input buffer when shapes allow (square decode).
        from ceph_tpu.ops import gf256_pallas

        R = matrix.shape[0]
        words3 = jax.lax.bitcast_convert_type(
            x.reshape(k, n // 4, 4), jnp.uint32
        ).reshape(k, -1, gf256_pallas.LANES)
        T = words3.shape[1]
        # tile capped at 512: one rig's remote compiler rejects the
        # t1024 kernel (scoped-VMEM limit), and 512 measures within
        # noise of 1024 on hardware anyway
        tile = max(t for t in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
                   if T % t == 0)
        # interpret=None: real lowering on TPU, interpreter elsewhere
        # (lets tests exercise THIS wrapper via CEPH_TPU_FORCE_PALLAS)
        global _pallas_broken
        if not _pallas_broken:
            try:
                out3 = gf256_pallas.encode_planes(
                    matrix, words3, tile=tile, interpret=None,
                    donate=donate)
                # u32 (R, T, 128) -> u8 (R, T, 128, 4) -> (R, n)
                return jax.lax.bitcast_convert_type(
                    out3, jnp.uint8).reshape(R, n)
            except jax.errors.JaxRuntimeError:
                # this rig's compiler rejects the kernel (observed:
                # remote-compile HTTP 500 on some libtpu builds) —
                # fall back to the XLA graph lowering for the rest of
                # the process instead of failing product encodes
                _pallas_broken = True
        # fall through to the XLA network path below (x is intact:
        # the failure happens at compile, before any donation)
    pad = (-n) % 4
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)))
    out = _compiled(matrix, donate, family)(x)
    if pad:
        out = out[:, :n]
    return out
