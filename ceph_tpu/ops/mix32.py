"""Shared u32 splitmix-style mixer, numpy and jnp twins.

Benchmarks generate data ON DEVICE (the axon tunnel's ~5 MB/s h2d makes
staging real payloads pointless) and pin correctness against the native
oracle on a HOST mirror of the same bytes — which only works if the
device generator and the host mirror compute bit-identical streams.
Keeping both twins in one module removes the four-copy drift hazard the
round-4 review flagged.
"""

from __future__ import annotations

import numpy as np

_C1, _C2, _C3 = 0x9E3779B9, 0x85EBCA6B, 0xC2B2AE35


def mix_np(i: np.ndarray) -> np.ndarray:
    """u32 ndarray -> mixed u32 ndarray (wrapping arithmetic)."""
    i = i.astype(np.uint32, copy=False)
    z = (i ^ np.uint32(_C1)) * np.uint32(_C2)
    z = (z ^ (z >> np.uint32(13))) * np.uint32(_C3)
    return z ^ (z >> np.uint32(16))


def mix_jnp(i):
    """jnp u32 array -> mixed u32 array; EXACTLY mirrors mix_np."""
    import jax.numpy as jnp

    z = (i ^ jnp.uint32(_C1)) * jnp.uint32(_C2)
    z = (z ^ (z >> jnp.uint32(13))) * jnp.uint32(_C3)
    return z ^ (z >> jnp.uint32(16))
