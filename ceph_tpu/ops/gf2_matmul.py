"""GF(2) bit-sliced matmul over byte streams — the erasure-code engine.

Every technique in the reference's codec family is linear over GF(2):

- RS over GF(2^8) (jerasure reed_sol_*, isa): each generator coefficient
  c is an 8x8 GF(2) companion block (gf.const_to_bitmatrix), so encode is
  one (8m x 8k) @ (8k x N) binary matmul over bit-planes of the chunk
  bytes (reference semantics: jerasure_matrix_encode,
  src/erasure-code/jerasure/ErasureCodeJerasure.cc:155; ISA-L
  ec_encode_data, src/erasure-code/isa/ErasureCodeIsa.cc:128).
- Bit-matrix codes (cauchy_*, liberation family) are *already* GF(2)
  matrices applied to w packets per chunk — same engine, different
  plane layout.
- Parity/XOR (RAID4-style, the isa single-erasure fast path
  src/erasure-code/isa/ErasureCodeIsa.cc:198) is the all-ones row.

On TPU the binary matmul rides the MXU as int8 x int8 -> int32 with a
mod-2 epilogue.  The Pallas kernel fuses bitplane expansion, matmul,
mod-2 and bit-packing in VMEM so HBM traffic is exactly k bytes read +
m bytes written per stripe column (the bandwidth-optimal schedule).
The jnp path expresses the same computation for CPU tests and as an XLA
fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ceph_tpu.tpu.devwatch import (instrumented_jit,
                                   instrumented_pallas_call)

try:  # pallas TPU backend (absent on CPU-only test runs)
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None


# ---------------------------------------------------------------------------
# jnp reference path
# ---------------------------------------------------------------------------


def bytes_to_bitplanes(x: jax.Array) -> jax.Array:
    """uint8 [k, n] -> int8 bitplanes [8k, n]; row j*8+b = bit b of row j."""
    k, n = x.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[:, None, :] >> shifts[None, :, None]) & jnp.uint8(1)
    return bits.reshape(k * 8, n).astype(jnp.int8)


def bitplanes_to_bytes(planes: jax.Array) -> jax.Array:
    """int32/int8 bitplanes [8m, n] -> uint8 [m, n]."""
    m8, n = planes.shape
    m = m8 // 8
    grouped = planes.reshape(m, 8, n).astype(jnp.int32)
    weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    # int32 accumulation: Mosaic/Pallas doesn't lower unsigned reductions
    return (grouped * weights).sum(axis=1, dtype=jnp.int32).astype(jnp.uint8)


def gf2_matmul_bytes_ref(mbits: jax.Array, x: jax.Array) -> jax.Array:
    """Apply a GF(2) bit-matrix to byte rows: [R8, K8] x uint8 [k, n].

    mbits: int8 (R8 x K8) binary matrix with R8 = 8*rows_out, K8 = 8*k.
    Returns uint8 [rows_out, n].
    """
    planes = bytes_to_bitplanes(x)
    acc = jax.lax.dot_general(
        mbits,
        planes,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return bitplanes_to_bytes(acc & 1)


# ---------------------------------------------------------------------------
# Pallas fused kernel
# ---------------------------------------------------------------------------


def _gf2_kernel(mbits_ref, x_ref, out_ref):
    """One (k, TN) tile: expand -> int8 matmul -> mod 2 -> pack."""
    x = x_ref[:]  # uint8 [k, TN]
    k, tn = x.shape
    # Mosaic only legalizes 32-bit iota/shifts: extract bits in int32
    shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    xi = x.astype(jnp.int32)
    bits = ((xi[:, None, :] >> shifts) & 1).astype(jnp.int8)
    planes = bits.reshape(k * 8, tn)
    acc = jax.lax.dot_general(
        mbits_ref[:],
        planes,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc = acc & 1
    m8 = acc.shape[0]
    weights = jnp.int32(1) << jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
    packed = (acc.reshape(m8 // 8, 8, tn) * weights).sum(
        axis=1, dtype=jnp.int32
    )
    out_ref[:] = packed.astype(jnp.uint8)


@functools.partial(instrumented_jit, family="gf2_matmul",
                   static_argnames=("tile_n",))
def gf2_matmul_bytes_pallas(
    mbits: jax.Array, x: jax.Array, tile_n: int = 2048
) -> jax.Array:
    """Fused TPU kernel: uint8 in / uint8 out, bitplanes never touch HBM."""
    r8, k8 = mbits.shape
    k, n = x.shape
    assert k8 == 8 * k and r8 % 8 == 0
    assert n % tile_n == 0, "pad n to a tile_n multiple"
    grid = (n // tile_n,)
    return instrumented_pallas_call(
        _gf2_kernel, family="gf2_matmul",
        out_shape=jax.ShapeDtypeStruct((r8 // 8, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, k8), lambda i: (0, 0)),
            pl.BlockSpec((k, tile_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((r8 // 8, tile_n), lambda i: (0, i)),
    )(mbits, x)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:  # pragma: no cover
        return False


def gf2_matmul_bytes(mbits: jax.Array, x: jax.Array, *, tile_n: int = 2048):
    """Dispatch: fused Pallas kernel on TPU, XLA reference elsewhere."""
    n = x.shape[1]
    if _on_tpu() and pltpu is not None and n % tile_n == 0:
        return gf2_matmul_bytes_pallas(mbits, x, tile_n=tile_n)
    return _ref_jit(mbits, x)


_ref_jit = instrumented_jit(gf2_matmul_bytes_ref, family="gf2_matmul")


def prepare_bitmatrix(matrix: np.ndarray, w: int = 8) -> np.ndarray:
    """Host-side: GF(2^w) coding matrix -> int8 GF(2) bit-matrix operand."""
    from ceph_tpu.ec import gf

    return gf.matrix_to_bitmatrix(matrix, w).astype(np.int8)
