"""Device kernels (Pallas + jnp) for the hot math.

- gf2_matmul: GF(2) bit-sliced matrix multiply over byte streams — the
  single engine behind every erasure-code technique (RS over GF(2^w),
  Cauchy bit-matrices, XOR parity).
- crush kernels live in ceph_tpu.crush (they are placement math, not
  byte-stream codecs).
"""
