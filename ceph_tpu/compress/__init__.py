"""Compressor plugin family.

Reference role: src/compressor/ (Compressor.h's create/registry,
plugins zlib/snappy/lz4/zstd/brotli) mirrored with the same registry
discipline as the EC plugins: name -> factory, preload at daemon start,
runtime-registrable third-party codecs.  Algorithms here are the
python-native ones (zlib/bz2/lzma from the stdlib) plus a zero-RLE
codec shaped like the storage fast paths (newly written objects are
often sparse).

The required_ratio discipline matches the reference: a compressed block
is only kept when it saves at least 1/8 of the input
(Compressor.h compressor_required_ratio default 0.875).
"""

from ceph_tpu.compress.plugins import (
    Compressor,
    CompressorError,
    CompressorRegistry,
    instance,
)

__all__ = ["Compressor", "CompressorError", "CompressorRegistry",
           "instance"]
