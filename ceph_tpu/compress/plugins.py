"""Compressor implementations + registry (reference: src/compressor/).

Each plugin is a tiny stateless codec with ``compress``/``decompress``
over bytes; the registry resolves names exactly like the EC plugin
registry (ceph_tpu.ec.registry) so daemons can preload and operators
can select per-pool/per-store algorithms by name.
"""

from __future__ import annotations

import bz2
import lzma
import threading
import zlib
from typing import Callable, Dict

import numpy as np


class CompressorError(Exception):
    pass


class Compressor:
    """Base codec (reference Compressor.h)."""

    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data


class ZlibCompressor(Compressor):
    name = "zlib"

    def __init__(self, level: int = 5) -> None:
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, data: bytes) -> bytes:
        try:
            return zlib.decompress(data)
        except zlib.error as e:
            raise CompressorError(f"zlib: {e}") from e


class Bz2Compressor(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data)

    def decompress(self, data: bytes) -> bytes:
        try:
            return bz2.decompress(data)
        except OSError as e:
            raise CompressorError(f"bz2: {e}") from e


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=1)

    def decompress(self, data: bytes) -> bytes:
        try:
            return lzma.decompress(data)
        except lzma.LZMAError as e:
            raise CompressorError(f"lzma: {e}") from e


class ZeroRleCompressor(Compressor):
    """Zero-run-length codec: vectorized numpy scan for the zero runs
    that dominate freshly-provisioned storage (sparse chunks, padded
    stripes).  Frame: sequence of [u8 tag][u32 len] where tag 0 = a run
    of zeros (no payload), tag 1 = literal bytes (payload follows)."""

    name = "zero_rle"

    def compress(self, data: bytes) -> bytes:
        arr = np.frombuffer(data, dtype=np.uint8)
        out = bytearray()
        if len(arr) == 0:
            return bytes(out)
        zero = arr == 0
        # run boundaries
        edges = np.nonzero(np.diff(zero))[0] + 1
        starts = np.concatenate([[0], edges])
        ends = np.concatenate([edges, [len(arr)]])
        for s, e in zip(starts, ends):
            if zero[s]:
                out += b"\x00" + int(e - s).to_bytes(4, "little")
            else:
                out += b"\x01" + int(e - s).to_bytes(4, "little")
                out += data[s:e]
        return bytes(out)

    def decompress(self, data: bytes) -> bytes:
        out = bytearray()
        i = 0
        try:
            while i < len(data):
                tag = data[i]
                n = int.from_bytes(data[i + 1: i + 5], "little")
                i += 5
                if tag == 0:
                    out += b"\x00" * n
                elif tag == 1:
                    out += data[i: i + n]
                    if i + n > len(data):
                        raise CompressorError("zero_rle: truncated")
                    i += n
                else:
                    raise CompressorError(f"zero_rle: bad tag {tag}")
        except IndexError as e:
            raise CompressorError("zero_rle: truncated") from e
        return bytes(out)


class CompressorRegistry:
    """Name -> factory, mirroring ErasureCodePluginRegistry."""

    _instance: "CompressorRegistry | None" = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._factories: Dict[str, Callable[[], Compressor]] = {
            "none": Compressor,
            "zlib": ZlibCompressor,
            "bz2": Bz2Compressor,
            "lzma": LzmaCompressor,
            "zero_rle": ZeroRleCompressor,
        }

    @classmethod
    def instance(cls) -> "CompressorRegistry":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def add(self, name: str, factory: Callable[[], Compressor]) -> None:
        if name in self._factories:
            raise CompressorError(f"compressor {name!r} already registered")
        self._factories[name] = factory

    def names(self):
        return sorted(self._factories)

    def factory(self, name: str) -> Compressor:
        if name not in self._factories:
            raise CompressorError(f"unknown compressor {name!r}")
        return self._factories[name]()


def instance() -> CompressorRegistry:
    return CompressorRegistry.instance()
