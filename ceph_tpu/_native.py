"""ctypes bindings to the native core (csrc/ -> libceph_tpu_native.so).

The native library provides the scalar conformance oracles (GF(2^8) RS,
rjenkins, crush_ln, crush_do_rule over the flattened map) and the CPU
baseline kernels the benchmarks compare the TPU path against.

Build with ``make -C csrc`` (done automatically by tests/conftest.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libceph_tpu_native.so")
_lib = None


def build():
    csrc = os.path.join(os.path.dirname(__file__), os.pardir, "csrc")
    subprocess.run(["make", "-C", csrc, "-s"], check=True)


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        if not os.path.exists(_LIB_PATH):
            build()
        L = ctypes.CDLL(_LIB_PATH)
        try:
            _configure(L)
        except AttributeError:
            # stale .so from an older source tree (missing newer
            # symbols): rebuild once and reload
            build()
            L = ctypes.CDLL(_LIB_PATH)
            _configure(L)
        _lib = L
    return _lib


def _configure(L: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    L.gf256_mul.restype = ctypes.c_uint8
    L.gf256_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
    L.gf256_inv.restype = ctypes.c_uint8
    L.gf256_inv.argtypes = [ctypes.c_uint8]
    L.gf256_rs_encode.restype = None
    L.gf256_rs_encode.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p, u8p,
                                  ctypes.c_int64]
    # c_void_p: accepts both POINTER instances and raw .ctypes.data
    # ints — the latter is the lean hot path (see rs_encode_simd)
    L.gf256_rs_encode_simd.restype = None
    L.gf256_rs_encode_simd.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_void_p,
                                       ctypes.c_void_p, ctypes.c_int64]
    L.gf256_simd_available.restype = ctypes.c_int
    L.gf256_simd_available.argtypes = []
    L.gf256_mat_invert.restype = ctypes.c_int
    L.gf256_mat_invert.argtypes = [u8p, u8p, ctypes.c_int]
    L.gf256_rs_decode_data.restype = ctypes.c_int
    L.gf256_rs_decode_data.argtypes = [u8p, ctypes.c_int, ctypes.c_int, i32p,
                                       u8p, u8p, ctypes.c_int64]
    L.crush_oracle_ln.restype = ctypes.c_int64
    L.crush_oracle_ln.argtypes = [ctypes.c_uint32]
    L.crush_oracle_hash3.restype = ctypes.c_uint32
    L.crush_oracle_hash3.argtypes = [ctypes.c_uint32] * 3
    L.crush_oracle_hash2.restype = ctypes.c_uint32
    L.crush_oracle_hash2.argtypes = [ctypes.c_uint32] * 2
    L.crush_oracle_straw2_choose.restype = ctypes.c_int
    L.crush_oracle_straw2_choose.argtypes = [
        ctypes.c_int32, ctypes.c_int32, i32p, u32p, i32p, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
    ]
    L.crush_oracle_do_rule.restype = ctypes.c_int
    L.crush_oracle_do_rule.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # n_buckets, max_size, max_devices
        i32p, u32p, i32p, i32p, i32p,                    # items, weights, sizes, algs, types
        u32p, ctypes.c_int32,                            # device_weights, weight_max
        i32p, ctypes.c_int32, ctypes.c_int32,            # steps, n_steps, x
        i32p, ctypes.c_int32,                            # result, result_max
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,  # tunables...
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
    ]


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def gf256_mul(a: int, b: int) -> int:
    return lib().gf256_mul(a, b)


def gf256_inv(a: int) -> int:
    return lib().gf256_inv(a)


def rs_encode(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """matrix (m,k) uint8; data (k, len) uint8 -> coding (m, len)."""
    m, k = matrix.shape
    length = data.shape[1]
    matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    coding = np.zeros((m, length), dtype=np.uint8)
    lib().gf256_rs_encode(_u8(matrix), k, m, _u8(data), _u8(coding), length)
    return coding


def rs_encode_simd(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """ISA-L-class encode (AVX2 split-nibble PSHUFB when compiled in,
    scalar fallback otherwise) — the honest CPU bench baseline.

    Kept LEAN on purpose: this is the product CPU-backend hot path for
    small ops (the 4 KiB BASELINE row), where ctypes marshalling used
    to cost ~3x the kernel itself.  The C side memsets `coding`, so
    np.empty suffices; pointer ints ride the c_void_p argtypes."""
    m, k = matrix.shape
    length = data.shape[1]
    if matrix.dtype != np.uint8 or not matrix.flags.c_contiguous:
        matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
    if data.dtype != np.uint8 or not data.flags.c_contiguous:
        data = np.ascontiguousarray(data, dtype=np.uint8)
    coding = np.empty((m, length), dtype=np.uint8)
    lib().gf256_rs_encode_simd(matrix.ctypes.data, k, m,
                               data.ctypes.data, coding.ctypes.data,
                               length)
    return coding


def simd_available() -> bool:
    return bool(lib().gf256_simd_available())


def rs_decode_data(full_gen: np.ndarray, k: int, m: int,
                   survivors: np.ndarray, avail: np.ndarray) -> np.ndarray:
    """Reconstruct the k data rows from k surviving chunk rows."""
    length = avail.shape[1]
    full_gen = np.ascontiguousarray(full_gen, dtype=np.uint8)
    survivors = np.ascontiguousarray(survivors, dtype=np.int32)
    avail = np.ascontiguousarray(avail, dtype=np.uint8)
    out = np.zeros((k, length), dtype=np.uint8)
    rc = lib().gf256_rs_decode_data(_u8(full_gen), k, m, _i32(survivors),
                                    _u8(avail), _u8(out), length)
    if rc:
        raise ValueError("native decode failed (singular submatrix)")
    return out


def crush_ln(x: int) -> int:
    return lib().crush_oracle_ln(x)


def hash3(a: int, b: int, c: int) -> int:
    return lib().crush_oracle_hash3(a, b, c)


def hash2(a: int, b: int) -> int:
    return lib().crush_oracle_hash2(a, b)


def straw2_choose(items: np.ndarray, weights: np.ndarray, sizes: np.ndarray,
                  bno: int, x: int, r: int) -> int:
    n_buckets, max_size = items.shape
    items = np.ascontiguousarray(items, dtype=np.int32)
    weights = np.ascontiguousarray(weights, dtype=np.uint32)
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    return lib().crush_oracle_straw2_choose(
        n_buckets, max_size, _i32(items), _u32(weights), _i32(sizes), bno, x, r
    )


def do_rule(flat, steps: np.ndarray, x: int, result_max: int,
            device_weights: np.ndarray) -> np.ndarray:
    """Run a rule on the flattened map `flat` (see ceph_tpu.crush.map)."""
    steps = np.ascontiguousarray(steps, dtype=np.int32)
    device_weights = np.ascontiguousarray(device_weights, dtype=np.uint32)
    result = np.full(result_max, 0x7FFFFFFF, dtype=np.int32)
    items = np.ascontiguousarray(flat.items, dtype=np.int32)
    weights = np.ascontiguousarray(flat.weights, dtype=np.uint32)
    sizes = np.ascontiguousarray(flat.sizes, dtype=np.int32)
    algs = np.ascontiguousarray(flat.algs, dtype=np.int32)
    types = np.ascontiguousarray(flat.types, dtype=np.int32)
    n = lib().crush_oracle_do_rule(
        items.shape[0], items.shape[1], flat.max_devices,
        _i32(items), _u32(weights), _i32(sizes), _i32(algs), _i32(types),
        _u32(device_weights), len(device_weights),
        _i32(steps), len(steps), x, _i32(result), result_max,
        flat.tunables.choose_total_tries, flat.tunables.choose_local_tries,
        flat.tunables.choose_local_fallback_tries,
        flat.tunables.chooseleaf_descend_once, flat.tunables.chooseleaf_vary_r,
        flat.tunables.chooseleaf_stable,
    )
    return result[:n]
