"""CephFS client speaking to the MDS daemon.

Reference: src/client/Client.cc (the userspace client) sized down:
metadata ops travel to the MDS as MClientRequest over the framework
Messenger; file DATA is striped straight to RADOS by the client (the
file_layout discipline — the MDS never touches data).  Capabilities
arrive with open/create replies; MDS-initiated revokes invoke
`on_cap_revoke` (after flushing any buffered state) and are acked so
the MDS can grant the conflicting client.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ceph_tpu.cephfs import messages as cm
from ceph_tpu.core.lockdep import make_lock
from ceph_tpu.cephfs.fs import CephFS
from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper
from ceph_tpu.msg.message import EntityName, Message
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger

CAP_RD, CAP_WR, CAP_EXCL = cm.CAP_RD, cm.CAP_WR, cm.CAP_EXCL


class MDSError(OSError):
    def __init__(self, rc: int, what: str = "") -> None:
        super().__init__(rc, what or f"mds error {rc}")
        self.rc = rc


class _Waiter:
    def __init__(self) -> None:
        self.ev = threading.Event()
        self.reply: Optional[cm.MClientReply] = None


class FSClient(Dispatcher):
    """One mounted client (reference Client.cc role)."""

    def __init__(self, ctx, ioctx: IoCtx, mds_addr,
                 name: str = "client") -> None:
        self.ctx = ctx
        self.io = ioctx
        self.name = name
        # single addr (rank 0) or {rank: addr} for multi-MDS; requests
        # that land on the wrong rank are redirected by the ESTALE+rank
        # hint (the reference client follows MDS forwards the same way)
        if isinstance(mds_addr, dict):
            self.mds_addrs = {int(r): tuple(a)
                              for r, a in mds_addr.items()}
        else:
            self.mds_addrs = {0: tuple(mds_addr)}
        self.mds_addr = self.mds_addrs[min(self.mds_addrs)]
        self.striper = RadosStriper(ioctx, stripe_unit=65536,
                                    stripe_count=4, object_size=4 << 20)
        self.caps: Dict[str, int] = {}  # path -> held caps
        self.revocations: List[Tuple[str, int]] = []  # observed revokes
        self.on_cap_revoke: Optional[Callable[[str, int], None]] = None
        self._waiters: Dict[int, _Waiter] = {}
        self.request_timeout = 30.0
        self._tid = 0
        self._lock = make_lock("cephfs.client")
        self._closed = threading.Event()
        self.msgr = Messenger(ctx, EntityName("client", id(self) & 0xFFFF))
        self.msgr.add_dispatcher(self)
        self.msgr.start()
        # route cache: path prefix -> rank (learned from redirects)
        self._rank_cache: Dict[str, int] = {}
        for rank in self.mds_addrs:
            self._request("session_open", "/", {"client": name},
                          rank=rank)

    def shutdown(self) -> None:
        self._closed.set()
        self.msgr.shutdown()

    # -- transport ---------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, cm.MClientReply):
            w = self._waiters.get(msg.tid)
            if w:
                w.reply = msg
                w.ev.set()
            return True
        if isinstance(msg, cm.MClientCaps) and msg.op == "revoke":
            # flush-then-ack (the client half of Locker's revoke):
            # buffered state must be visible before the MDS lets a
            # conflicting client in
            self.caps[msg.path] = msg.caps
            self.revocations.append((msg.path, msg.caps))
            if self.on_cap_revoke:
                try:
                    self.on_cap_revoke(msg.path, msg.caps)
                except Exception:
                    pass
            conn.send(cm.MClientCaps("ack", msg.path, msg.caps,
                                     self.name))
            return True
        return False

    def _request(self, op: str, path: str, args: Optional[dict] = None,
                 timeout: Optional[float] = None,
                 rank: Optional[int] = None) -> cm.MClientReply:
        timeout = timeout if timeout is not None else self.request_timeout
        if rank is None:
            rank = self._route(path)
        for hop in range(6):  # redirects converge in one hop normally
            addr = self.mds_addrs.get(rank)
            if addr is None:
                raise MDSError(-22, f"redirected to unknown MDS rank "
                               f"{rank} (pinned to a dead rank?)")
            rep = self._request_to(addr, op, path, args, timeout)
            if rep.result == -116 and "rank" in rep.data:  # ESTALE hint
                rank = int(rep.data["rank"])
                self._rank_cache[self._route_key(path)] = rank
                if hop >= 2:
                    # ranks briefly disagree right after a pin change
                    # (each refreshes its table within pin_ttl): wait
                    # out the window instead of failing a valid op —
                    # interruptibly, so shutdown() never trails a
                    # residual sleep
                    if self._closed.wait(0.2):
                        raise MDSError(-108, "client shut down")
                continue
            break
        if rep.result < 0:
            raise MDSError(rep.result, str(rep.data.get("error", "")))
        return rep

    @staticmethod
    def _route_key(path: str) -> str:
        # cache by top-level component (pins are subtree-granular;
        # deeper pins correct themselves via one extra redirect)
        parts = [p for p in path.split("/") if p]
        return "/" + parts[0] if parts else "/"

    def _route(self, path: str) -> int:
        return self._rank_cache.get(self._route_key(path), 0)

    def _request_to(self, addr, op, path, args, timeout
                    ) -> cm.MClientReply:
        with self._lock:
            self._tid += 1
            tid = self._tid
        w = _Waiter()
        self._waiters[tid] = w
        try:
            msg = cm.MClientRequest(op, path, args or {})
            msg.tid = tid
            self.msgr.send_message(msg, addr)
            if not w.ev.wait(timeout):
                raise MDSError(-110, f"mds request {op} timed out")
            rep = w.reply
        finally:
            self._waiters.pop(tid, None)
        return rep

    # -- metadata surface --------------------------------------------------
    def mkdir(self, path: str) -> None:
        self._request("mkdir", path)

    def listdir(self, path: str) -> List[str]:
        return self._request("listdir", path).data["names"]

    def rmdir(self, path: str) -> None:
        self._request("rmdir", path)

    def stat(self, path: str) -> dict:
        rep = self._request("stat", path)
        snapc = rep.data.get("snapc")
        if snapc is not None:
            # realm SnapContext piggybacked on the reply: the next data
            # write on this ioctx clones what live snapshots cover
            self.io.set_snap_context(int(snapc[0]),
                                     [int(s) for s in snapc[1]])
        return rep.data["inode"]

    # -- snapshots (.snap semantics via the MDS; journaled there) ---------
    def mksnap(self, path: str, name: str) -> int:
        return int(self._request("mksnap", path,
                                 {"name": name}).data["snapid"])

    def rmsnap(self, path: str, name: str) -> None:
        self._request("rmsnap", path, {"name": name})

    def lssnap(self, path: str) -> List[str]:
        return self._request("lssnap", path).data["names"]

    def unlink(self, path: str) -> None:
        self._request("unlink", path)
        self.caps.pop(path, None)

    def rename(self, src: str, dst: str) -> None:
        self._request("rename", src, {"dst": dst})

    def symlink(self, target: str, linkpath: str) -> None:
        self._request("symlink", linkpath, {"target": target})

    def readlink(self, path: str) -> str:
        return self._request("readlink", path).data["target"]

    def set_pin(self, path: str, rank: int) -> None:
        """Pin a directory subtree to an MDS rank (ceph.dir.pin)."""
        if rank not in self.mds_addrs:
            raise MDSError(-22, f"no MDS rank {rank} in this mount")
        self._request("set_pin", path,
                      {"rank": rank,
                       "known_ranks": sorted(self.mds_addrs)})

    # -- files + caps ------------------------------------------------------
    def create(self, path: str, wants: int = CAP_RD | CAP_WR | CAP_EXCL,
               mode: int = 0o644) -> dict:
        rep = self._request("create", path,
                            {"client": self.name, "wants": wants,
                             "mode": mode})
        self.caps[path] = rep.data["caps"]
        return rep.data["inode"]

    def open(self, path: str,
             wants: int = CAP_RD | CAP_WR | CAP_EXCL) -> dict:
        rep = self._request("open", path,
                            {"client": self.name, "wants": wants})
        self.caps[path] = rep.data["caps"]
        return rep.data["inode"]

    def close(self, path: str) -> None:
        self._request("close", path, {"client": self.name})
        self.caps.pop(path, None)

    def held_caps(self, path: str) -> int:
        return self.caps.get(path, 0)

    # -- data IO (client-direct striping; size via MDS setattr) -----------
    def write(self, path: str, data: bytes, off: int = 0) -> int:
        try:
            inode = self.stat(path)
        except MDSError as e:
            if e.rc != -2:
                raise
            inode = self.create(path, wants=CAP_RD | CAP_WR)
        if inode["type"] != "file":
            raise MDSError(-21, "is a directory")  # EISDIR
        if inode.get("snapid"):
            raise MDSError(-30, "snapshots are read-only")  # EROFS
        self.striper.write(CephFS._data_oid(inode["ino"]), data, off=off)
        new_size = max(inode.get("size", 0), off + len(data))
        self._request("setattr", path,
                      {"attrs": {"size": new_size, "mtime": time.time()}})
        return len(data)

    def read(self, path: str, length: int = 0, off: int = 0) -> bytes:
        inode = self.stat(path)
        size = inode.get("size", 0)
        if length <= 0:
            length = max(0, size - off)
        length = min(length, max(0, size - off))
        if length == 0:
            return b""
        try:
            got = self.striper.read(CephFS._data_oid(inode["ino"]),
                                    length, off,
                                    snapid=inode.get("snapid", 0),
                                    size=size)
        except RadosError as e:
            if e.rc != -2:
                raise
            got = b""
        if len(got) < length:
            got += b"\0" * (length - len(got))
        return got
