"""MDS — the metadata server daemon with journaled metadata + caps.

Reference roles re-derived (not ported):

- **Journaled metadata with crash replay** (src/mds/journal.cc +
  MDLog): every metadata mutation is appended to a RADOS-backed
  write-ahead journal (EUpdate role) BEFORE it is applied to the
  backing dentry store, and the journal's commit pointer advances only
  every `commit_every` events.  A crashed MDS (kill -9 between journal
  append and a multi-step apply, e.g. mid-rename) replays the
  uncommitted tail on restart: events are idempotent, so replay
  converges on exactly the intended tree.
- **Client capabilities** (src/mds/Locker.cc:106 handle_client_caps,
  collapsed to the RD/WR/EXCL trio): clients acquire caps at open;
  conflicting acquisitions revoke the EXCL of other holders
  (MClientCaps "revoke" -> client flushes -> "ack"), and an EXCL
  grant is downgraded when other clients hold the file.  This is the
  consistency contract that lets a sole client buffer writes.
- Sessions ride the framework Messenger (MClientRequest/Reply), the
  same transport every other daemon family uses.

Data IO stays client-direct (clients stripe file data straight to
RADOS, exactly like CephFS clients do) — only metadata routes here.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.cephfs import messages as cm
from ceph_tpu.cephfs.fs import CephFS, FSError, NoSuchEntry, ReadOnlyFS
from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.msg.message import EntityName, Message
from ceph_tpu.msg.messenger import Connection, Dispatcher, Messenger
from ceph_tpu.rbd.journal import Journaler

EPERM, ENOENT, EEXIST, EBUSY, EINVAL, ENOTDIR, ENOTEMPTY = (
    -1, -2, -17, -16, -22, -20, -39)


class MDSDaemon(Dispatcher):
    """One MDS rank.  `commit_every` is the journal commit lag — the
    window a crash leaves for replay to heal.

    Multi-MDS: the namespace is PARTITIONED by export pins (the
    reference's ceph.dir.pin / mds_export_pin feature, the static
    subset of MDBalancer subtree management): a pin table in the
    fs.meta object maps directory subtrees to ranks, every rank owns
    the longest-prefix-pinned subtrees assigned to it (rank 0 owns the
    rest), each rank journals its own mds<rank> WAL, and a request
    landing on the wrong rank is answered with ESTALE + the owner so
    the client redirects.  Cross-rank renames are EXDEV, like a POSIX
    cross-mount rename."""

    def __init__(self, ctx, ioctx: IoCtx, bind_port: int = 0,
                 commit_every: int = 16, rank: int = 0) -> None:
        self.ctx = ctx
        self.io = ioctx
        self.rank = rank
        self.fs = CephFS(ioctx)
        self.commit_every = commit_every
        self.journal = Journaler(ioctx, f"mds{rank}")
        self.journal.create()
        self._pin_cache: Tuple[float, Dict[str, int]] = (0.0, {})
        self._pin_gen = 0
        # ownership-table staleness bound: a pin change is visible to
        # every rank within this window (set_pin refreshes its own rank
        # immediately; peers discover via their next refresh, and the
        # client's redirect loop waits it out — see FSClient._request)
        self.pin_ttl = 0.5
        self._log = ctx.log.dout("mds")
        self.lock = threading.RLock()
        # caps[path] = {client: caps bits}; client -> session conn
        self.caps: Dict[str, Dict[str, int]] = {}
        self._grant_locks: Dict[str, threading.Lock] = {}
        self.sessions: Dict[str, Connection] = {}
        self._cap_acks: Dict[Tuple[str, str], threading.Event] = {}
        self._uncommitted = 0
        self._applied_seq = 0
        # fault injection for crash tests: apply only the first N
        # backing-store steps of the next event, then die
        self._apply_steps_left: Optional[int] = None
        # -- dynamic subtree balancing (reference MDBalancer.cc) ------
        # per-subtree request counters, decayed at each publish; every
        # rank publishes its load row to fs.meta and rank 0 re-pins a
        # hot subtree onto the least-loaded rank when the spread is
        # wide enough (the migration itself is the pin-table change:
        # metadata lives in shared RADOS objects, so handoff = old
        # owner starts ESTALE'ing within pin_ttl and clients follow)
        self._req_load: Dict[str, float] = {}
        self.bal_interval = 5.0       # publish+balance cadence
        self.bal_min_ratio = 2.0      # act when max > ratio * min
        self.bal_min_load = 20.0      # ...and the hot rank is busy
        self._bal_stop = threading.Event()
        self.msgr = Messenger(ctx, EntityName("mds", 0),
                              bind_port=bind_port)
        self.msgr.add_dispatcher(self)
        self.msgr.start()
        self.addr = self.msgr.addr
        self.replay()

    def boot(self, monmap, retries: int = 20,
             interval: float = 0.25) -> None:
        """Announce this rank to the mon quorum until the FSMap commits
        it (reference MMDSBeacon resends periodically; a one-shot send
        is lost during elections).  Clients discover us via
        `fs status`."""
        from ceph_tpu.mon import messages as mm

        # a fresh incarnation nonce per boot() call: resent/replayed
        # beacons of THIS incarnation are idempotent, and a beacon
        # replayed after `mds fail` cannot resurrect the failed
        # incarnation (only a new boot() re-registers) — see MMDSBoot
        self._boot_gen = getattr(self, "_boot_gen", 0) + 1
        bnonce = ((self.msgr.nonce & 0xFFFFFFFF) << 16) | self._boot_gen

        def send_all() -> None:
            for addr in monmap.addrs:
                if addr is not None:
                    self.msgr.send_message(
                        mm.MMDSBoot(self.rank, self.addr[0],
                                    self.addr[1], boot_nonce=bnonce),
                        tuple(addr))

        send_all()
        threading.Thread(
            target=lambda: [time.sleep(interval) or send_all()
                            for _ in range(retries)],
            name=f"mds{self.rank}-beacon", daemon=True).start()
        threading.Thread(target=self._balance_loop,
                         name=f"mds{self.rank}-balancer",
                         daemon=True).start()

    # -- lifecycle / journal ----------------------------------------------
    def replay(self) -> None:
        """Crash recovery (reference MDLog replay): re-apply every
        journaled event past the commit pointer.  Events are
        idempotent, so re-applying an already-half-applied suffix
        converges."""
        entries = self.journal.entries(after=self.journal.committed())
        for seq, payload in entries:
            ev = json.loads(payload.decode())
            try:
                self._apply(ev)
            except (FSError, RadosError):
                pass  # already fully applied before the crash
            self._applied_seq = seq
        if entries:
            self._log(1, f"mds: replayed {len(entries)} journal events")
            self.journal.commit(self._applied_seq)

    def shutdown(self) -> None:
        self._bal_stop.set()
        self.msgr.shutdown()

    def kill(self) -> None:
        """Crash (no journal commit, no flush) — the test hook."""
        self._bal_stop.set()
        self.msgr.shutdown()

    # -- dynamic subtree balancing (reference src/mds/MDBalancer.cc:
    # per-rank load epochs + Migrator-driven subtree moves; here the
    # move is the pin-table flip, see __init__ comment) ---------------
    def _account(self, path: str) -> None:
        """Charge one request to the path's top-level subtree."""
        p = self.fs._norm(path)
        parts = [s for s in p.split("/") if s]
        if not parts:
            return
        sub = "/" + parts[0]
        with self.lock:
            self._req_load[sub] = self._req_load.get(sub, 0.0) + 1.0

    def _publish_load(self) -> None:
        """Decay + publish this rank's per-subtree load row (the
        mds_load exchange, MDBalancer.cc send_heartbeat role)."""
        with self.lock:
            snap = dict(self._req_load)
            for k in list(self._req_load):
                self._req_load[k] *= 0.5
                if self._req_load[k] < 0.5:
                    del self._req_load[k]
        try:
            self.io.omap_set("fs.meta", {
                f"load.{self.rank}": json.dumps(
                    {"t": time.time(), "subs": snap}).encode()})
        except RadosError:
            pass

    def _balance_once(self) -> Optional[Tuple[str, int]]:
        """Rank 0's rebalance decision (MDBalancer.cc prep_rebalance):
        move the hottest subtree of the most-loaded rank to the
        least-loaded LIVE rank when the spread justifies it.  Returns
        (subtree, target_rank) when a migration was committed."""
        if self.rank != 0:
            return None
        try:
            om = self.io.omap_get("fs.meta")
        except RadosError:
            return None
        now = time.time()
        loads: Dict[int, Dict[str, float]] = {}
        for k, v in om.items():
            if not k.startswith("load."):
                continue
            try:
                row = json.loads(v.decode())
            except ValueError:
                continue
            if now - row.get("t", 0) > 4 * self.bal_interval:
                continue  # stale row: rank likely dead
            loads[int(k[len("load."):])] = row.get("subs", {})
        if len(loads) < 2:
            return None
        totals = {r: sum(s.values()) for r, s in loads.items()}
        hot_rank = max(totals, key=totals.get)
        cold_rank = min(totals, key=totals.get)
        if hot_rank == cold_rank:
            return None
        if totals[hot_rank] < self.bal_min_load or \
                totals[hot_rank] < self.bal_min_ratio * max(
                    totals[cold_rank], 1.0):
            return None
        pins = {k[len("subtree."):]: int(v) for k, v in om.items()
                if k.startswith("subtree.")}

        def owner_of(p: str) -> int:
            # longest-prefix over the FRESH pin table (the rank-local
            # cache may be pin_ttl stale — not good enough to decide a
            # migration against)
            best_pp, r = "", 0
            for pp, rr in pins.items():
                if (p == pp or p.startswith(pp.rstrip("/") + "/")) \
                        and len(pp) > len(best_pp):
                    best_pp, r = pp, rr
            return r

        # hottest subtree the hot rank actually OWNS whose move
        # STRICTLY shrinks the spread: new spread |diff - 2*load| must
        # beat diff, i.e. 0 < load < diff — a subtree carrying the
        # whole imbalance would merely reverse it (and then ping-pong
        # back every interval)
        diff = totals[hot_rank] - totals[cold_rank]
        best = None
        for sub, load in sorted(loads[hot_rank].items(),
                                key=lambda kv: -kv[1]):
            if owner_of(sub) != hot_rank:
                continue
            if 0 < load < diff:
                best = (sub, load)
                break
        if best is None:
            return None
        sub, _ = best
        self.io.omap_set("fs.meta", {
            f"subtree.{sub}": str(cold_rank).encode()})
        with self.lock:
            self._pin_gen += 1
            self._pin_cache = (0.0, {})
        self._log(1, f"mds: balancer migrated {sub} "
                     f"rank {hot_rank} -> {cold_rank} "
                     f"(loads {totals})")
        return (sub, cold_rank)

    def _retract_foreign_caps(self) -> None:
        """Revoke capabilities this rank still holds on paths it no
        longer owns (a balancer re-pin — or a manual export-pin —
        moved the subtree; an idle EXCL holder would otherwise never
        learn, and the new owner could grant a SECOND EXCL)."""
        with self.lock:
            held = [(p, list(hs)) for p, hs in self.caps.items() if hs]
        for path, holders in held:
            try:
                if self.owner_rank(path) == self.rank:
                    continue
            except Exception:  # noqa: BLE001 — table read raced
                continue
            for client in holders:
                self._revoke(path, client, 0)
            with self.lock:
                self.caps.pop(path, None)

    def _balance_loop(self) -> None:
        while not self._bal_stop.wait(self.bal_interval):
            try:
                self._publish_load()
                self._balance_once()
                self._retract_foreign_caps()
            except Exception:  # noqa: BLE001 — balancer must not die
                pass

    # -- journaled mutation pipeline --------------------------------------
    def _submit(self, ev: dict) -> None:
        """EUpdate discipline: journal first, then apply; commit lazily."""
        seq = self.journal.append(json.dumps(ev).encode())
        self._apply(ev)
        self._applied_seq = seq
        self._uncommitted += 1
        if self._uncommitted >= self.commit_every:
            self.journal.commit(seq)
            self._uncommitted = 0

    def _step(self) -> None:
        """Fault-injection gate between backing-store steps."""
        if self._apply_steps_left is not None:
            if self._apply_steps_left <= 0:
                raise _Crashed()
            self._apply_steps_left -= 1

    def _apply(self, ev: dict) -> None:
        op = ev["op"]
        fs = self.fs
        if op == "mkdir":
            self._step()
            try:
                fs.mkdir(ev["path"])
            except (FSError, RadosError):
                pass  # already exists: replayed event
        elif op == "create":
            # idempotent create: link only when absent
            try:
                fs._lookup(ev["path"])
            except NoSuchEntry:
                self._step()
                parent, name = fs._split(ev["path"])
                fs._link(parent, name, ev["inode"])
        elif op == "unlink":
            self._step()
            try:
                fs.unlink(ev["path"])
            except FSError:
                pass
        elif op == "rmdir":
            self._step()
            try:
                fs.rmdir(ev["path"])
            except FSError:
                pass
        elif op == "rename":
            # two backing steps: unlink src, link dst — the torn-crash
            # case replay exists for
            src, dst = ev["src"], ev["dst"]
            try:
                inode = fs._lookup(src)
                self._step()
                sp, sn = fs._split(src)
                fs._unlink(sp, sn)
            except NoSuchEntry:
                inode = ev.get("inode")  # src already gone: use journaled
            if inode is not None:
                self._step()
                dp, dn = fs._split(dst)
                fs._link(dp, dn, inode, replace=True)
        elif op == "setattr":
            self._step()
            try:
                parent, name = fs._split(ev["path"])
                inode = fs._lookup(ev["path"])
                inode.update(ev["attrs"])
                fs._link(parent, name, inode, replace=True)
            except NoSuchEntry:
                pass
        elif op == "symlink":
            try:
                fs._lookup(ev["path"])
            except NoSuchEntry:
                self._step()
                fs.symlink(ev["target"], ev["path"])
        elif op == "mksnap":
            # snapid journaled at submit time -> replay re-freezes with
            # the SAME id (freeze-copy is plain overwrites, idempotent)
            self._step()
            try:
                fs.mksnap(ev["path"], ev["name"], snapid=ev["snapid"])
            except (FSError, RadosError):
                pass
        elif op == "rmsnap":
            self._step()
            try:
                fs.rmsnap(ev["path"], ev["name"])
            except (FSError, RadosError):
                pass  # already removed: replayed event
        else:
            self._log(1, f"mds: unknown journal op {op!r}")

    # -- subtree ownership (export pins) ----------------------------------
    def _pins(self) -> Dict[str, int]:
        with self.lock:
            stamp, table = self._pin_cache
            gen = self._pin_gen
        now = time.time()
        if now - stamp > self.pin_ttl:
            try:
                om = self.io.omap_get("fs.meta")
            except RadosError:
                om = {}
            table = {k[len("subtree."):]: int(v)
                     for k, v in om.items() if k.startswith("subtree.")}
            with self.lock:
                # an invalidation that raced this refresh (set_pin bumps
                # the generation) wins: never reinstate a stale table
                if self._pin_gen == gen:
                    self._pin_cache = (now, table)
        return table

    def owner_rank(self, path: str) -> int:
        """Longest-prefix pin match; unpinned namespace is rank 0."""
        p = self.fs._norm(path)
        best, rank = "", 0
        for pin_path, r in self._pins().items():
            if (p == pin_path or p.startswith(pin_path.rstrip("/") + "/")) \
                    and len(pin_path) > len(best):
                best, rank = pin_path, r
        return rank

    # -- capabilities (Locker role) ---------------------------------------
    def _grant_caps(self, path: str, client: str, wants: int) -> int:
        """Arbitrate `wants` against current holders; revokes other
        holders' EXCL synchronously (they flush, then ack).  The whole
        revoke+grant sequence is serialized PER PATH: two concurrent
        EXCL opens must arbitrate against each other, not race past
        the holder scan (requests run on their own threads)."""
        with self.lock:
            plock = self._grant_locks.setdefault(path, threading.Lock())
        with plock:
            return self._grant_caps_locked(path, client, wants)

    def _grant_caps_locked(self, path: str, client: str,
                           wants: int) -> int:
        with self.lock:
            holders = self.caps.setdefault(path, {})
            to_revoke: List[Tuple[str, int]] = []
            for other, held in holders.items():
                if other == client:
                    continue
                if held & cm.CAP_EXCL:
                    # any second holder breaks exclusivity
                    to_revoke.append((other, held & ~cm.CAP_EXCL))
        for other, newcaps in to_revoke:
            self._revoke(path, other, newcaps)
        with self.lock:
            holders = self.caps.setdefault(path, {})
            grant = wants
            if any(o != client for o in holders):
                grant &= ~cm.CAP_EXCL  # shared file: nobody buffers
            holders[client] = holders.get(client, 0) | grant
            return grant

    def _revoke(self, path: str, client: str, newcaps: int) -> None:
        conn = self.sessions.get(client)
        if conn is None:
            with self.lock:
                self.caps.get(path, {}).pop(client, None)
            return
        ev = threading.Event()
        self._cap_acks[(path, client)] = ev
        try:
            conn.send(cm.MClientCaps("revoke", path, newcaps, client))
            if not ev.wait(timeout=10.0):
                self._log(1, f"mds: cap revoke timeout {client} {path}")
            with self.lock:
                self.caps.setdefault(path, {})[client] = newcaps
                if newcaps == 0:
                    self.caps[path].pop(client, None)
        finally:
            self._cap_acks.pop((path, client), None)

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        if isinstance(msg, cm.MClientCaps):
            if msg.op == "ack":
                ev = self._cap_acks.get((msg.path, msg.client))
                if ev:
                    ev.set()
            elif msg.op == "release":
                with self.lock:
                    self.caps.get(msg.path, {}).pop(msg.client, None)
            return True
        if not isinstance(msg, cm.MClientRequest):
            return False
        # requests may block on cap revokes (peer round-trips): run
        # them off the dispatch thread
        threading.Thread(target=self._handle_request, daemon=True,
                         args=(conn, msg)).start()
        return True

    def _handle_request(self, conn: Connection,
                        msg: cm.MClientRequest) -> None:
        try:
            rep = self._do_op(conn, msg)
        except _Crashed:
            return  # injected crash: no reply, daemon is "dead"
        except NoSuchEntry:
            rep = cm.MClientReply(ENOENT)
        except ReadOnlyFS as e:
            rep = cm.MClientReply(-30, {"error": str(e)})  # EROFS
        except FSError as e:
            rep = cm.MClientReply(EINVAL, {"error": str(e)})
        except RadosError as e:
            rep = cm.MClientReply(e.rc, {"error": str(e)})
        rep.tid = msg.tid
        conn.send(rep)

    ESTALE = -116

    def _do_op(self, conn, msg) -> cm.MClientReply:
        op, path, args = msg.op, msg.path, msg.args
        if op == "session_open":
            client = args["client"]
            self.sessions[client] = conn
            return cm.MClientReply(0, {"mds": self.rank})
        if op == "set_pin":
            # pin a subtree to a rank (ceph.dir.pin role); any rank may
            # write the table — it lives in the shared fs.meta object
            rank = int(args["rank"])
            if rank not in args.get("known_ranks", [rank]):
                return cm.MClientReply(EINVAL,
                                       {"error": f"no MDS rank {rank}"})
            self.fs._lookup(path)
            self.io.omap_set("fs.meta", {
                f"subtree.{self.fs._norm(path)}": str(rank).encode()})
            with self.lock:
                self._pin_gen += 1
                self._pin_cache = (0.0, {})
            return cm.MClientReply(0)
        owner = self.owner_rank(path)
        if owner != self.rank:
            # wrong rank: redirect the client (reference forwards
            # requests between MDSs; the hint keeps it one hop)
            return cm.MClientReply(self.ESTALE, {"rank": owner})
        self._account(path)  # balancer load sample (served here only)
        if op == "rename" and self.owner_rank(args["dst"]) != self.rank:
            return cm.MClientReply(
                -18, {"error": "cross-rank rename (EXDEV): subtrees "
                      "are pinned to different MDS ranks"})
        if op == "mkdir":
            self._submit({"op": "mkdir", "path": path})
            return cm.MClientReply(0)
        if op == "create":
            ino = self.fs._next_ino()
            inode = {"type": "file", "ino": ino, "size": 0,
                     "mtime": time.time(), "mode": args.get("mode", 0o644)}
            self._submit({"op": "create", "path": path, "inode": inode})
            grant = self._grant_caps(path, args["client"],
                                     args.get("wants", cm.CAP_RD))
            return cm.MClientReply(0, {"inode": inode, "caps": grant})
        if op == "open":
            inode = self.fs._lookup(path)
            grant = self._grant_caps(path, args["client"],
                                     args.get("wants", cm.CAP_RD))
            return cm.MClientReply(0, {"inode": inode, "caps": grant})
        if op == "close":
            with self.lock:
                self.caps.get(path, {}).pop(args["client"], None)
            return cm.MClientReply(0)
        if op == "stat":
            # the reply carries the path's realm SnapContext so the
            # client's next data write clones exactly what live
            # snapshots cover (client.write stats first, so every
            # write sees a fresh realm — the SnapRealm propagation
            # the reference pushes through cap messages)
            seq, ids = self.fs._realm_snapc(path)
            return cm.MClientReply(0, {"inode": self.fs._lookup(path),
                                       "snapc": [seq, ids]})
        if op == "mksnap":
            name = args["name"]
            # full validation BEFORE journaling: _apply swallows
            # FSErrors (idempotent-replay discipline), so a bogus event
            # journaled here would ack a snapshot that never exists
            if self.fs._lookup(path)["type"] != "dir":
                return cm.MClientReply(-20)  # ENOTDIR
            if (not name or "/" in name
                    or name == self.fs.SNAP_DIR):
                return cm.MClientReply(
                    EINVAL, {"error": f"bad snapshot name {name!r}"})
            key = self.fs._snap_key(path, name)
            if key in self.io.omap_get("fs.meta", [key]):
                return cm.MClientReply(EEXIST)
            # allocate OUTSIDE the journal append (ids are cheap; a
            # crash between alloc and append just wastes one) and
            # restore the ioctx write context — realm scoping is the
            # only place snapcs belong (see fs.mksnap)
            saved = (self.io.snap_seq, list(self.io.snaps))
            snapid = self.io.selfmanaged_snap_create()
            self.io.set_snap_context(*saved)
            self._submit({"op": "mksnap", "path": path, "name": name,
                          "snapid": snapid})
            return cm.MClientReply(0, {"snapid": snapid})
        if op == "rmsnap":
            name = args["name"]
            key = self.fs._snap_key(path, name)
            if key not in self.io.omap_get("fs.meta", [key]):
                return cm.MClientReply(ENOENT)
            self._submit({"op": "rmsnap", "path": path, "name": name})
            return cm.MClientReply(0)
        if op == "lssnap":
            return cm.MClientReply(0, {"names": self.fs.snaps(path)})
        if op == "listdir":
            return cm.MClientReply(0, {"names": self.fs.listdir(path)})
        if op == "unlink":
            self.fs._lookup(path)  # ENOENT surfaces before journaling
            self._submit({"op": "unlink", "path": path})
            with self.lock:
                self.caps.pop(path, None)
            return cm.MClientReply(0)
        if op == "rmdir":
            if self.fs.listdir(path):
                return cm.MClientReply(ENOTEMPTY)
            self._submit({"op": "rmdir", "path": path})
            return cm.MClientReply(0)
        if op == "rename":
            inode = self.fs._lookup(path)
            self._submit({"op": "rename", "src": path,
                          "dst": args["dst"], "inode": inode})
            return cm.MClientReply(0)
        if op == "setattr":
            self.fs._lookup(path)
            self._submit({"op": "setattr", "path": path,
                          "attrs": args["attrs"]})
            return cm.MClientReply(0)
        if op == "symlink":
            self._submit({"op": "symlink", "path": path,
                          "target": args["target"]})
            return cm.MClientReply(0)
        if op == "readlink":
            return cm.MClientReply(0, {"target": self.fs.readlink(path)})
        return cm.MClientReply(EINVAL, {"error": f"unknown op {op!r}"})


class _Crashed(Exception):
    pass
