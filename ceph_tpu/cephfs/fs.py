"""POSIX-shaped filesystem over RADOS — the CephFS role.

Reference: src/mds/ + src/client/ re-derived small: directories are
RADOS objects whose OMAP is the dentry table (the reference's CDir
omap storage format role), file inodes carry (ino, size, mtime, mode)
in the dentry entry (embedded inodes, as CephFS stores them), and file
DATA rides the striping layer keyed by inode number (the reference's
file_layout over `<ino>.<object>` data objects).  Metadata mutations
go through the in-OSD `fsdir` object class, so each directory update
(link/unlink/rename-step) is atomic inside the PG write pipeline —
the single-writer discipline the MDS journal provides, collapsed onto
the object store for this single-MDS-role implementation.

Not modeled (future rounds): distributed metadata cache/capabilities,
subtree migration, the MDS journal and multi-MDS.
"""

from __future__ import annotations

import json
import posixpath
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper
from ceph_tpu.osd.cls import CLS_RD, CLS_WR, ClassHandler, ClsError


class FSError(OSError):
    pass


class NoSuchEntry(FSError):
    pass


class NotADirectory(FSError):
    pass


class IsADirectory(FSError):
    pass


class NotEmpty(FSError):
    pass


def _register_fs_cls() -> None:
    """Atomic dentry-table mutations (the MDS-journal atomicity role)."""
    h = ClassHandler.instance()
    if h.get("fsdir.link") is not None:
        return

    def alloc_ino(ctx, indata: bytes) -> bytes:
        """Atomic inode allocation (the MDS inotable role): the
        read+increment runs inside the PG write pipeline, so two
        clients can never mint the same ino."""
        cur = int(ctx.omap_get(["next_ino"]).get("next_ino", b"1"))
        ctx.omap_set({"next_ino": str(cur + 1).encode()})
        return str(cur).encode()

    def link(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        name = req["name"]
        if not req.get("replace") and name in ctx.omap_get([name]):
            raise ClsError(-17, "entry exists")  # EEXIST
        ctx.omap_set({name: json.dumps(req["inode"]).encode()})
        return b""

    def unlink(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        name = req["name"]
        got = ctx.omap_get([name])
        if name not in got:
            raise ClsError(-2, "no entry")
        ctx.omap_rm([name])
        return got[name]  # the unlinked inode rides back

    h.register("fsdir", "link", CLS_RD | CLS_WR, link)
    h.register("fsdir", "unlink", CLS_RD | CLS_WR, unlink)
    h.register("fsdir", "alloc_ino", CLS_RD | CLS_WR, alloc_ino)


_register_fs_cls()


class ReadOnlyFS(FSError):
    pass


class CephFS:
    def __init__(self, ioctx: IoCtx, stripe_unit: int = 65536,
                 object_size: int = 4 << 20) -> None:
        self.io = ioctx
        self.striper = RadosStriper(ioctx, stripe_unit=stripe_unit,
                                    stripe_count=4,
                                    object_size=object_size)
        # snapshot registry cache (path -> {name: snapid}); small TTL —
        # the realm snapc consulted on writes tolerates the same
        # bounded staleness the reference's client cap cache does
        self._snap_cache: Tuple[float, Dict[str, Dict[str, int]]] = \
            (0.0, {})
        self.snap_ttl = 0.5
        self._mkroot()

    # -- layout ------------------------------------------------------------
    @staticmethod
    def _norm(path: str) -> str:
        p = posixpath.normpath("/" + path.strip("/"))
        return p

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        p = CephFS._norm(path)
        if p == "/":
            raise FSError("root has no parent")
        return posixpath.dirname(p), posixpath.basename(p)

    @staticmethod
    def _dir_oid(path: str) -> str:
        return f"fs.dir.{CephFS._norm(path)}"

    @staticmethod
    def _data_oid(ino: int) -> str:
        return f"fs.data.{ino:016x}"

    def _mkroot(self) -> None:
        try:
            self.io.stat(self._dir_oid("/"))
        except RadosError:
            self.io.write_full(self._dir_oid("/"), b"")
            self.io.omap_set("fs.meta", {"next_ino": b"1"})

    def _next_ino(self) -> int:
        # inode allocator (the MDS inotable role): read+increment runs
        # as ONE in-OSD cls op, so concurrent clients never collide
        return int(self.io.call("fs.meta", "fsdir", "alloc_ino"))

    # -- snapshots (reference SnapRealm / .snap semantics,
    # src/mds/SnapRealm.h + snap.cc re-derived): a snapshot of a
    # directory freezes that subtree.  Metadata is frozen eagerly
    # (dentry tables are small: copied to fs.snap.<id>.dir.* objects);
    # file DATA is copy-on-write via the OSD's self-managed snapshots —
    # writes under a snapped subtree carry the subtree's realm
    # SnapContext, so the OSD clones old data on first overwrite, and
    # `.snap/<name>/...` reads fetch the clone (striper snapid reads,
    # the same machinery RBD snapshots ride) ---------------------------
    SNAP_DIR = ".snap"

    @staticmethod
    def _snap_key(path: str, name: str) -> str:
        return f"fssnap.{CephFS._norm(path)}//{name}"

    @staticmethod
    def _snap_dir_oid(snapid: int, path: str) -> str:
        return f"fs.snap.{snapid}.dir.{CephFS._norm(path)}"

    def _snap_registry(self) -> Dict[str, Dict[str, int]]:
        """{dir_path: {snap_name: snapid}} from fs.meta (TTL-cached)."""
        stamp, table = self._snap_cache
        now = time.time()
        if now - stamp <= self.snap_ttl:
            return table
        try:
            om = self.io.omap_get("fs.meta")
        except RadosError:
            om = {}
        table = {}
        for k, v in om.items():
            if not k.startswith("fssnap."):
                continue
            p, _, name = k[len("fssnap."):].rpartition("//")
            table.setdefault(p, {})[name] = int(json.loads(
                v.decode())["snapid"])
        self._snap_cache = (now, table)
        return table

    def _invalidate_snaps(self) -> None:
        self._snap_cache = (0.0, {})

    def _realm_snapc(self, path: str) -> Tuple[int, List[int]]:
        """SnapContext covering `path`: snapids of every snapshot taken
        on it or any ancestor (the reference's realm resolution,
        SnapRealm::get_snap_context)."""
        p = self._norm(path)
        reg = self._snap_registry()
        ids: List[int] = []
        for dirp, snaps in reg.items():
            if p == dirp or p.startswith(dirp.rstrip("/") + "/"):
                ids.extend(snaps.values())
        ids.sort(reverse=True)
        return (ids[0] if ids else 0, ids)

    def _with_realm(self, path: str):
        """Context manager: point the ioctx snap context at the path's
        realm for the duration of a data mutation, so the OSD clones
        exactly the objects a live snapshot covers (no pool-wide
        cloning, no leaked clones)."""
        import contextlib

        fs = self

        @contextlib.contextmanager
        def cm():
            saved = (fs.io.snap_seq, list(fs.io.snaps))
            seq, ids = fs._realm_snapc(path)
            fs.io.set_snap_context(seq, ids)
            try:
                yield
            finally:
                fs.io.set_snap_context(*saved)
        return cm()

    def _split_snap(self, path: str
                    ) -> Optional[Tuple[str, str, str]]:
        """`/a/b/.snap/name/rest` -> (/a/b, name, rest); None when the
        path has no .snap component."""
        p = self._norm(path)
        parts = [q for q in p.split("/") if q]
        if self.SNAP_DIR not in parts:
            return None
        i = parts.index(self.SNAP_DIR)
        base = "/" + "/".join(parts[:i])
        name = parts[i + 1] if len(parts) > i + 1 else ""
        rest = "/".join(parts[i + 2:])
        return self._norm(base), name, rest

    def _snap_id(self, base: str, name: str) -> int:
        reg = self._snap_registry()
        snaps = reg.get(self._norm(base), {})
        if name not in snaps:
            raise NoSuchEntry(f"{base}/.snap/{name}")
        return snaps[name]

    def _snap_lookup(self, base: str, name: str, rest: str) -> Dict:
        sid = self._snap_id(base, name)
        if not rest:
            return {"type": "dir", "ino": 0, "snapid": sid}
        full = self._norm(base + "/" + rest)
        parent = posixpath.dirname(full)
        leaf = posixpath.basename(full)
        try:
            got = self.io.omap_get(self._snap_dir_oid(sid, parent),
                                   [leaf])
        except RadosError:
            raise NoSuchEntry(f"{base}/.snap/{name}/{rest}")
        if leaf not in got:
            raise NoSuchEntry(f"{base}/.snap/{name}/{rest}")
        ent = json.loads(got[leaf].decode())
        ent["snapid"] = sid
        return ent

    def _tree_tables(self, path: str, oid_fn):
        """Depth-first (dir_path, dentry_kv) walk over the dentry
        tables rooted at `path`, read via oid_fn(path) — the ONE
        subtree traversal freeze/trim/move all share."""
        p = self._norm(path)
        try:
            kv = self.io.omap_get(oid_fn(p))
        except RadosError:
            kv = {}
        yield p, kv
        for nm, blob in kv.items():
            child = json.loads(blob.decode())
            if child.get("type") == "dir":
                yield from self._tree_tables(f"{p}/{nm}", oid_fn)

    def _freeze_tree(self, snapid: int, path: str) -> None:
        """Copy the subtree's dentry tables into the snapshot
        namespace (idempotent: plain overwrites)."""
        for p, kv in self._tree_tables(path, self._dir_oid):
            self.io.write_full(self._snap_dir_oid(snapid, p), b"")
            if kv:
                self.io.omap_set(self._snap_dir_oid(snapid, p), kv)

    def mksnap(self, path: str, name: str,
               snapid: Optional[int] = None) -> int:
        """Snapshot the subtree at `path` as `.snap/<name>`.  Returns
        the snapid.  `snapid` is passed on journal replay so the apply
        is idempotent (a fresh call allocates)."""
        p = self._norm(path)
        if not name or "/" in name or name == self.SNAP_DIR:
            raise FSError(-22, f"bad snapshot name {name!r}")
        if self._lookup(p)["type"] != "dir":
            raise NotADirectory(p)
        key = self._snap_key(p, name)
        existing = self.io.omap_get("fs.meta", [key])
        if key in existing:
            if snapid is not None:  # replay of an applied event
                return int(json.loads(existing[key].decode())["snapid"])
            raise FSError(-17, f"snapshot {name!r} exists")  # EEXIST
        if snapid is None:
            # allocation must NOT leak into the ioctx's write context:
            # selfmanaged_snap_create folds the new id into the global
            # snapc, but realm scoping (_with_realm) is the ONLY place
            # snap contexts belong — otherwise every later metadata/cls
            # write clones pool-wide and rmsnap can't reclaim it
            saved = (self.io.snap_seq, list(self.io.snaps))
            snapid = self.io.selfmanaged_snap_create()
            self.io.set_snap_context(*saved)
        self._freeze_tree(snapid, p)
        self.io.omap_set("fs.meta", {key: json.dumps(
            {"snapid": snapid, "created": time.time()}).encode()})
        self._invalidate_snaps()
        return snapid

    def rmsnap(self, path: str, name: str) -> None:
        """Delete a snapshot: trim every covered file's data clones,
        drop the frozen dentry tables, unregister."""
        p = self._norm(path)
        sid = self._snap_id(p, name)
        self._trim_tree(sid, p)
        self.io.omap_rm("fs.meta", [self._snap_key(p, name)])
        self._invalidate_snaps()

    def _trim_tree(self, snapid: int, path: str) -> None:
        oid_fn = lambda q: self._snap_dir_oid(snapid, q)  # noqa: E731
        for p, kv in self._tree_tables(path, oid_fn):
            for nm, blob in kv.items():
                ent = json.loads(blob.decode())
                if ent.get("type") == "file":
                    self._trim_file(snapid, ent)
            try:
                self.io.remove(oid_fn(p))
            except RadosError:
                pass

    def _trim_file(self, snapid: int, ent: Dict) -> None:
        soid = self._data_oid(ent["ino"])
        size = max(ent.get("size", 0), 1)
        for comp in self.striper.component_oids(soid, size):
            try:
                self.io.snap_trim(comp, snapid)
            except RadosError:
                pass

    def snaps(self, path: str) -> List[str]:
        """Snapshot names on `path` (the .snap dir listing)."""
        self._lookup(path)
        return sorted(self._snap_registry().get(self._norm(path), {}))

    def _subtree_has_snaps(self, path: str) -> bool:
        """True when any directory at/under `path` has a snapshot —
        registry keys are absolute paths, so such a subtree cannot be
        renamed without detaching its snapshots."""
        p = self._norm(path)
        for dirp in self._snap_registry():
            if dirp == p or dirp.startswith(p.rstrip("/") + "/"):
                return True
        return False

    def _lookup(self, path: str) -> Dict:
        p = self._norm(path)
        sp = self._split_snap(p)
        if sp is not None:
            return self._snap_lookup(*sp)
        if p == "/":
            return {"type": "dir", "ino": 0}
        parent, name = self._split(p)
        try:
            got = self.io.omap_get(self._dir_oid(parent), [name])
        except RadosError:
            raise NoSuchEntry(p)
        if name not in got:
            raise NoSuchEntry(p)
        return json.loads(got[name].decode())

    def _link(self, parent: str, name: str, inode: Dict,
              replace: bool = False) -> None:
        self.io.call(self._dir_oid(parent), "fsdir", "link",
                     json.dumps({"name": name, "inode": inode,
                                 "replace": replace}).encode())

    def _unlink(self, parent: str, name: str) -> Dict:
        try:
            got = self.io.call(self._dir_oid(parent), "fsdir", "unlink",
                               json.dumps({"name": name}).encode())
        except RadosError as e:
            if e.rc == -2:
                raise NoSuchEntry(f"{parent}/{name}")
            raise
        return json.loads(got.decode())

    def _deny_snap_write(self, *paths: str) -> None:
        for p in paths:
            if self._split_snap(p) is not None:
                raise ReadOnlyFS(-30, f"{p}: snapshots are read-only")

    # -- directories -------------------------------------------------------
    def mkdir(self, path: str) -> None:
        self._deny_snap_write(path)
        parent, name = self._split(path)
        if name == self.SNAP_DIR:
            raise FSError(-22, ".snap is reserved")
        if self._lookup(parent)["type"] != "dir":
            raise NotADirectory(parent)
        self.io.write_full(self._dir_oid(path), b"")
        self._link(parent, name, {"type": "dir", "ino": self._next_ino(),
                                  "mtime": time.time()})

    def listdir(self, path: str) -> List[str]:
        sp = self._split_snap(path)
        if sp is not None:
            base, name, rest = sp
            if not name:  # "/a/.snap" lists the snapshots themselves
                return self.snaps(base)
            sid = self._snap_id(base, name)
            full = self._norm(base + ("/" + rest if rest else ""))
            ent = self._snap_lookup(base, name, rest)
            if ent["type"] != "dir":
                raise NotADirectory(path)
            try:
                return sorted(self.io.omap_get(
                    self._snap_dir_oid(sid, full)))
            except RadosError:
                raise NoSuchEntry(path)
        ent = self._lookup(path)
        if ent["type"] != "dir":
            raise NotADirectory(path)
        try:
            return sorted(self.io.omap_get(self._dir_oid(path)))
        except RadosError:
            raise NoSuchEntry(path)

    def rmdir(self, path: str) -> None:
        self._deny_snap_write(path)
        if self.listdir(path):
            raise NotEmpty(path)
        if self.snaps(path):
            raise NotEmpty(f"{path} has snapshots")
        parent, name = self._split(path)
        self._unlink(parent, name)
        try:
            self.io.remove(self._dir_oid(path))
        except RadosError:
            pass

    # -- files -------------------------------------------------------------
    def write(self, path: str, data: bytes, off: int = 0) -> int:
        self._deny_snap_write(path)
        parent, name = self._split(path)
        if name == self.SNAP_DIR:
            raise FSError(-22, ".snap is reserved")
        try:
            ent = self._lookup(path)
            if ent["type"] == "dir":
                raise IsADirectory(path)
        except NoSuchEntry:
            ent = {"type": "file", "ino": self._next_ino(), "size": 0}
        with self._with_realm(path):
            self.striper.write(self._data_oid(ent["ino"]), data, off=off)
        ent["size"] = max(ent.get("size", 0), off + len(data))
        ent["mtime"] = time.time()
        self._link(parent, name, ent, replace=True)
        return len(data)

    def read(self, path: str, length: int = 0, off: int = 0) -> bytes:
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        size = ent.get("size", 0)
        if off >= size:
            return b""
        if length == 0 or off + length > size:
            length = size - off
        try:
            got = self.striper.read(self._data_oid(ent["ino"]),
                                    length, off,
                                    snapid=ent.get("snapid", 0),
                                    size=size)
        except RadosError:
            got = b""
        if len(got) < length:
            got += b"\0" * (length - len(got))
        return got

    def stat(self, path: str) -> Dict:
        return dict(self._lookup(path))

    # -- symlinks (reference Client::symlink/readlink; the target lives
    # in the dentry inode like the MDS's inline symlink target) -----------
    def symlink(self, target: str, linkpath: str) -> None:
        self._deny_snap_write(linkpath)
        parent, name = self._split(linkpath)
        if self._lookup(parent)["type"] != "dir":
            raise NotADirectory(parent)
        try:
            self._lookup(linkpath)
            raise FSError(-17, f"{linkpath} exists")  # EEXIST
        except NoSuchEntry:
            pass
        self._link(parent, name, {"type": "symlink",
                                  "ino": self._next_ino(),
                                  "target": target,
                                  "mtime": time.time()})

    def readlink(self, path: str) -> str:
        ent = self._lookup(path)
        if ent["type"] != "symlink":
            raise FSError(-22, f"{path} is not a symlink")
        return ent["target"]

    # -- file locks (reference Client::flock over the MDS filelock; here
    # the in-OSD lock class on the file's data object — the same
    # primitive librbd's exclusive lock uses) -----------------------------
    def flock(self, path: str, owner: str,
              shared: bool = False) -> None:
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        self.io.call(self._data_oid(ent["ino"]), "lock", "lock",
                     json.dumps({"name": "flock", "owner": owner,
                                 "type": "shared" if shared
                                 else "exclusive"}).encode())

    def funlock(self, path: str, owner: str) -> None:
        ent = self._lookup(path)
        self.io.call(self._data_oid(ent["ino"]), "lock", "unlock",
                     json.dumps({"name": "flock",
                                 "owner": owner}).encode())

    def flock_info(self, path: str) -> Optional[Dict]:
        ent = self._lookup(path)
        got = self.io.call(self._data_oid(ent["ino"]), "lock",
                           "get_info",
                           json.dumps({"name": "flock"}).encode())
        info = json.loads(got.decode()) if got else None
        return info or None

    def resolve(self, path: str, _depth: int = 0) -> str:
        """Follow symlinks to the real path (bounded, ELOOP past 16)."""
        if _depth > 16:
            raise FSError(-40, f"symlink loop at {path}")  # ELOOP
        ent = self._lookup(path)
        if ent["type"] != "symlink":
            return self._norm(path)
        target = ent["target"]
        if not target.startswith("/"):
            parent, _name = self._split(path)
            target = parent.rstrip("/") + "/" + target
        return self.resolve(target, _depth + 1)

    def unlink(self, path: str) -> None:
        self._deny_snap_write(path)
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        parent, name = self._split(path)
        self._unlink(parent, name)
        try:
            # under a live realm the OSD whiteouts the head and keeps
            # the clones, so .snap reads survive the unlink
            with self._with_realm(path):
                self.striper.remove(self._data_oid(ent["ino"]))
        except RadosError:
            pass

    def truncate(self, path: str, size: int) -> None:
        self._deny_snap_write(path)
        parent, name = self._split(path)
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        try:
            with self._with_realm(path):
                self.striper.truncate(self._data_oid(ent["ino"]), size)
        except RadosError:
            pass
        ent["size"] = size
        self._link(parent, name, ent, replace=True)

    def rename(self, src: str, dst: str) -> None:
        """link-then-unlink two-phase (the MDS would journal this; a
        crash between phases leaves both names valid, never neither).
        Directory renames move the WHOLE subtree's dentry-table
        objects — tables are keyed by absolute path, so every
        descendant directory relocates too."""
        self._deny_snap_write(src, dst)
        # registry + frozen tables are keyed by absolute path: moving
        # the tree would detach its snapshots (and a future dir at the
        # old path would inherit them) — refuse, like rmdir of a
        # snapped dir (reference: ENOTEMPTY).  Fresh registry read: a
        # false allow from the TTL cache would lose snapshot COW.
        self._invalidate_snaps()
        if self._subtree_has_snaps(src):
            raise NotEmpty(f"{src}: subtree has snapshots")
        sp, sn = self._split(src)
        dp, dn = self._split(dst)
        ent = self._lookup(src)
        if ent["type"] == "dir":
            self._link(dp, dn, ent, replace=True)
            self._move_dir_tree(self._norm(src), self._norm(dst))
            self._unlink(sp, sn)
        else:
            self._link(dp, dn, ent, replace=True)
            self._unlink(sp, sn)

    def _move_dir_tree(self, src: str, dst: str) -> None:
        """Depth-first copy of dentry tables src/* -> dst/*, then drop
        the old tables."""
        src = self._norm(src)
        dst = self._norm(dst)
        for p, kv in self._tree_tables(src, self._dir_oid):
            dstp = dst + p[len(src):]
            self.io.write_full(self._dir_oid(dstp), b"")
            if kv:
                self.io.omap_set(self._dir_oid(dstp), kv)
            try:
                self.io.remove(self._dir_oid(p))
            except RadosError:
                pass
