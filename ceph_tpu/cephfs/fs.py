"""POSIX-shaped filesystem over RADOS — the CephFS role.

Reference: src/mds/ + src/client/ re-derived small: directories are
RADOS objects whose OMAP is the dentry table (the reference's CDir
omap storage format role), file inodes carry (ino, size, mtime, mode)
in the dentry entry (embedded inodes, as CephFS stores them), and file
DATA rides the striping layer keyed by inode number (the reference's
file_layout over `<ino>.<object>` data objects).  Metadata mutations
go through the in-OSD `fsdir` object class, so each directory update
(link/unlink/rename-step) is atomic inside the PG write pipeline —
the single-writer discipline the MDS journal provides, collapsed onto
the object store for this single-MDS-role implementation.

Not modeled (future rounds): distributed metadata cache/capabilities,
subtree migration, the MDS journal and multi-MDS.
"""

from __future__ import annotations

import json
import posixpath
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper
from ceph_tpu.osd.cls import CLS_RD, CLS_WR, ClassHandler, ClsError


class FSError(OSError):
    pass


class NoSuchEntry(FSError):
    pass


class NotADirectory(FSError):
    pass


class IsADirectory(FSError):
    pass


class NotEmpty(FSError):
    pass


def _register_fs_cls() -> None:
    """Atomic dentry-table mutations (the MDS-journal atomicity role)."""
    h = ClassHandler.instance()
    if h.get("fsdir.link") is not None:
        return

    def alloc_ino(ctx, indata: bytes) -> bytes:
        """Atomic inode allocation (the MDS inotable role): the
        read+increment runs inside the PG write pipeline, so two
        clients can never mint the same ino."""
        cur = int(ctx.omap_get(["next_ino"]).get("next_ino", b"1"))
        ctx.omap_set({"next_ino": str(cur + 1).encode()})
        return str(cur).encode()

    def link(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        name = req["name"]
        if not req.get("replace") and name in ctx.omap_get([name]):
            raise ClsError(-17, "entry exists")  # EEXIST
        ctx.omap_set({name: json.dumps(req["inode"]).encode()})
        return b""

    def unlink(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        name = req["name"]
        got = ctx.omap_get([name])
        if name not in got:
            raise ClsError(-2, "no entry")
        ctx.omap_rm([name])
        return got[name]  # the unlinked inode rides back

    h.register("fsdir", "link", CLS_RD | CLS_WR, link)
    h.register("fsdir", "unlink", CLS_RD | CLS_WR, unlink)
    h.register("fsdir", "alloc_ino", CLS_RD | CLS_WR, alloc_ino)


_register_fs_cls()


class CephFS:
    def __init__(self, ioctx: IoCtx, stripe_unit: int = 65536,
                 object_size: int = 4 << 20) -> None:
        self.io = ioctx
        self.striper = RadosStriper(ioctx, stripe_unit=stripe_unit,
                                    stripe_count=4,
                                    object_size=object_size)
        self._mkroot()

    # -- layout ------------------------------------------------------------
    @staticmethod
    def _norm(path: str) -> str:
        p = posixpath.normpath("/" + path.strip("/"))
        return p

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        p = CephFS._norm(path)
        if p == "/":
            raise FSError("root has no parent")
        return posixpath.dirname(p), posixpath.basename(p)

    @staticmethod
    def _dir_oid(path: str) -> str:
        return f"fs.dir.{CephFS._norm(path)}"

    @staticmethod
    def _data_oid(ino: int) -> str:
        return f"fs.data.{ino:016x}"

    def _mkroot(self) -> None:
        try:
            self.io.stat(self._dir_oid("/"))
        except RadosError:
            self.io.write_full(self._dir_oid("/"), b"")
            self.io.omap_set("fs.meta", {"next_ino": b"1"})

    def _next_ino(self) -> int:
        # inode allocator (the MDS inotable role): read+increment runs
        # as ONE in-OSD cls op, so concurrent clients never collide
        return int(self.io.call("fs.meta", "fsdir", "alloc_ino"))

    def _lookup(self, path: str) -> Dict:
        p = self._norm(path)
        if p == "/":
            return {"type": "dir", "ino": 0}
        parent, name = self._split(p)
        try:
            got = self.io.omap_get(self._dir_oid(parent), [name])
        except RadosError:
            raise NoSuchEntry(p)
        if name not in got:
            raise NoSuchEntry(p)
        return json.loads(got[name].decode())

    def _link(self, parent: str, name: str, inode: Dict,
              replace: bool = False) -> None:
        self.io.call(self._dir_oid(parent), "fsdir", "link",
                     json.dumps({"name": name, "inode": inode,
                                 "replace": replace}).encode())

    def _unlink(self, parent: str, name: str) -> Dict:
        try:
            got = self.io.call(self._dir_oid(parent), "fsdir", "unlink",
                               json.dumps({"name": name}).encode())
        except RadosError as e:
            if e.rc == -2:
                raise NoSuchEntry(f"{parent}/{name}")
            raise
        return json.loads(got.decode())

    # -- directories -------------------------------------------------------
    def mkdir(self, path: str) -> None:
        parent, name = self._split(path)
        if self._lookup(parent)["type"] != "dir":
            raise NotADirectory(parent)
        self.io.write_full(self._dir_oid(path), b"")
        self._link(parent, name, {"type": "dir", "ino": self._next_ino(),
                                  "mtime": time.time()})

    def listdir(self, path: str) -> List[str]:
        ent = self._lookup(path)
        if ent["type"] != "dir":
            raise NotADirectory(path)
        try:
            return sorted(self.io.omap_get(self._dir_oid(path)))
        except RadosError:
            raise NoSuchEntry(path)

    def rmdir(self, path: str) -> None:
        if self.listdir(path):
            raise NotEmpty(path)
        parent, name = self._split(path)
        self._unlink(parent, name)
        try:
            self.io.remove(self._dir_oid(path))
        except RadosError:
            pass

    # -- files -------------------------------------------------------------
    def write(self, path: str, data: bytes, off: int = 0) -> int:
        parent, name = self._split(path)
        try:
            ent = self._lookup(path)
            if ent["type"] == "dir":
                raise IsADirectory(path)
        except NoSuchEntry:
            ent = {"type": "file", "ino": self._next_ino(), "size": 0}
        self.striper.write(self._data_oid(ent["ino"]), data, off=off)
        ent["size"] = max(ent.get("size", 0), off + len(data))
        ent["mtime"] = time.time()
        self._link(parent, name, ent, replace=True)
        return len(data)

    def read(self, path: str, length: int = 0, off: int = 0) -> bytes:
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        size = ent.get("size", 0)
        if off >= size:
            return b""
        if length == 0 or off + length > size:
            length = size - off
        try:
            got = self.striper.read(self._data_oid(ent["ino"]),
                                    length, off)
        except RadosError:
            got = b""
        if len(got) < length:
            got += b"\0" * (length - len(got))
        return got

    def stat(self, path: str) -> Dict:
        return dict(self._lookup(path))

    # -- symlinks (reference Client::symlink/readlink; the target lives
    # in the dentry inode like the MDS's inline symlink target) -----------
    def symlink(self, target: str, linkpath: str) -> None:
        parent, name = self._split(linkpath)
        if self._lookup(parent)["type"] != "dir":
            raise NotADirectory(parent)
        try:
            self._lookup(linkpath)
            raise FSError(-17, f"{linkpath} exists")  # EEXIST
        except NoSuchEntry:
            pass
        self._link(parent, name, {"type": "symlink",
                                  "ino": self._next_ino(),
                                  "target": target,
                                  "mtime": time.time()})

    def readlink(self, path: str) -> str:
        ent = self._lookup(path)
        if ent["type"] != "symlink":
            raise FSError(-22, f"{path} is not a symlink")
        return ent["target"]

    # -- file locks (reference Client::flock over the MDS filelock; here
    # the in-OSD lock class on the file's data object — the same
    # primitive librbd's exclusive lock uses) -----------------------------
    def flock(self, path: str, owner: str,
              shared: bool = False) -> None:
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        self.io.call(self._data_oid(ent["ino"]), "lock", "lock",
                     json.dumps({"name": "flock", "owner": owner,
                                 "type": "shared" if shared
                                 else "exclusive"}).encode())

    def funlock(self, path: str, owner: str) -> None:
        ent = self._lookup(path)
        self.io.call(self._data_oid(ent["ino"]), "lock", "unlock",
                     json.dumps({"name": "flock",
                                 "owner": owner}).encode())

    def flock_info(self, path: str) -> Optional[Dict]:
        ent = self._lookup(path)
        got = self.io.call(self._data_oid(ent["ino"]), "lock",
                           "get_info",
                           json.dumps({"name": "flock"}).encode())
        info = json.loads(got.decode()) if got else None
        return info or None

    def resolve(self, path: str, _depth: int = 0) -> str:
        """Follow symlinks to the real path (bounded, ELOOP past 16)."""
        if _depth > 16:
            raise FSError(-40, f"symlink loop at {path}")  # ELOOP
        ent = self._lookup(path)
        if ent["type"] != "symlink":
            return self._norm(path)
        target = ent["target"]
        if not target.startswith("/"):
            parent, _name = self._split(path)
            target = parent.rstrip("/") + "/" + target
        return self.resolve(target, _depth + 1)

    def unlink(self, path: str) -> None:
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        parent, name = self._split(path)
        self._unlink(parent, name)
        try:
            self.striper.remove(self._data_oid(ent["ino"]))
        except RadosError:
            pass

    def truncate(self, path: str, size: int) -> None:
        parent, name = self._split(path)
        ent = self._lookup(path)
        if ent["type"] == "dir":
            raise IsADirectory(path)
        try:
            self.striper.truncate(self._data_oid(ent["ino"]), size)
        except RadosError:
            pass
        ent["size"] = size
        self._link(parent, name, ent, replace=True)

    def rename(self, src: str, dst: str) -> None:
        """link-then-unlink two-phase (the MDS would journal this; a
        crash between phases leaves both names valid, never neither).
        Directory renames move the WHOLE subtree's dentry-table
        objects — tables are keyed by absolute path, so every
        descendant directory relocates too."""
        sp, sn = self._split(src)
        dp, dn = self._split(dst)
        ent = self._lookup(src)
        if ent["type"] == "dir":
            self._link(dp, dn, ent, replace=True)
            self._move_dir_tree(self._norm(src), self._norm(dst))
            self._unlink(sp, sn)
        else:
            self._link(dp, dn, ent, replace=True)
            self._unlink(sp, sn)

    def _move_dir_tree(self, src: str, dst: str) -> None:
        """Depth-first copy of dentry tables src/* -> dst/*, then drop
        the old tables."""
        try:
            kv = self.io.omap_get(self._dir_oid(src))
        except RadosError:
            kv = {}
        self.io.write_full(self._dir_oid(dst), b"")
        if kv:
            self.io.omap_set(self._dir_oid(dst), kv)
        for name, blob in kv.items():
            child = json.loads(blob.decode())
            if child.get("type") == "dir":
                self._move_dir_tree(f"{src}/{name}", f"{dst}/{name}")
        try:
            self.io.remove(self._dir_oid(src))
        except RadosError:
            pass
