"""CephFS-role POSIX-ish filesystem over RADOS (reference: src/mds/ +
src/client/)."""

from ceph_tpu.cephfs.fs import (
    CephFS,
    FSError,
    IsADirectory,
    NotADirectory,
    NotEmpty,
    NoSuchEntry,
)

__all__ = ["CephFS", "FSError", "NoSuchEntry", "NotADirectory",
           "IsADirectory", "NotEmpty"]
