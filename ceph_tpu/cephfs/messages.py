"""CephFS wire messages (reference src/messages/MClientRequest.h,
MClientReply.h, MClientCaps.h — the client<->MDS protocol, sized to
this framework's MDS)."""

from __future__ import annotations

import json
from typing import Any, Dict, Optional

from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.msg.message import Message, register

# capability bits (reference CEPH_CAP_* collapsed to the file-level
# trio the Locker arbitration needs)
CAP_RD = 1    # may read (and cache reads)
CAP_WR = 2    # may write through
CAP_EXCL = 4  # sole client: may buffer writes / cache aggressively


@register
class MClientRequest(Message):
    """client -> MDS: one metadata op (mkdir/stat/open/...)."""

    TYPE = 42

    def __init__(self, op: str = "", path: str = "",
                 args: Optional[Dict[str, Any]] = None) -> None:
        super().__init__()
        self.op = op
        self.path = path
        self.args = args or {}

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.path)
        e.blob(json.dumps(self.args).encode())

    def decode_payload(self, d: Decoder) -> None:
        self.op = d.string()
        self.path = d.string()
        self.args = json.loads(d.blob().decode())


@register
class MClientReply(Message):
    TYPE = 43

    def __init__(self, result: int = 0,
                 data: Optional[Dict[str, Any]] = None) -> None:
        super().__init__()
        self.result = result
        self.data = data or {}

    def encode_payload(self, e: Encoder) -> None:
        e.s32(self.result)
        e.blob(json.dumps(self.data).encode())

    def decode_payload(self, d: Decoder) -> None:
        self.result = d.s32()
        self.data = json.loads(d.blob().decode())


@register
class MClientCaps(Message):
    """Bidirectional cap traffic (reference MClientCaps):
    op="revoke":  MDS -> client: your caps on `path` shrink to `caps`
    op="ack":     client -> MDS: flushed + accepted the shrink
    op="release": client -> MDS: dropping caps voluntarily (close)
    """

    TYPE = 44

    def __init__(self, op: str = "", path: str = "", caps: int = 0,
                 client: str = "") -> None:
        super().__init__()
        self.op = op
        self.path = path
        self.caps = caps
        self.client = client

    def encode_payload(self, e: Encoder) -> None:
        e.string(self.op).string(self.path).u32(self.caps)
        e.string(self.client)

    def decode_payload(self, d: Decoder) -> None:
        self.op = d.string()
        self.path = d.string()
        self.caps = d.u32()
        self.client = d.string()
