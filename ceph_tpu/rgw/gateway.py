"""S3-shaped object gateway over the client library.

Reference role: src/rgw/ re-derived on this framework's primitives:
bucket metadata lives in a root registry object (the rgw_directory /
zone bucket-index root role), each bucket's KEY INDEX is an omap on a
bucket-index object maintained ATOMICALLY by an in-OSD `rgw` object
class (the cls_rgw role — index updates execute inside the PG write
pipeline, so a crashed gateway can never leave index/data torn on the
index side), and object payloads ride the striping layer so big
uploads fan out across PGs.

Surface: create/list/delete buckets, put/get/head/delete objects with
ETags + user metadata, prefix/marker/max-keys listing (the S3
ListObjects pagination contract).
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper
from ceph_tpu.osd.cls import CLS_RD, CLS_WR, ClassHandler, ClsError

ROOT_OID = "rgw.root"
# the zone metadata log (mdlog role): ONE module-level name shared by
# the gateway, RGWUserAdmin and the sync agent
META_LOG_OID = "rgw.meta.log"


class NoSuchBucket(KeyError):
    pass


class NoSuchKey(KeyError):
    pass


class BucketExists(ValueError):
    pass


class BucketNotEmpty(ValueError):
    pass


def _register_rgw_cls() -> None:
    """cls_rgw role: atomic bucket-index mutations server-side."""
    h = ClassHandler.instance()
    if h.get("rgw.index_put") is not None:
        return

    # the bucket-index CHANGE LOG rides the same omap under a reserved
    # prefix and is appended in the SAME atomic cls call as the index
    # mutation (reference cls_rgw's bucket index log — the feed
    # multisite data sync replays); "~" is reserved, like the
    # reference's \\x80-prefixed special index entries
    BILOG = "~bilog."
    BILOG_SEQ = "~bilog_seq"

    def _bilog_append(ctx, op: str, key: str) -> None:
        seq = int(ctx.omap_get([BILOG_SEQ]).get(BILOG_SEQ, b"0")) + 1
        ctx.omap_set({
            BILOG_SEQ: str(seq).encode(),
            f"{BILOG}{seq:020d}": json.dumps(
                {"op": op, "key": key}).encode()})

    def index_put(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        if req["key"].startswith("~"):
            # "~" is the reserved index namespace (bilog + counters) —
            # the reference escapes user keys out of its \x80 space
            raise ClsError(-22, "object keys may not start with '~'")
        ctx.omap_set({req["key"]: json.dumps(req["entry"]).encode()})
        _bilog_append(ctx, "put", req["key"])
        return b""

    def index_rm(ctx, indata: bytes) -> bytes:
        key = indata.decode()
        if key not in ctx.omap_get([key]):
            raise ClsError(-2, "no such key")
        ctx.omap_rm([key])
        _bilog_append(ctx, "rm", key)
        return b""

    def index_list(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode() or "{}")
        prefix = req.get("prefix", "")
        marker = req.get("marker", "")
        maxk = int(req.get("max_keys", 1000))
        out = []
        for k in sorted(ctx.omap_get()):
            if k.startswith("~"):  # reserved: bilog + counters
                continue
            if k <= marker or not k.startswith(prefix):
                continue
            out.append((k, ctx.omap_get([k])[k].decode()))
            if len(out) >= maxk + 1:
                break
        truncated = len(out) > maxk
        return json.dumps({"entries": out[:maxk],
                           "truncated": truncated}).encode()

    def _log_list(ctx, indata: bytes, prefix: str) -> bytes:
        req = json.loads(indata.decode() or "{}")
        after = int(req.get("after", 0))
        maxk = int(req.get("max", 1000))
        out = []
        if ctx.exists:
            full = ctx.omap_get()  # ONE read; no per-entry re-fetch
            for k in sorted(full):
                if not k.startswith(prefix):
                    continue
                seq = int(k[len(prefix):])
                if seq <= after:
                    continue
                out.append({"seq": seq, **json.loads(full[k].decode())})
                if len(out) >= maxk:
                    break
        return json.dumps(out).encode()

    def _log_trim(ctx, indata: bytes, prefix: str) -> bytes:
        upto = int(indata.decode() or "0")
        doomed = [k for k in ctx.omap_get()
                  if k.startswith(prefix) and int(k[len(prefix):]) <= upto]
        if doomed:
            ctx.omap_rm(doomed)
        return str(len(doomed)).encode()

    def bilog_list(ctx, indata: bytes) -> bytes:
        return _log_list(ctx, indata, BILOG)

    def bilog_trim(ctx, indata: bytes) -> bytes:
        return _log_trim(ctx, indata, BILOG)

    # the METADATA log (reference rgw_sync.cc mdlog role): user/bucket
    # metadata mutations append here so secondary zones can replay the
    # metadata NAMESPACE (accounts, bucket existence), not just object
    # data — one global log object per zone, same atomic append shape
    # as the bilog
    MDLOG = "~mdlog."
    MDLOG_SEQ = "~mdlog_seq"

    def mdlog_add(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        cur = (ctx.omap_get([MDLOG_SEQ]).get(MDLOG_SEQ, b"0")
               if ctx.exists else b"0")
        seq = int(cur) + 1
        ctx.omap_set({
            MDLOG_SEQ: str(seq).encode(),
            f"{MDLOG}{seq:020d}": json.dumps(
                {"section": req["section"], "name": req["name"],
                 "op": req["op"]}).encode()})
        return str(seq).encode()

    def mdlog_list(ctx, indata: bytes) -> bytes:
        return _log_list(ctx, indata, MDLOG)

    def mdlog_trim(ctx, indata: bytes) -> bytes:
        return _log_trim(ctx, indata, MDLOG)

    h.register("rgw", "index_put", CLS_RD | CLS_WR, index_put)
    h.register("rgw", "index_rm", CLS_RD | CLS_WR, index_rm)
    h.register("rgw", "index_list", CLS_RD, index_list)
    h.register("rgw", "bilog_list", CLS_RD, bilog_list)
    h.register("rgw", "bilog_trim", CLS_RD | CLS_WR, bilog_trim)
    h.register("rgw", "mdlog_add", CLS_RD | CLS_WR, mdlog_add)
    h.register("rgw", "mdlog_list", CLS_RD, mdlog_list)
    h.register("rgw", "mdlog_trim", CLS_RD | CLS_WR, mdlog_trim)


_register_rgw_cls()


class RGW:
    def __init__(self, ioctx: IoCtx, stripe_unit: int = 65536,
                 object_size: int = 4 << 20) -> None:
        self.io = ioctx
        self.striper = RadosStriper(ioctx, stripe_unit=stripe_unit,
                                    stripe_count=4,
                                    object_size=object_size)

    # metadata log object: user/bucket namespace mutations append here
    # (the rgw_sync.cc mdlog role; tailed by RGWZoneSync.meta sync)
    META_LOG_OID = META_LOG_OID  # class alias of the module constant

    def _mdlog(self, section: str, name: str, op: str) -> None:
        try:
            self.io.call(self.META_LOG_OID, "rgw", "mdlog_add",
                         json.dumps({"section": section, "name": name,
                                     "op": op}).encode())
        except RadosError:
            pass  # the log is an aux feed, never a mutation blocker

    # -- buckets -----------------------------------------------------------
    def _index_oid(self, bucket: str) -> str:
        return f"rgw.bucket.{bucket}"

    def create_bucket(self, name: str, log_meta: bool = True) -> None:
        """log_meta=False is the SYNC-REPLAY entry (RGWZoneSync): a
        replayed mutation must not append to THIS zone's mdlog, or
        active-active sync echoes it back — a bounced 'remove' would
        force-clean a bucket the source has since recreated."""
        try:
            known = self.io.omap_get(ROOT_OID, [name])
        except RadosError:
            known = {}
        if name in known:
            raise BucketExists(name)
        self.io.write_full(self._index_oid(name), b"")
        meta = {"created": time.time()}
        self.io.omap_set(ROOT_OID, {name: json.dumps(meta).encode()})
        if log_meta:
            self._mdlog("bucket", name, "write")

    def list_buckets(self) -> List[str]:
        try:
            return sorted(self.io.omap_get(ROOT_OID))
        except RadosError:
            return []

    def _require_bucket(self, name: str) -> None:
        try:
            known = self.io.omap_get(ROOT_OID, [name])
        except RadosError:
            raise NoSuchBucket(name)
        if name not in known:
            raise NoSuchBucket(name)

    def delete_bucket(self, name: str, log_meta: bool = True) -> None:
        self._require_bucket(name)
        # emptiness must consult the RAW index: an in-progress
        # multipart entry (_mp_/...) sorts before most user keys, so a
        # filtered listing could report "empty" while live objects and
        # part data remain (S3: DeleteBucket fails on in-progress
        # uploads too)
        got = self.io.call(self._index_oid(name), "rgw", "index_list",
                           json.dumps({"max_keys": 1}).encode())
        if json.loads(got.decode())["entries"]:
            raise BucketNotEmpty(name)
        try:
            self.io.remove(self._index_oid(name))
        except RadosError:
            pass
        self.io.operate(ROOT_OID, [_omap_rm(name)])
        # the bilog died with the index object: zone data cursors for
        # it are meaningless (a recreated bucket restarts at seq 1) —
        # drop the sync-status object so every zone restarts clean
        try:
            self.io.remove(f"rgw.sync.{name}")
        except RadosError:
            pass
        if log_meta:
            self._mdlog("bucket", name, "remove")

    # -- objects -----------------------------------------------------------
    def _data_oid(self, bucket: str, key: str) -> str:
        return f"rgw.obj.{bucket}/{key}"

    def put_object(self, bucket: str, key: str, data: bytes,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        self._require_bucket(bucket)
        etag = hashlib.md5(data).hexdigest()
        self.striper.write(self._data_oid(bucket, key), data)
        entry = {"size": len(data), "etag": etag,
                 "mtime": time.time(), "meta": metadata or {}}
        # ATOMIC index update inside the PG (cls_rgw role)
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": key, "entry": entry}).encode())
        return etag

    def head_object(self, bucket: str, key: str) -> Dict:
        self._require_bucket(bucket)
        got = self.io.call(self._index_oid(bucket), "rgw", "index_list",
                           json.dumps({"prefix": key,
                                       "max_keys": 1}).encode())
        entries = json.loads(got.decode())["entries"]
        if not entries or entries[0][0] != key:
            raise NoSuchKey(f"{bucket}/{key}")
        return json.loads(entries[0][1])

    def get_object(self, bucket: str, key: str) -> Tuple[bytes, Dict]:
        head = self.head_object(bucket, key)
        manifest = head.get("manifest")
        if manifest:
            # multipart object: stitch the parts in order
            data = b"".join(
                self.striper.read(
                    self._mp_oid(bucket, seg["upload_id"], seg["part"]),
                    seg["size"])
                for seg in manifest)
        else:
            data = self.striper.read(self._data_oid(bucket, key),
                                     head["size"])
        return data, head

    def delete_object(self, bucket: str, key: str) -> None:
        self._require_bucket(bucket)
        try:
            head = self.head_object(bucket, key)
        except NoSuchKey:
            head = {}
        try:
            self.io.call(self._index_oid(bucket), "rgw", "index_rm",
                         key.encode())
        except RadosError as e:
            if e.rc == -2:
                raise NoSuchKey(f"{bucket}/{key}")
            raise
        for seg in head.get("manifest", []):
            try:
                self.striper.remove(self._mp_oid(
                    bucket, seg["upload_id"], seg["part"]))
            except RadosError:
                pass
        try:
            self.striper.remove(self._data_oid(bucket, key))
        except RadosError:
            pass

    # -- multipart upload (reference rgw_multipart.* / RGWMultipart*:
    # parts land as separate striped objects; complete writes a
    # manifest entry whose ETag is md5(part-md5s)-N, and GET stitches
    # the parts in order) --------------------------------------------------
    def create_multipart_upload(self, bucket: str, key: str,
                                metadata: Optional[Dict] = None) -> str:
        self._require_bucket(bucket)
        import secrets

        upload_id = secrets.token_hex(8)
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": f"_mp_/{key}/{upload_id}",
                                 "entry": {"size": 0, "etag": "",
                                           "mtime": time.time(),
                                           "meta": metadata or {},
                                           "parts": {}}}).encode())
        return upload_id

    def _mp_oid(self, bucket: str, upload_id: str, part: int) -> str:
        return f"rgw.mp.{bucket}/{upload_id}/{part}"

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes) -> str:
        self._require_bucket(bucket)
        if not 1 <= part_number <= 10000:
            raise ValueError("part number out of range")
        etag = hashlib.md5(data).hexdigest()
        self.striper.write(self._mp_oid(bucket, upload_id, part_number),
                           data)
        # part bookkeeping rides the same atomic index
        mp_key = f"_mp_/{key}/{upload_id}"
        head = self.head_object(bucket, mp_key)
        head["parts"][str(part_number)] = {"size": len(data),
                                           "etag": etag}
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": mp_key,
                                 "entry": head}).encode())
        return etag

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str) -> str:
        self._require_bucket(bucket)
        mp_key = f"_mp_/{key}/{upload_id}"
        head = self.head_object(bucket, mp_key)
        parts = sorted(((int(n), p) for n, p in head["parts"].items()))
        if not parts:
            raise NoSuchKey(f"no parts for upload {upload_id}")
        # S3 multipart etag: md5 of the concatenated binary part md5s,
        # suffixed with the part count
        md5s = b"".join(bytes.fromhex(p["etag"]) for _, p in parts)
        etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        entry = {"size": sum(p["size"] for _, p in parts), "etag": etag,
                 "mtime": time.time(), "meta": head.get("meta", {}),
                 "manifest": [{"upload_id": upload_id, "part": n,
                               "size": p["size"]} for n, p in parts]}
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": key, "entry": entry}).encode())
        self.io.call(self._index_oid(bucket), "rgw", "index_rm",
                     mp_key.encode())
        return etag

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str) -> None:
        self._require_bucket(bucket)
        mp_key = f"_mp_/{key}/{upload_id}"
        head = self.head_object(bucket, mp_key)
        for n in head["parts"]:
            try:
                self.striper.remove(self._mp_oid(bucket, upload_id,
                                                 int(n)))
            except RadosError:
                pass
        self.io.call(self._index_oid(bucket), "rgw", "index_rm",
                     mp_key.encode())

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: int = 1000
                     ) -> Tuple[List[Dict], bool]:
        """S3 ListObjects: ([{Key, Size, ETag}...], is_truncated)."""
        self._require_bucket(bucket)
        got = self.io.call(self._index_oid(bucket), "rgw", "index_list",
                           json.dumps({"prefix": prefix,
                                       "marker": marker,
                                       "max_keys": max_keys}).encode())
        out = json.loads(got.decode())
        entries = []
        for k, blob in out["entries"]:
            if k.startswith("_mp_/"):
                continue  # in-progress multipart bookkeeping is hidden
            e = json.loads(blob)
            entries.append({"Key": k, "Size": e["size"],
                            "ETag": e["etag"], "Meta": e.get("meta", {})})
        return entries, out["truncated"]


def _omap_rm(key: str):
    from ceph_tpu.osd import types as t_
    from ceph_tpu.osd.types import OSDOp

    return OSDOp(t_.OP_OMAP_RM, keys=[key])
