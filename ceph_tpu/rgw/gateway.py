"""S3-shaped object gateway over the client library.

Reference role: src/rgw/ re-derived on this framework's primitives:
bucket metadata lives in a root registry object (the rgw_directory /
zone bucket-index root role), each bucket's KEY INDEX is an omap on a
bucket-index object maintained ATOMICALLY by an in-OSD `rgw` object
class (the cls_rgw role — index updates execute inside the PG write
pipeline, so a crashed gateway can never leave index/data torn on the
index side), and object payloads ride the striping layer so big
uploads fan out across PGs.

Surface: create/list/delete buckets, put/get/head/delete objects with
ETags + user metadata, prefix/marker/max-keys listing (the S3
ListObjects pagination contract).
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Dict, List, Optional, Tuple

from ceph_tpu.client.rados import IoCtx, RadosError
from ceph_tpu.client.striper import RadosStriper
from ceph_tpu.osd.cls import CLS_RD, CLS_WR, ClassHandler, ClsError
from ceph_tpu.rgw import acl as acl_mod

ROOT_OID = "rgw.root"
# the zone metadata log (mdlog role): ONE module-level name shared by
# the gateway, RGWUserAdmin and the sync agent
META_LOG_OID = "rgw.meta.log"


class NoSuchBucket(KeyError):
    pass


class NoSuchKey(KeyError):
    pass


class BucketExists(ValueError):
    pass


class BucketNotEmpty(ValueError):
    pass


class AccessDenied(PermissionError):
    pass


class NoSuchVersion(KeyError):
    pass


def _register_rgw_cls() -> None:
    """cls_rgw role: atomic bucket-index mutations server-side."""
    h = ClassHandler.instance()
    if h.get("rgw.index_put") is not None:
        return

    # the bucket-index CHANGE LOG rides the same omap under a reserved
    # prefix and is appended in the SAME atomic cls call as the index
    # mutation (reference cls_rgw's bucket index log — the feed
    # multisite data sync replays); "~" is reserved, like the
    # reference's \\x80-prefixed special index entries
    BILOG = "~bilog."
    BILOG_SEQ = "~bilog_seq"

    def _bilog_append(ctx, op: str, key: str) -> None:
        seq = int(ctx.omap_get([BILOG_SEQ]).get(BILOG_SEQ, b"0")) + 1
        ctx.omap_set({
            BILOG_SEQ: str(seq).encode(),
            f"{BILOG}{seq:020d}": json.dumps(
                {"op": op, "key": key}).encode()})

    def index_put(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        if req["key"].startswith("~"):
            # "~" is the reserved index namespace (bilog + counters) —
            # the reference escapes user keys out of its \x80 space
            raise ClsError(-22, "object keys may not start with '~'")
        ctx.omap_set({req["key"]: json.dumps(req["entry"]).encode()})
        _bilog_append(ctx, "put", req["key"])
        return b""

    def index_rm(ctx, indata: bytes) -> bytes:
        key = indata.decode()
        if key not in ctx.omap_get([key]):
            raise ClsError(-2, "no such key")
        ctx.omap_rm([key])
        _bilog_append(ctx, "rm", key)
        return b""

    def index_list(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode() or "{}")
        prefix = req.get("prefix", "")
        marker = req.get("marker", "")
        maxk = int(req.get("max_keys", 1000))
        out = []
        for k in sorted(ctx.omap_get()):
            if k.startswith("~"):  # reserved: bilog + counters
                continue
            if k <= marker or not k.startswith(prefix):
                continue
            out.append((k, ctx.omap_get([k])[k].decode()))
            if len(out) >= maxk + 1:
                break
        truncated = len(out) > maxk
        return json.dumps({"entries": out[:maxk],
                           "truncated": truncated}).encode()

    def _log_list(ctx, indata: bytes, prefix: str) -> bytes:
        req = json.loads(indata.decode() or "{}")
        after = int(req.get("after", 0))
        maxk = int(req.get("max", 1000))
        out = []
        if ctx.exists:
            full = ctx.omap_get()  # ONE read; no per-entry re-fetch
            for k in sorted(full):
                if not k.startswith(prefix):
                    continue
                seq = int(k[len(prefix):])
                if seq <= after:
                    continue
                out.append({"seq": seq, **json.loads(full[k].decode())})
                if len(out) >= maxk:
                    break
        return json.dumps(out).encode()

    def _log_trim(ctx, indata: bytes, prefix: str) -> bytes:
        upto = int(indata.decode() or "0")
        doomed = [k for k in ctx.omap_get()
                  if k.startswith(prefix) and int(k[len(prefix):]) <= upto]
        if doomed:
            ctx.omap_rm(doomed)
        return str(len(doomed)).encode()

    def bilog_list(ctx, indata: bytes) -> bytes:
        return _log_list(ctx, indata, BILOG)

    def bilog_trim(ctx, indata: bytes) -> bytes:
        return _log_trim(ctx, indata, BILOG)

    # the METADATA log (reference rgw_sync.cc mdlog role): user/bucket
    # metadata mutations append here so secondary zones can replay the
    # metadata NAMESPACE (accounts, bucket existence), not just object
    # data — one global log object per zone, same atomic append shape
    # as the bilog
    MDLOG = "~mdlog."
    MDLOG_SEQ = "~mdlog_seq"

    def mdlog_add(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        cur = (ctx.omap_get([MDLOG_SEQ]).get(MDLOG_SEQ, b"0")
               if ctx.exists else b"0")
        seq = int(cur) + 1
        ctx.omap_set({
            MDLOG_SEQ: str(seq).encode(),
            f"{MDLOG}{seq:020d}": json.dumps(
                {"section": req["section"], "name": req["name"],
                 "op": req["op"]}).encode()})
        return str(seq).encode()

    def mdlog_list(ctx, indata: bytes) -> bytes:
        return _log_list(ctx, indata, MDLOG)

    def mdlog_trim(ctx, indata: bytes) -> bytes:
        return _log_trim(ctx, indata, MDLOG)

    # -- versioned-object index rows (reference rgw_rados olh/instance
    # entries, src/cls/rgw/cls_rgw.cc bucket_link_olh): each versioned
    # key keeps an ordered version list in one "~olh/<key>" omap row
    # (oldest..newest; the last entry is current), while the PLAIN key
    # row mirrors the current version so unversioned listings/reads
    # are unchanged.  All transitions are ONE atomic cls call.
    OLH = "~olh/"

    def _cur_row(ver: dict) -> bytes:
        e = {kk: ver[kk] for kk in ("size", "etag", "mtime", "meta",
                                    "owner", "acl", "manifest", "oid",
                                    "vid") if kk in ver}
        return json.dumps(e).encode()

    def ver_put(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        key, ver = req["key"], req["ver"]
        if key.startswith("~"):
            raise ClsError(-22, "object keys may not start with '~'")
        olhk = OLH + key
        got = ctx.omap_get([olhk]) if ctx.exists else {}
        olh = json.loads(got.get(olhk, b"[]").decode())
        replaced = None
        if req.get("replace_null"):
            # suspended-versioning semantics: the "null" version is
            # replaced in place (reference rgw_rados null-instance)
            for v in olh:
                if v["vid"] == "null":
                    replaced = v
            olh = [v for v in olh if v["vid"] != "null"]
        olh.append(ver)
        sets = {olhk: json.dumps(olh).encode()}
        if ver.get("delete_marker"):
            if key in ctx.omap_get([key]):
                ctx.omap_rm([key])
            ctx.omap_set(sets)
            _bilog_append(ctx, "rm", key)
        else:
            sets[key] = _cur_row(ver)
            ctx.omap_set(sets)
            _bilog_append(ctx, "put", key)
        return json.dumps({"replaced": replaced}).encode()

    def ver_rm(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode())
        key, vid = req["key"], req["vid"]
        olhk = OLH + key
        got = ctx.omap_get([olhk]) if ctx.exists else {}
        if olhk not in got:
            raise ClsError(-2, "no such versioned object")
        olh = json.loads(got[olhk].decode())
        hit = [v for v in olh if v["vid"] == vid]
        if not hit:
            raise ClsError(-2, "no such version")
        keep = [v for v in olh if v["vid"] != vid]
        was_current = olh[-1]["vid"] == vid
        if keep:
            sets = {olhk: json.dumps(keep).encode()}
            if was_current:
                cur = keep[-1]
                if cur.get("delete_marker"):
                    ctx.omap_set(sets)
                    if key in ctx.omap_get([key]):
                        ctx.omap_rm([key])
                    _bilog_append(ctx, "rm", key)
                else:
                    sets[key] = _cur_row(cur)
                    ctx.omap_set(sets)
                    _bilog_append(ctx, "put", key)
            else:
                ctx.omap_set(sets)
        else:
            doomed = [olhk]
            if key in ctx.omap_get([key]):
                doomed.append(key)
            ctx.omap_rm(doomed)
            _bilog_append(ctx, "rm", key)
        return json.dumps(hit[0]).encode()

    def ver_update(ctx, indata: bytes) -> bytes:
        """Patch mutable fields (acl/owner/meta) of ONE version in
        place — no history reorder, no bilog entry (ACL changes are
        not data mutations the zone sync replays)."""
        req = json.loads(indata.decode())
        key, vid, patch = req["key"], req["vid"], req["patch"]
        olhk = OLH + key
        got = ctx.omap_get([olhk]) if ctx.exists else {}
        if olhk not in got:
            raise ClsError(-2, "no such versioned object")
        olh = json.loads(got[olhk].decode())
        hit = None
        for v in olh:
            if v["vid"] == vid:
                for f in ("acl", "owner", "meta"):
                    if f in patch:
                        v[f] = patch[f]
                hit = v
        if hit is None:
            raise ClsError(-2, "no such version")
        sets = {olhk: json.dumps(olh).encode()}
        if olh[-1]["vid"] == vid and not hit.get("delete_marker"):
            sets[key] = _cur_row(hit)
        ctx.omap_set(sets)
        return b""

    def index_update(ctx, indata: bytes) -> bytes:
        """Patch mutable fields (acl/owner/meta) of ONE plain index
        row in place (ver_update's non-versioned twin): the merge
        happens inside the cls handler against the row AS STORED, so a
        PUT racing an ACL change keeps its size/etag/oid — the
        read-modify-write the gateway used to do round-tripped a
        stale entry and clobbered the winner.  No bilog entry: ACL
        changes are not data mutations the zone sync replays."""
        req = json.loads(indata.decode())
        key, patch = req["key"], req["patch"]
        got = ctx.omap_get([key]) if ctx.exists else {}
        if key not in got:
            raise ClsError(-2, "no such key")
        entry = json.loads(got[key].decode())
        for f in ("acl", "owner", "meta"):
            if f in patch:
                entry[f] = patch[f]
        ctx.omap_set({key: json.dumps(entry).encode()})
        return b""

    def olh_get(ctx, indata: bytes) -> bytes:
        key = indata.decode()
        olhk = OLH + key
        got = ctx.omap_get([olhk]) if ctx.exists else {}
        if olhk not in got:
            raise ClsError(-2, "no such versioned object")
        return got[olhk]

    def olh_list(ctx, indata: bytes) -> bytes:
        req = json.loads(indata.decode() or "{}")
        prefix = req.get("prefix", "")
        marker = req.get("key_marker", "")
        maxk = int(req.get("max_keys", 1000))
        out = []
        full = ctx.omap_get() if ctx.exists else {}
        for kk in sorted(full):
            if not kk.startswith(OLH):
                continue
            key = kk[len(OLH):]
            if key <= marker or not key.startswith(prefix):
                continue
            out.append((key, json.loads(full[kk].decode())))
            if len(out) >= maxk + 1:
                break
        return json.dumps({"entries": out[:maxk],
                           "truncated": len(out) > maxk}).encode()

    h.register("rgw", "index_put", CLS_RD | CLS_WR, index_put)
    h.register("rgw", "index_update", CLS_RD | CLS_WR, index_update)
    h.register("rgw", "index_rm", CLS_RD | CLS_WR, index_rm)
    h.register("rgw", "index_list", CLS_RD, index_list)
    h.register("rgw", "ver_put", CLS_RD | CLS_WR, ver_put)
    h.register("rgw", "ver_rm", CLS_RD | CLS_WR, ver_rm)
    h.register("rgw", "ver_update", CLS_RD | CLS_WR, ver_update)
    h.register("rgw", "olh_get", CLS_RD, olh_get)
    h.register("rgw", "olh_list", CLS_RD, olh_list)
    h.register("rgw", "bilog_list", CLS_RD, bilog_list)
    h.register("rgw", "bilog_trim", CLS_RD | CLS_WR, bilog_trim)
    h.register("rgw", "mdlog_add", CLS_RD | CLS_WR, mdlog_add)
    h.register("rgw", "mdlog_list", CLS_RD, mdlog_list)
    h.register("rgw", "mdlog_trim", CLS_RD | CLS_WR, mdlog_trim)


_register_rgw_cls()


class RGW:
    def __init__(self, ioctx: IoCtx, stripe_unit: int = 65536,
                 object_size: int = 4 << 20) -> None:
        self.io = ioctx
        self.striper = RadosStriper(ioctx, stripe_unit=stripe_unit,
                                    stripe_count=4,
                                    object_size=object_size)

    # metadata log object: user/bucket namespace mutations append here
    # (the rgw_sync.cc mdlog role; tailed by RGWZoneSync.meta sync)
    META_LOG_OID = META_LOG_OID  # class alias of the module constant

    def _mdlog(self, section: str, name: str, op: str) -> None:
        try:
            self.io.call(self.META_LOG_OID, "rgw", "mdlog_add",
                         json.dumps({"section": section, "name": name,
                                     "op": op}).encode())
        except RadosError:
            pass  # the log is an aux feed, never a mutation blocker

    # -- buckets -----------------------------------------------------------
    def _index_oid(self, bucket: str) -> str:
        return f"rgw.bucket.{bucket}"

    # -- access control (reference rgw_op.cc verify_*_permission) ----
    def _bucket_meta(self, name: str) -> Dict:
        try:
            known = self.io.omap_get(ROOT_OID, [name])
        except RadosError:
            raise NoSuchBucket(name)
        if name not in known:
            raise NoSuchBucket(name)
        return json.loads(known[name].decode())

    def _save_bucket_meta(self, name: str, meta: Dict) -> None:
        self.io.omap_set(ROOT_OID, {name: json.dumps(meta).encode()})

    @staticmethod
    def _bucket_acl(meta: Dict) -> Optional[Dict]:
        a = meta.get("acl")
        if a is None and meta.get("owner"):
            a = {"owner": meta["owner"], "grants": []}
        return a

    def _check_bucket(self, meta: Dict, actor, perm: str) -> None:
        """actor None = internal caller (sync agents, lifecycle, raw
        library users) — never gated, like the reference's system
        users.  A bucket with no recorded owner (pre-ACL metadata)
        stays open for compatibility."""
        if actor is None:
            return
        a = self._bucket_acl(meta)
        if a is None:
            return
        if not acl_mod.allows(a, actor, perm):
            raise AccessDenied(f"{actor!r} lacks {perm} on bucket")

    @staticmethod
    def _check_owner(meta: Dict, actor, what: str) -> None:
        """Owner-only operations (delete bucket, versioning,
        lifecycle): one definition so policy tweaks stay in sync."""
        if actor is not None and meta.get("owner") not in (None, actor):
            raise AccessDenied(f"only the bucket owner may {what}")

    def _check_object(self, bmeta: Dict, entry: Dict, actor,
                      perm: str) -> None:
        if actor is None:
            return
        a = entry.get("acl")
        if a is None:
            owner = entry.get("owner") or bmeta.get("owner")
            if owner is None:
                return
            a = {"owner": owner, "grants": []}
        # the bucket owner always retains READ_ACP/WRITE_ACP-grade
        # control in S3; modeled as bucket-owner bypass
        if actor == bmeta.get("owner"):
            return
        if not acl_mod.allows(a, actor, perm):
            raise AccessDenied(f"{actor!r} lacks {perm} on object")

    def create_bucket(self, name: str, log_meta: bool = True, *,
                      actor: Optional[str] = None,
                      canned: str = "private") -> None:
        """log_meta=False is the SYNC-REPLAY entry (RGWZoneSync): a
        replayed mutation must not append to THIS zone's mdlog, or
        active-active sync echoes it back — a bounced 'remove' would
        force-clean a bucket the source has since recreated."""
        try:
            known = self.io.omap_get(ROOT_OID, [name])
        except RadosError:
            known = {}
        if name in known:
            raise BucketExists(name)
        # ACL validation BEFORE the index object exists: an invalid
        # x-amz-acl must not leak an orphan index object
        meta: Dict = {"created": time.time()}
        if actor is not None:
            meta["owner"] = actor
            meta["acl"] = acl_mod.canned_acl(actor, canned)
        self.io.write_full(self._index_oid(name), b"")
        self.io.omap_set(ROOT_OID, {name: json.dumps(meta).encode()})
        if log_meta:
            self._mdlog("bucket", name, "write")

    # -- bucket ACL subresource --------------------------------------
    def get_bucket_acl(self, name: str, *,
                       actor: Optional[str] = None) -> Dict:
        meta = self._bucket_meta(name)
        self._check_bucket(meta, actor, "READ_ACP")
        a = self._bucket_acl(meta)
        if a is None:
            raise NoSuchKey("bucket has no ACL (pre-ACL metadata)")
        return a

    def put_bucket_acl(self, name: str, policy: Dict, *,
                       actor: Optional[str] = None) -> None:
        meta = self._bucket_meta(name)
        self._check_bucket(meta, actor, "WRITE_ACP")
        policy = acl_mod.validate(policy)
        # ownership is immutable via ?acl (S3: a policy whose Owner
        # differs from the actual owner is rejected) — otherwise a
        # WRITE_ACP grantee could take the bucket over and lock the
        # real owner out
        if meta.get("owner") and policy["owner"] != meta["owner"]:
            raise AccessDenied("ACL owner must match the bucket owner")
        meta["acl"] = policy
        meta.setdefault("owner", policy["owner"])
        self._save_bucket_meta(name, meta)
        self._mdlog("bucket", name, "write")

    # -- versioning subresource (reference rgw_rados versioning) -----
    def set_versioning(self, name: str, status: str, *,
                       actor: Optional[str] = None) -> None:
        if status not in ("Enabled", "Suspended"):
            raise ValueError(f"bad versioning status {status!r}")
        meta = self._bucket_meta(name)
        self._check_owner(meta, actor, "set versioning")
        meta["versioning"] = status
        self._save_bucket_meta(name, meta)
        self._mdlog("bucket", name, "write")

    def get_versioning(self, name: str, *,
                       actor: Optional[str] = None) -> Optional[str]:
        meta = self._bucket_meta(name)
        self._check_bucket(meta, actor, "READ")
        return meta.get("versioning")

    def list_buckets(self) -> List[str]:
        try:
            return sorted(self.io.omap_get(ROOT_OID))
        except RadosError:
            return []

    def _require_bucket(self, name: str) -> None:
        try:
            known = self.io.omap_get(ROOT_OID, [name])
        except RadosError:
            raise NoSuchBucket(name)
        if name not in known:
            raise NoSuchBucket(name)

    def delete_bucket(self, name: str, log_meta: bool = True, *,
                      actor: Optional[str] = None) -> None:
        meta = self._bucket_meta(name)
        self._check_owner(meta, actor, "delete it")
        # emptiness must consult the RAW index: an in-progress
        # multipart entry (_mp_/...) sorts before most user keys, so a
        # filtered listing could report "empty" while live objects and
        # part data remain (S3: DeleteBucket fails on in-progress
        # uploads too)
        got = self.io.call(self._index_oid(name), "rgw", "index_list",
                           json.dumps({"max_keys": 1}).encode())
        if json.loads(got.decode())["entries"]:
            raise BucketNotEmpty(name)
        # versioned buckets: ANY surviving version or delete marker
        # blocks deletion (S3 semantics).  A transient error here must
        # PROPAGATE — swallowing it could delete a bucket whose olh
        # rows (and their rgw.ver.* data) still exist
        vgot = self.io.call(self._index_oid(name), "rgw", "olh_list",
                            json.dumps({"max_keys": 1}).encode())
        if json.loads(vgot.decode())["entries"]:
            raise BucketNotEmpty(name)
        try:
            self.io.remove(self._index_oid(name))
        except RadosError:
            pass
        self.io.operate(ROOT_OID, [_omap_rm(name)])
        # the bilog died with the index object: zone data cursors for
        # it are meaningless (a recreated bucket restarts at seq 1) —
        # drop the sync-status object so every zone restarts clean
        try:
            self.io.remove(f"rgw.sync.{name}")
        except RadosError:
            pass
        if log_meta:
            self._mdlog("bucket", name, "remove")

    # -- objects -----------------------------------------------------------
    def _data_oid(self, bucket: str, key: str) -> str:
        return f"rgw.obj.{bucket}/{key}"

    def _ver_oid(self, bucket: str, vid: str, key: str) -> str:
        # vid-first namespace: version ids are hex tokens, so no user
        # key can collide with another version's oid
        return f"rgw.ver.{bucket}/{vid}/{key}"

    @staticmethod
    def _new_vid() -> str:
        import secrets

        return f"{int(time.time() * 1000):013d}-{secrets.token_hex(4)}"

    def _olh(self, bucket: str, key: str) -> List[Dict]:
        try:
            got = self.io.call(self._index_oid(bucket), "rgw",
                               "olh_get", key.encode())
        except RadosError as e:
            if e.rc == -2:
                raise NoSuchKey(f"{bucket}/{key}")
            raise
        return json.loads(got.decode())

    def _migrate_null(self, bucket: str, key: str) -> None:
        """First versioned op on a key that predates versioning: its
        plain entry becomes the 'null' version (reference rgw_rados
        null-instance semantics), keeping its legacy data oid."""
        try:
            entry = self.head_object(bucket, key)
        except NoSuchKey:
            return
        if entry.get("vid"):
            return  # already versioned
        ver = dict(entry)
        ver["vid"] = "null"
        ver.setdefault("oid", self._data_oid(bucket, key))
        self.io.call(self._index_oid(bucket), "rgw", "ver_put",
                     json.dumps({"key": key, "ver": ver,
                                 "replace_null": True}).encode())

    def put_object(self, bucket: str, key: str, data: bytes,
                   metadata: Optional[Dict[str, str]] = None, *,
                   actor: Optional[str] = None,
                   canned: str = "private") -> str:
        return self.put_object2(bucket, key, data, metadata,
                                actor=actor, canned=canned)["etag"]

    def put_object2(self, bucket: str, key: str, data: bytes,
                    metadata: Optional[Dict[str, str]] = None, *,
                    actor: Optional[str] = None,
                    canned: str = "private") -> Dict:
        """PUT returning {etag, version_id?} (the frontend needs the
        x-amz-version-id response header)."""
        bmeta = self._bucket_meta(bucket)
        self._check_bucket(bmeta, actor, "WRITE")
        etag = hashlib.md5(data).hexdigest()
        entry: Dict = {"size": len(data), "etag": etag,
                       "mtime": time.time(), "meta": metadata or {}}
        owner = actor or bmeta.get("owner")
        if owner:
            entry["owner"] = owner
            entry["acl"] = acl_mod.canned_acl(
                owner, canned, bucket_owner=bmeta.get("owner"))
        vstatus = bmeta.get("versioning")
        if vstatus in ("Enabled", "Suspended"):
            self._migrate_null(bucket, key)
            vid = "null" if vstatus == "Suspended" else self._new_vid()
            oid = self._ver_oid(bucket, vid, key)
            self.striper.write(oid, data)
            entry["vid"] = vid
            entry["oid"] = oid
            got = self.io.call(self._index_oid(bucket), "rgw",
                               "ver_put",
                               json.dumps({"key": key, "ver": entry,
                                           "replace_null":
                                               vid == "null"}).encode())
            replaced = json.loads(got.decode()).get("replaced")
            if replaced and (replaced.get("manifest")
                             or replaced.get("oid") != oid):
                # a replaced null version whose data does NOT share
                # this write's oid (legacy-migrated or multipart)
                self._remove_version_data(bucket, replaced)
            return {"etag": etag, "version_id": vid}
        self.striper.write(self._data_oid(bucket, key), data)
        # ATOMIC index update inside the PG (cls_rgw role)
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": key, "entry": entry}).encode())
        return {"etag": etag}

    def head_object(self, bucket: str, key: str, *,
                    version_id: Optional[str] = None,
                    actor: Optional[str] = None) -> Dict:
        bmeta = self._bucket_meta(bucket)
        if version_id is not None:
            try:
                olh = self._olh(bucket, key)
            except NoSuchKey:
                olh = None
            if olh is None:
                if version_id == "null":
                    # implicit null: the object predates versioning
                    # and no versioned op has migrated it yet — S3
                    # defines it as version "null" from the moment
                    # versioning is enabled
                    entry = self.head_object(bucket, key, actor=actor)
                    if not entry.get("vid"):
                        return dict(entry, vid="null")
                raise NoSuchVersion(f"{bucket}/{key}@{version_id}")
            for v in olh:
                if v["vid"] == version_id:
                    if v.get("delete_marker"):
                        raise NoSuchKey(f"{bucket}/{key}")
                    self._check_object(bmeta, v, actor, "READ")
                    return v
            raise NoSuchVersion(f"{bucket}/{key}@{version_id}")
        got = self.io.call(self._index_oid(bucket), "rgw", "index_list",
                           json.dumps({"prefix": key,
                                       "max_keys": 1}).encode())
        entries = json.loads(got.decode())["entries"]
        if not entries or entries[0][0] != key:
            raise NoSuchKey(f"{bucket}/{key}")
        entry = json.loads(entries[0][1])
        self._check_object(bmeta, entry, actor, "READ")
        return entry

    def get_object(self, bucket: str, key: str, *,
                   version_id: Optional[str] = None,
                   actor: Optional[str] = None) -> Tuple[bytes, Dict]:
        head = self.head_object(bucket, key, version_id=version_id,
                                actor=actor)
        manifest = head.get("manifest")
        if manifest:
            # multipart object: stitch the parts in order
            data = b"".join(
                self.striper.read(
                    self._mp_oid(bucket, seg["upload_id"], seg["part"]),
                    seg["size"])
                for seg in manifest)
        else:
            oid = head.get("oid") or self._data_oid(bucket, key)
            data = self.striper.read(oid, head["size"])
        return data, head

    # -- object ACL subresource --------------------------------------
    def get_object_acl(self, bucket: str, key: str, *,
                       actor: Optional[str] = None) -> Dict:
        bmeta = self._bucket_meta(bucket)
        entry = self.head_object(bucket, key)
        self._check_object(bmeta, entry, actor, "READ_ACP")
        a = entry.get("acl")
        if a is None:
            owner = entry.get("owner") or bmeta.get("owner")
            if owner is None:
                raise NoSuchKey("object has no ACL (pre-ACL entry)")
            a = {"owner": owner, "grants": []}
        return a

    def put_object_acl(self, bucket: str, key: str, policy: Dict, *,
                       actor: Optional[str] = None) -> None:
        bmeta = self._bucket_meta(bucket)
        entry = self.head_object(bucket, key)
        self._check_object(bmeta, entry, actor, "WRITE_ACP")
        policy = acl_mod.validate(policy)
        cur_owner = entry.get("owner") or bmeta.get("owner")
        if cur_owner and policy["owner"] != cur_owner:
            raise AccessDenied("ACL owner must match the object owner")
        if entry.get("vid"):
            # ONE atomic in-place patch of the version row (ver_update
            # — a drop+re-add would reorder history and a crash
            # between the calls would lose the version)
            self.io.call(
                self._index_oid(bucket), "rgw", "ver_update",
                json.dumps({"key": key, "vid": entry["vid"],
                            "patch": {"acl": policy,
                                      "owner": entry.get(
                                          "owner",
                                          policy["owner"])}}).encode())
            return
        # same atomic in-place discipline as the versioned branch: the
        # cls handler merges acl/owner into the row AS STORED, so a
        # concurrent PUT's fresh size/etag/oid survives (round-tripping
        # the stale `entry` here lost the race)
        self.io.call(
            self._index_oid(bucket), "rgw", "index_update",
            json.dumps({"key": key,
                        "patch": {"acl": policy,
                                  "owner": entry.get(
                                      "owner", policy["owner"])}}).encode())

    def delete_object(self, bucket: str, key: str, *,
                      version_id: Optional[str] = None,
                      actor: Optional[str] = None) -> Dict:
        """Returns {} for plain deletes, {delete_marker: True,
        version_id} when a marker was created, {version_id} when a
        specific version was removed (the S3 response headers)."""
        bmeta = self._bucket_meta(bucket)
        self._check_bucket(bmeta, actor, "WRITE")
        vstatus = bmeta.get("versioning")
        if version_id is not None:
            if version_id == "null":
                # a legacy pre-versioning object IS the null version:
                # materialize its olh row so ver_rm can act on it
                self._migrate_null(bucket, key)
            try:
                got = self.io.call(
                    self._index_oid(bucket), "rgw", "ver_rm",
                    json.dumps({"key": key,
                                "vid": version_id}).encode())
            except RadosError as e:
                if e.rc == -2:
                    raise NoSuchVersion(f"{bucket}/{key}@{version_id}")
                raise
            removed = json.loads(got.decode())
            self._remove_version_data(bucket, removed)
            return {"version_id": version_id,
                    "delete_marker": bool(removed.get("delete_marker"))}
        if vstatus in ("Enabled", "Suspended"):
            self._migrate_null(bucket, key)
            # Idempotence guard (deliberate S3 divergence: S3 stacks a
            # marker per DELETE even on absent keys).  A replayed zone-
            # sync 'rm' or a retried drain must CONVERGE: absent key ->
            # NoSuchKey like the unversioned path; already-deleted ->
            # return the existing marker instead of stacking another.
            try:
                olh = self._olh(bucket, key)
            except NoSuchKey:
                olh = []
            if not olh:
                raise NoSuchKey(f"{bucket}/{key}")
            if olh[-1].get("delete_marker"):
                return {"delete_marker": True,
                        "version_id": olh[-1]["vid"]}
            vid = "null" if vstatus == "Suspended" else self._new_vid()
            marker = {"vid": vid, "mtime": time.time(),
                      "delete_marker": True,
                      "owner": actor or bmeta.get("owner")}
            got = self.io.call(self._index_oid(bucket), "rgw", "ver_put",
                               json.dumps({"key": key, "ver": marker,
                                           "replace_null":
                                               vid == "null"}).encode())
            replaced = json.loads(got.decode()).get("replaced")
            if replaced:
                # suspended delete removes the null version's data
                self._remove_version_data(bucket, replaced)
            return {"delete_marker": True, "version_id": vid}
        try:
            head = self.head_object(bucket, key)
        except NoSuchKey:
            head = {}
        try:
            self.io.call(self._index_oid(bucket), "rgw", "index_rm",
                         key.encode())
        except RadosError as e:
            if e.rc == -2:
                raise NoSuchKey(f"{bucket}/{key}")
            raise
        for seg in head.get("manifest", []):
            try:
                self.striper.remove(self._mp_oid(
                    bucket, seg["upload_id"], seg["part"]))
            except RadosError:
                pass
        try:
            self.striper.remove(self._data_oid(bucket, key))
        except RadosError:
            pass
        return {}

    def _remove_version_data(self, bucket: str, ver: Dict) -> None:
        if ver.get("delete_marker"):
            return
        for seg in ver.get("manifest", []):
            try:
                self.striper.remove(self._mp_oid(
                    bucket, seg["upload_id"], seg["part"]))
            except RadosError:
                pass
        oid = ver.get("oid")
        if oid and not ver.get("manifest"):
            try:
                self.striper.remove(oid)
            except RadosError:
                pass

    def list_object_versions(self, bucket: str, prefix: str = "",
                             key_marker: str = "",
                             max_keys: int = 1000, *,
                             actor: Optional[str] = None,
                             with_marker: bool = False):
        """S3 ListObjectVersions: newest-first per key, is_latest on
        the current version (reference rgw_rados list_objects with
        list_versions=true).

        `with_marker=True` appends the raw continuation key-marker: the
        dual-listing bound clamp below can drop EVERY visible row from
        a truncated page, and a pager resuming from its last visible
        key would then re-fetch the same page forever (or give up and
        abandon the bucket — the lc_process stall)."""
        bmeta = self._bucket_meta(bucket)
        self._check_bucket(bmeta, actor, "READ")
        got = self.io.call(self._index_oid(bucket), "rgw", "olh_list",
                           json.dumps({"prefix": prefix,
                                       "key_marker": key_marker,
                                       "max_keys": max_keys}).encode())
        out = json.loads(got.decode())
        per_key: Dict[str, List[Dict]] = {}
        for key, olh in out["entries"]:
            per_key[key] = [{
                "Key": key, "VersionId": v["vid"],
                "IsLatest": idx == 0,
                "IsDeleteMarker": bool(v.get("delete_marker")),
                "Size": v.get("size", 0),
                "ETag": v.get("etag", ""),
                "LastModified": v.get("mtime", 0.0),
            } for idx, v in enumerate(reversed(olh))]
        # implicit null versions: plain rows that predate versioning
        # and were never touched by a versioned op have no olh row —
        # S3 still lists them as the latest "null" version
        pgot = self.io.call(self._index_oid(bucket), "rgw",
                            "index_list",
                            json.dumps({"prefix": prefix,
                                        "marker": key_marker,
                                        "max_keys": max_keys}).encode())
        pout = json.loads(pgot.decode())
        for key, blob in pout["entries"]:
            if key in per_key or key.startswith("_mp_/"):
                continue
            e = json.loads(blob)
            if e.get("vid"):
                continue
            per_key[key] = [{
                "Key": key, "VersionId": "null", "IsLatest": True,
                "IsDeleteMarker": False, "Size": e.get("size", 0),
                "ETag": e.get("etag", ""),
                "LastModified": e.get("mtime", 0.0),
            }]
        # dual-listing truncation: each page enumerates keys up to ITS
        # OWN last key — a merged page may only extend to the SMALLER
        # of the two bounds, or marker-based continuation skips keys
        # between the truncation points (review finding)
        truncated = bool(out["truncated"] or pout["truncated"])
        next_key = ""
        if truncated:
            bounds = []
            if out["truncated"] and out["entries"]:
                bounds.append(out["entries"][-1][0])
            if pout["truncated"] and pout["entries"]:
                bounds.append(pout["entries"][-1][0])
            if bounds:
                bound = min(bounds)
                per_key = {k: v for k, v in per_key.items()
                           if k <= bound}
                next_key = bound
        rows: List[Dict] = []
        for key in sorted(per_key):
            rows.extend(per_key[key])
        if with_marker:
            return rows, truncated, next_key
        return rows, truncated

    # -- multipart upload (reference rgw_multipart.* / RGWMultipart*:
    # parts land as separate striped objects; complete writes a
    # manifest entry whose ETag is md5(part-md5s)-N, and GET stitches
    # the parts in order) --------------------------------------------------
    def create_multipart_upload(self, bucket: str, key: str,
                                metadata: Optional[Dict] = None, *,
                                actor: Optional[str] = None) -> str:
        self._check_bucket(self._bucket_meta(bucket), actor, "WRITE")
        import secrets

        upload_id = secrets.token_hex(8)
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": f"_mp_/{key}/{upload_id}",
                                 "entry": {"size": 0, "etag": "",
                                           "mtime": time.time(),
                                           "meta": metadata or {},
                                           "parts": {}}}).encode())
        return upload_id

    def _mp_oid(self, bucket: str, upload_id: str, part: int) -> str:
        return f"rgw.mp.{bucket}/{upload_id}/{part}"

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, data: bytes, *,
                    actor: Optional[str] = None) -> str:
        self._check_bucket(self._bucket_meta(bucket), actor, "WRITE")
        if not 1 <= part_number <= 10000:
            raise ValueError("part number out of range")
        etag = hashlib.md5(data).hexdigest()
        self.striper.write(self._mp_oid(bucket, upload_id, part_number),
                           data)
        # part bookkeeping rides the same atomic index
        mp_key = f"_mp_/{key}/{upload_id}"
        head = self.head_object(bucket, mp_key)
        head["parts"][str(part_number)] = {"size": len(data),
                                           "etag": etag}
        self.io.call(self._index_oid(bucket), "rgw", "index_put",
                     json.dumps({"key": mp_key,
                                 "entry": head}).encode())
        return etag

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str, *,
                                  actor: Optional[str] = None) -> str:
        bmeta = self._bucket_meta(bucket)
        self._check_bucket(bmeta, actor, "WRITE")
        mp_key = f"_mp_/{key}/{upload_id}"
        head = self.head_object(bucket, mp_key)
        parts = sorted(((int(n), p) for n, p in head["parts"].items()))
        if not parts:
            raise NoSuchKey(f"no parts for upload {upload_id}")
        # S3 multipart etag: md5 of the concatenated binary part md5s,
        # suffixed with the part count
        md5s = b"".join(bytes.fromhex(p["etag"]) for _, p in parts)
        etag = f"{hashlib.md5(md5s).hexdigest()}-{len(parts)}"
        entry: Dict = {
            "size": sum(p["size"] for _, p in parts), "etag": etag,
            "mtime": time.time(), "meta": head.get("meta", {}),
            "manifest": [{"upload_id": upload_id, "part": n,
                          "size": p["size"]} for n, p in parts]}
        owner = actor or bmeta.get("owner")
        if owner:
            entry["owner"] = owner
            entry["acl"] = acl_mod.canned_acl(
                owner, bucket_owner=bmeta.get("owner"))
        if bmeta.get("versioning") in ("Enabled", "Suspended"):
            # a completed multipart object versions like any PUT; its
            # data lives in the upload's part objects (unique per
            # upload id, so versions never collide)
            self._migrate_null(bucket, key)
            vid = ("null" if bmeta["versioning"] == "Suspended"
                   else self._new_vid())
            entry["vid"] = vid
            got = self.io.call(self._index_oid(bucket), "rgw",
                               "ver_put",
                               json.dumps({"key": key, "ver": entry,
                                           "replace_null":
                                               vid == "null"}).encode())
            replaced = json.loads(got.decode()).get("replaced")
            if replaced:
                self._remove_version_data(bucket, replaced)
        else:
            self.io.call(self._index_oid(bucket), "rgw", "index_put",
                         json.dumps({"key": key,
                                     "entry": entry}).encode())
        self.io.call(self._index_oid(bucket), "rgw", "index_rm",
                     mp_key.encode())
        return etag

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str, *,
                               actor: Optional[str] = None) -> None:
        self._check_bucket(self._bucket_meta(bucket), actor, "WRITE")
        mp_key = f"_mp_/{key}/{upload_id}"
        head = self.head_object(bucket, mp_key)
        for n in head["parts"]:
            try:
                self.striper.remove(self._mp_oid(bucket, upload_id,
                                                 int(n)))
            except RadosError:
                pass
        self.io.call(self._index_oid(bucket), "rgw", "index_rm",
                     mp_key.encode())

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: int = 1000, *,
                     actor: Optional[str] = None,
                     with_marker: bool = False):
        """S3 ListObjects: ([{Key, Size, ETag}...], is_truncated).

        `with_marker=True` appends the RAW continuation marker (the
        last index key the page scanned, hidden `_mp_/` rows included):
        a truncated page whose visible entries all filtered out
        otherwise gives the caller nothing to resume from, and pagers
        that track the last VISIBLE key abandon the rest of the bucket
        (the lc_process stall)."""
        self._check_bucket(self._bucket_meta(bucket), actor, "READ")
        got = self.io.call(self._index_oid(bucket), "rgw", "index_list",
                           json.dumps({"prefix": prefix,
                                       "marker": marker,
                                       "max_keys": max_keys}).encode())
        out = json.loads(got.decode())
        entries = []
        for k, blob in out["entries"]:
            if k.startswith("_mp_/"):
                continue  # in-progress multipart bookkeeping is hidden
            e = json.loads(blob)
            entries.append({"Key": k, "Size": e["size"],
                            "ETag": e["etag"], "Meta": e.get("meta", {})})
        if with_marker:
            nxt = (out["entries"][-1][0]
                   if out["truncated"] and out["entries"] else "")
            return entries, out["truncated"], nxt
        return entries, out["truncated"]


    # -- lifecycle (reference src/rgw/rgw_lc.cc RGWLC) ----------------
    def put_lifecycle(self, bucket: str, rules: List[Dict], *,
                      actor: Optional[str] = None) -> None:
        meta = self._bucket_meta(bucket)
        self._check_owner(meta, actor, "set lifecycle")
        clean = []
        for r in rules:
            if r.get("status", "Enabled") not in ("Enabled", "Disabled"):
                raise ValueError(f"bad rule status {r.get('status')!r}")
            days = r.get("expiration_days")
            nc = r.get("noncurrent_days")
            if days is None and nc is None:
                raise ValueError("rule needs expiration_days and/or "
                                 "noncurrent_days")
            if (days is not None and int(days) < 1) or \
                    (nc is not None and int(nc) < 1):
                raise ValueError("expiration days must be >= 1")
            clean.append({
                "id": r.get("id") or f"rule-{len(clean)}",
                "prefix": r.get("prefix", ""),
                "status": r.get("status", "Enabled"),
                **({"expiration_days": int(days)}
                   if days is not None else {}),
                **({"noncurrent_days": int(nc)}
                   if nc is not None else {}),
            })
        meta["lifecycle"] = clean
        self._save_bucket_meta(bucket, meta)
        self._mdlog("bucket", bucket, "write")

    def get_lifecycle(self, bucket: str, *,
                      actor: Optional[str] = None) -> List[Dict]:
        meta = self._bucket_meta(bucket)
        self._check_bucket(meta, actor, "READ")
        lc = meta.get("lifecycle")
        if not lc:
            raise NoSuchKey(f"no lifecycle on {bucket}")
        return lc

    def delete_lifecycle(self, bucket: str, *,
                         actor: Optional[str] = None) -> None:
        meta = self._bucket_meta(bucket)
        self._check_owner(meta, actor, "set lifecycle")
        meta.pop("lifecycle", None)
        self._save_bucket_meta(bucket, meta)

    def lc_process(self, bucket: Optional[str] = None,
                   now: Optional[float] = None) -> Dict:
        """One lifecycle pass (the RGWLC::process worker role —
        reference runs it on a schedule; tools/radosgw.py ticks it).
        Expiration of CURRENT objects deletes them (which in a
        versioned bucket lays a delete marker, rgw_lc.cc semantics);
        noncurrent_days expires NONCURRENT versions for good."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "noncurrent_expired": 0, "buckets": 0}
        names = [bucket] if bucket else self.list_buckets()
        for name in names:
            try:
                meta = self._bucket_meta(name)
            except NoSuchBucket:
                continue
            rules = [r for r in meta.get("lifecycle", [])
                     if r.get("status") == "Enabled"]
            if not rules:
                continue
            stats["buckets"] += 1
            for rule in rules:
                pref = rule.get("prefix", "")
                days = rule.get("expiration_days")
                if days is not None:
                    cutoff = now - days * 86400
                    marker = ""
                    while True:
                        entries, truncated, nxt = self.list_objects(
                            name, prefix=pref, marker=marker,
                            max_keys=1000, with_marker=True)
                        for e in entries:
                            head = self.head_object(name, e["Key"])
                            if head.get("mtime", now) <= cutoff:
                                self.delete_object(name, e["Key"])
                                stats["expired"] += 1
                        # continue from the RAW last key scanned, not
                        # the last visible entry: a truncated page of
                        # nothing but hidden rows used to abandon the
                        # rest of the bucket here
                        if not truncated or nxt <= marker:
                            break
                        marker = nxt
                nc = rule.get("noncurrent_days")
                if nc is not None:
                    cutoff = now - nc * 86400
                    kmarker = ""
                    while True:
                        rows, truncated, nxt = \
                            self.list_object_versions(
                                name, prefix=pref, key_marker=kmarker,
                                max_keys=1000, with_marker=True)
                        for row in rows:
                            if row["IsLatest"]:
                                continue
                            if row["LastModified"] <= cutoff:
                                self.delete_object(
                                    name, row["Key"],
                                    version_id=row["VersionId"])
                                stats["noncurrent_expired"] += 1
                        # raw continuation marker: the dual-listing
                        # bound clamp can leave a truncated page with
                        # zero visible rows — resuming from the last
                        # visible key would abandon the bucket
                        if not truncated or nxt <= kmarker:
                            break
                        kmarker = nxt
        return stats


def _omap_rm(key: str):
    from ceph_tpu.osd import types as t_
    from ceph_tpu.osd.types import OSDOp

    return OSDOp(t_.OP_OMAP_RM, keys=[key])
