"""RGW-role object gateway: S3-shaped buckets/objects over RADOS
(reference: src/rgw/)."""

from ceph_tpu.rgw.gateway import (
    BucketExists,
    BucketNotEmpty,
    NoSuchBucket,
    NoSuchKey,
    RGW,
)

__all__ = ["RGW", "NoSuchBucket", "NoSuchKey", "BucketExists",
           "BucketNotEmpty"]
