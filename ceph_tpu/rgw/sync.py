"""RGW multisite data sync — zone-to-zone object replication.

Reference role: src/rgw/rgw_data_sync.cc (+ rgw_sync.cc metadata
sync): a secondary zone tails the primary's bucket-index logs and
replays the changes against its own store.  Re-derived here:

- the CHANGE FEED is the per-bucket index log (`~bilog.*` omap
  entries, appended atomically with every index mutation by the rgw
  cls — see gateway._register_rgw_cls), the same shape as the
  reference's cls_rgw bucket index log;
- RGWZoneSync tails every source bucket's bilog past a persisted
  per-bucket cursor, fetches changed objects from the source gateway
  and applies them to the destination (puts copy data + user
  metadata; rms delete), then commits the cursor — replay is
  idempotent, so a crash between apply and commit re-applies at most
  one batch;
- cursors are cls_journal CLIENTS registered on a dedicated per-bucket
  sync-status object in the SOURCE zone (one consumer per destination
  zone), so the source can see every zone's sync position — the
  reference's sync-status markers.  A separate object keeps the
  consumer bookkeeping out of the bucket index omap the S3 listings
  iterate.

METADATA sync (the reference's rgw_sync.cc companion to data sync):
user/bucket namespace mutations append to the source zone's mdlog
(`rgw.meta.log`, see gateway mdlog_add) and are replayed here —
account records (with their key index) copy verbatim, bucket removes
propagate (force-cleaning any object data the removed source bilog
can no longer replay).  Buckets additionally replicate on sight
during data sync so object replay never races the namespace.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from ceph_tpu.client.rados import RadosError
from ceph_tpu.rgw.gateway import (RGW, BucketExists, NoSuchBucket,
                                  NoSuchKey)


class RGWZoneSync:
    """One-direction sync agent: src zone -> dst zone."""

    def __init__(self, src: RGW, dst: RGW, zone: str = "secondary",
                 interval: float = 0.1) -> None:
        self.src = src
        self.dst = dst
        self.zone = zone
        self.interval = interval
        self.applied = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- cursors (cls_journal clients on the src bucket index) ------------
    def _client_id(self) -> str:
        return f"zone.{self.zone}"

    def _status_oid(self, bucket: str) -> str:
        return f"rgw.sync.{bucket}"

    def _cursor_at(self, oid: str) -> int:
        """Read (registering on first contact) this zone's commit
        cursor on a sync-status object."""
        try:
            got = self.src.io.call(oid, "journal", "get_client",
                                   self._client_id().encode())
        except RadosError as e:
            if e.rc == -2:
                try:
                    self.src.io.call(
                        oid, "journal", "client_register",
                        json.dumps({"id": self._client_id()}).encode())
                except RadosError as e2:
                    if e2.rc != -17:
                        raise
                return 0
            raise
        return int(json.loads(got.decode()).get("commit", 0))

    def _commit_at(self, oid: str, seq: int) -> None:
        self.src.io.call(oid, "journal", "client_commit",
                         json.dumps({"id": self._client_id(),
                                     "commit": seq}).encode())

    def _cursor(self, bucket: str) -> int:
        return self._cursor_at(self._status_oid(bucket))

    def _commit(self, bucket: str, seq: int) -> None:
        self._commit_at(self._status_oid(bucket), seq)

    # -- one pass ----------------------------------------------------------
    def _bilog(self, bucket: str, after: int) -> List[dict]:
        got = self.src.io.call(self.src._index_oid(bucket), "rgw",
                               "bilog_list",
                               json.dumps({"after": after}).encode())
        return json.loads(got.decode())

    # -- metadata sync (mdlog replay) --------------------------------------
    META_SYNC_OID = "rgw.meta.sync"

    def _meta_cursor(self) -> int:
        return self._cursor_at(self.META_SYNC_OID)

    def meta_sync_once(self) -> int:
        """Replay the source mdlog: user records copy verbatim (same
        access/secret keys authenticate in either zone), bucket
        removes force-clean the destination (a removed source bucket's
        bilog is gone, so the remove IS the authoritative end state)."""
        from ceph_tpu.rgw.users import KEYS_OID, USERS_OID

        cursor = self._meta_cursor()
        got = self.src.io.call(
            self.src.META_LOG_OID, "rgw", "mdlog_list",
            json.dumps({"after": cursor}).encode())
        last, n = cursor, 0
        for ev in json.loads(got.decode()):
            section, name, op = ev["section"], ev["name"], ev["op"]
            if section == "user":
                if op == "write":
                    raw = self.src.io.omap_get(USERS_OID, [name]
                                               ).get(name)
                    if raw is not None:
                        rec = json.loads(raw.decode())
                        self.dst.io.omap_set(USERS_OID, {name: raw})
                        self.dst.io.omap_set(
                            KEYS_OID,
                            {rec["access_key"]: name.encode()})
                else:
                    try:
                        raw = self.dst.io.omap_get(USERS_OID, [name]
                                                   ).get(name)
                        if raw is not None:
                            rec = json.loads(raw.decode())
                            self.dst.io.omap_rm(USERS_OID, [name])
                            self.dst.io.omap_rm(
                                KEYS_OID, [rec["access_key"]])
                    except RadosError:
                        pass
            elif section == "bucket":
                # log_meta=False everywhere: a REPLAYED mutation must
                # not append to the destination's own mdlog — in
                # active-active sync the echoed event would bounce
                # back (a bounced remove force-cleans a bucket the
                # source has since recreated: data loss)
                if op == "write":
                    try:
                        self.dst.create_bucket(name, log_meta=False)
                    except BucketExists:
                        pass  # replayed create: already converged
                    except RadosError:
                        # TRANSIENT failure: stop the batch with the
                        # cursor still before this event so the next
                        # tick retries — swallowing it would advance
                        # past a create that never happened (ADVICE
                        # r4: data sync's create-on-sight would heal
                        # it only much later)
                        break
                else:
                    try:
                        self._force_remove_bucket(name)
                    except NoSuchBucket:
                        pass
                    except RadosError as e:
                        if e.rc == -16:
                            # not yet drainable: stop the batch HERE so
                            # the cursor stays before this event and
                            # the next tick retries it
                            break
            last = ev["seq"]
            n += 1
        if last != cursor:
            self._commit_at(self.META_SYNC_OID, last)
        return n

    def _force_remove_bucket(self, name: str) -> None:
        """Apply an authoritative source-side bucket removal: drain
        EVERY page of remaining replicated objects, then drop the
        bucket (without echoing to this zone's mdlog)."""
        from ceph_tpu.rgw.gateway import BucketNotEmpty

        while True:
            keys, truncated = self.dst.list_objects(name,
                                                    max_keys=1000)
            for ent in keys:
                try:
                    self.dst.delete_object(name, ent["Key"])
                except (NoSuchKey, NoSuchBucket):
                    pass
            if not truncated:
                break
        try:
            self.dst.delete_bucket(name, log_meta=False)
        except BucketNotEmpty:
            # residue the filtered listing can't see (e.g. in-progress
            # multipart bookkeeping): leave the bucket; the next tick
            # retries from the uncommitted event
            raise RadosError(-16, f"{name}: not yet drainable")

    def sync_once(self) -> int:
        """Replay the zone mdlog (metadata), then tail every source
        bucket's change log (data); returns the number of applied
        changes.  Order doesn't matter for correctness — bucket
        removes force-clean, creates are idempotent, and data sync
        creates buckets on sight — but metadata-first surfaces new
        accounts before their buckets fill."""
        n = self.meta_sync_once()
        for bucket in self.src.list_buckets():
            try:
                self.dst.create_bucket(bucket)  # metadata sync on sight
            except Exception:
                pass  # already there
            cursor = self._cursor(bucket)
            last = cursor
            for ev in self._bilog(bucket, cursor):
                key = ev["key"]
                if key.startswith("_mp_/"):
                    last = ev["seq"]
                    continue  # in-progress multipart bookkeeping
                if ev["op"] == "put":
                    try:
                        data, head = self.src.get_object(bucket, key)
                    except (NoSuchKey, NoSuchBucket):
                        last = ev["seq"]
                        continue  # deleted again since: rm event follows
                    self.dst.put_object(bucket, key, data,
                                        metadata=head.get("meta", {}))
                else:
                    try:
                        self.dst.delete_object(bucket, key)
                    except (NoSuchKey, NoSuchBucket):
                        pass
                last = ev["seq"]
                n += 1
            if last != cursor:
                self._commit(bucket, last)
        self.applied += n
        return n

    # -- daemon ------------------------------------------------------------
    def start(self) -> "RGWZoneSync":
        if self._thread is not None and self._thread.is_alive():
            return self

        def _loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.sync_once()
                except Exception:
                    continue  # transient (peer down): retry next tick

        self._stop.clear()
        self._thread = threading.Thread(
            target=_loop, daemon=True, name=f"rgw-sync-{self.zone}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
