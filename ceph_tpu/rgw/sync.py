"""RGW multisite data sync — zone-to-zone object replication.

Reference role: src/rgw/rgw_data_sync.cc (+ rgw_sync.cc metadata
sync): a secondary zone tails the primary's bucket-index logs and
replays the changes against its own store.  Re-derived here:

- the CHANGE FEED is the per-bucket index log (`~bilog.*` omap
  entries, appended atomically with every index mutation by the rgw
  cls — see gateway._register_rgw_cls), the same shape as the
  reference's cls_rgw bucket index log;
- RGWZoneSync tails every source bucket's bilog past a persisted
  per-bucket cursor, fetches changed objects from the source gateway
  and applies them to the destination (puts copy data + user
  metadata; rms delete), then commits the cursor — replay is
  idempotent, so a crash between apply and commit re-applies at most
  one batch;
- cursors are cls_journal CLIENTS registered on a dedicated per-bucket
  sync-status object in the SOURCE zone (one consumer per destination
  zone), so the source can see every zone's sync position — the
  reference's sync-status markers.  A separate object keeps the
  consumer bookkeeping out of the bucket index omap the S3 listings
  iterate.

Buckets themselves (metadata sync) replicate on sight: a source
bucket missing on the destination is created before its log replays.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from ceph_tpu.client.rados import RadosError
from ceph_tpu.rgw.gateway import RGW, NoSuchBucket, NoSuchKey


class RGWZoneSync:
    """One-direction sync agent: src zone -> dst zone."""

    def __init__(self, src: RGW, dst: RGW, zone: str = "secondary",
                 interval: float = 0.1) -> None:
        self.src = src
        self.dst = dst
        self.zone = zone
        self.interval = interval
        self.applied = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- cursors (cls_journal clients on the src bucket index) ------------
    def _client_id(self) -> str:
        return f"zone.{self.zone}"

    def _status_oid(self, bucket: str) -> str:
        return f"rgw.sync.{bucket}"

    def _cursor(self, bucket: str) -> int:
        oid = self._status_oid(bucket)
        try:
            got = self.src.io.call(oid, "journal", "get_client",
                                   self._client_id().encode())
        except RadosError as e:
            if e.rc == -2:
                try:
                    self.src.io.call(
                        oid, "journal", "client_register",
                        json.dumps({"id": self._client_id()}).encode())
                except RadosError as e2:
                    if e2.rc != -17:
                        raise
                return 0
            raise
        return int(json.loads(got.decode()).get("commit", 0))

    def _commit(self, bucket: str, seq: int) -> None:
        self.src.io.call(self._status_oid(bucket), "journal",
                         "client_commit",
                         json.dumps({"id": self._client_id(),
                                     "commit": seq}).encode())

    # -- one pass ----------------------------------------------------------
    def _bilog(self, bucket: str, after: int) -> List[dict]:
        got = self.src.io.call(self.src._index_oid(bucket), "rgw",
                               "bilog_list",
                               json.dumps({"after": after}).encode())
        return json.loads(got.decode())

    def sync_once(self) -> int:
        """Tail every source bucket's change log once; returns the
        number of applied changes."""
        n = 0
        for bucket in self.src.list_buckets():
            try:
                self.dst.create_bucket(bucket)  # metadata sync on sight
            except Exception:
                pass  # already there
            cursor = self._cursor(bucket)
            last = cursor
            for ev in self._bilog(bucket, cursor):
                key = ev["key"]
                if key.startswith("_mp_/"):
                    last = ev["seq"]
                    continue  # in-progress multipart bookkeeping
                if ev["op"] == "put":
                    try:
                        data, head = self.src.get_object(bucket, key)
                    except (NoSuchKey, NoSuchBucket):
                        last = ev["seq"]
                        continue  # deleted again since: rm event follows
                    self.dst.put_object(bucket, key, data,
                                        metadata=head.get("meta", {}))
                else:
                    try:
                        self.dst.delete_object(bucket, key)
                    except (NoSuchKey, NoSuchBucket):
                        pass
                last = ev["seq"]
                n += 1
            if last != cursor:
                self._commit(bucket, last)
        self.applied += n
        return n

    # -- daemon ------------------------------------------------------------
    def start(self) -> "RGWZoneSync":
        if self._thread is not None and self._thread.is_alive():
            return self

        def _loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.sync_once()
                except Exception:
                    continue  # transient (peer down): retry next tick

        self._stop.clear()
        self._thread = threading.Thread(
            target=_loop, daemon=True, name=f"rgw-sync-{self.zone}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
