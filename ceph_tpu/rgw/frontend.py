"""RGW HTTP frontend: a real S3 REST endpoint over the gateway.

Reference role: src/rgw/rgw_asio_frontend.cc (the beast HTTP frontend)
+ src/rgw/rgw_rest_s3.cc (S3 REST op dispatch; SigV4 auth completion at
rgw_rest_s3.cc:938).  This frontend owns HTTP parsing + AWS SigV4
canonicalization and delegates storage semantics to `gateway.RGW` and
credential verification to `users.RGWUserAdmin` — the same split the
reference keeps between its frontends and rgw::auth.

Surface (enough for any S3 client speaking path-style requests):
  GET    /                                     list buckets
  PUT    /bucket                               create bucket
  DELETE /bucket                               delete bucket
  GET    /bucket?prefix=&marker=&max-keys=     list objects
  PUT    /bucket/key                           put object
  PUT    /bucket/key?partNumber=N&uploadId=U   upload part
  GET    /bucket/key                           get object
  HEAD   /bucket/key                           head object
  DELETE /bucket/key                           delete object
  POST   /bucket/key?uploads                   create multipart upload
  POST   /bucket/key?uploadId=U                complete multipart upload
  DELETE /bucket/key?uploadId=U                abort multipart upload

Every request must carry AWS SigV4 (Authorization header +
x-amz-content-sha256 + x-amz-date), verified against the cluster's
user database.  `SigV4Session` is the client half (an SDK-shaped
signer over http.client) used by tools and tests.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from xml.sax.saxutils import escape

from ceph_tpu.rgw import acl as _acl
from ceph_tpu.rgw import gateway as gw
from ceph_tpu.rgw.users import AuthFailure, RGWUserAdmin

REGION = "us-east-1"
SERVICE = "s3"


# ---------------------------------------------------------------------------
# SigV4 canonicalization (shared by the verifying server and the
# signing client — the algorithm is AWS's, the code is symmetric)
# ---------------------------------------------------------------------------

def _canonical_query(query: str) -> str:
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    enc = [(urllib.parse.quote(k, safe="-_.~"),
            urllib.parse.quote(v, safe="-_.~")) for k, v in pairs]
    return "&".join(f"{k}={v}" for k, v in sorted(enc))


def _canonical_request(method: str, path: str, query: str,
                       headers: Dict[str, str], signed_headers: str,
                       payload_hash: str) -> str:
    canon_uri = urllib.parse.quote(path, safe="/-_.~")
    names = signed_headers.split(";")
    canon_headers = "".join(
        f"{n}:{' '.join(headers.get(n, '').split())}\n" for n in names)
    return "\n".join([method, canon_uri, _canonical_query(query),
                      canon_headers, signed_headers, payload_hash])


def _string_to_sign(amz_date: str, scope: str, canonical: str) -> str:
    return "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])


def _derive_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = hmac.new(("AWS4" + secret).encode(), date.encode(),
                 hashlib.sha256).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------

class _S3Error(Exception):
    def __init__(self, status: int, code: str, msg: str = "") -> None:
        super().__init__(msg or code)
        self.status = status
        self.code = code


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "ceph-tpu-rgw/1.0"

    # quiet: access logs ride the frontend's perf/log hooks, not stderr
    def log_message(self, fmt, *args):  # noqa: A003
        self.server.frontend._log(10, fmt % args)

    # -- auth -------------------------------------------------------------
    def _authenticate(self, body: bytes) -> Dict:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            raise _S3Error(403, "AccessDenied", "missing SigV4 auth")
        fields = {}
        for kv in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = kv.strip().partition("=")
            fields[k] = v
        try:
            cred = fields["Credential"]
            signed_headers = fields["SignedHeaders"]
            signature = fields["Signature"]
            access_key, date, region, service, term = cred.split("/")
        except (KeyError, ValueError):
            raise _S3Error(403, "AccessDenied", "malformed Authorization")
        if (term != "aws4_request" or service != SERVICE):
            raise _S3Error(403, "AccessDenied", "bad credential scope")
        payload_hash = self.headers.get("x-amz-content-sha256", "")
        if payload_hash != "UNSIGNED-PAYLOAD" and \
                payload_hash != hashlib.sha256(body).hexdigest():
            raise _S3Error(400, "XAmzContentSHA256Mismatch")
        amz_date = self.headers.get("x-amz-date", "")
        if not amz_date.startswith(date):
            raise _S3Error(403, "AccessDenied", "date/scope mismatch")
        parsed = urllib.parse.urlsplit(self.path)
        hdrs = {k.lower(): v for k, v in self.headers.items()}
        canonical = _canonical_request(
            self.command, parsed.path, parsed.query, hdrs,
            signed_headers, payload_hash)
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = _string_to_sign(amz_date, scope, canonical)
        try:
            return self.server.frontend.users.authenticate(
                access_key, date, region, sts, signature)
        except AuthFailure as e:
            raise _S3Error(403, "SignatureDoesNotMatch", str(e))

    # -- plumbing ---------------------------------------------------------
    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _reply(self, status: int, body: bytes = b"",
               ctype: str = "application/xml",
               extra: Optional[Dict[str, str]] = None) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if self.command != "HEAD" and body:
            self.wfile.write(body)

    def _error(self, e: _S3Error) -> None:
        body = (f"<?xml version=\"1.0\"?><Error><Code>{e.code}</Code>"
                f"<Message>{escape(str(e))}</Message></Error>").encode()
        self._reply(e.status, body)

    # -- Swift dialect (reference rgw Swift API + tempauth) ---------------
    def _swift_auth(self) -> None:
        """GET /auth/v1.0 with X-Auth-User/X-Auth-Key -> token + URL
        (the tempauth handshake Swift clients start with)."""
        user = self.headers.get("X-Auth-User", "")
        key = self.headers.get("X-Auth-Key", "")
        fe = self.server.frontend
        try:
            info = fe.users.resolve_key(user)
        except Exception:
            raise _S3Error(403, "AccessDenied", "bad swift credentials")
        import hmac as _hmac

        if not _hmac.compare_digest(info["secret_key"], key) \
                or info.get("suspended"):
            raise _S3Error(403, "AccessDenied", "bad swift credentials")
        import secrets as _secrets
        import time as _time

        token = "AUTH_tk" + _secrets.token_hex(16)
        now = _time.time()
        # expire stale tokens on issue so the table stays bounded
        fe._swift_tokens = {t: (u, exp) for t, (u, exp)
                            in fe._swift_tokens.items() if exp > now}
        fe._swift_tokens[token] = (info["uid"],
                                   now + fe.swift_token_ttl)
        host, port = self.server.server_address[:2]
        self._reply(204, extra={
            "X-Auth-Token": token,
            "X-Storage-Url": f"http://{host}:{port}/swift/v1"})

    def _swift_route(self, body: bytes) -> None:
        """Swift REST verbs (reference rgw_rest_swift.cc): containers
        and objects over the SAME bucket/object store as S3."""
        fe = self.server.frontend
        token = self.headers.get("X-Auth-Token", "")
        import time as _time

        entry = fe._swift_tokens.get(token)
        if entry is None or entry[1] < _time.time():
            fe._swift_tokens.pop(token, None)
            raise _S3Error(401, "Unauthorized", "bad or missing token")
        # suspension takes effect on USE, not only at issue time
        try:
            if fe.users.user_info(entry[0]).get("suspended"):
                raise _S3Error(401, "Unauthorized", "user suspended")
        except _S3Error:
            raise
        except Exception:
            raise _S3Error(401, "Unauthorized", "unknown user")
        actor = entry[0]
        parsed = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parsed.query,
                                        keep_blank_values=True))
        parts = parsed.path[len("/swift/v1"):].lstrip("/").split("/", 1)
        container = urllib.parse.unquote(parts[0]) if parts[0] else ""
        obj = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        rgw = fe.rgw
        meth = self.command
        try:
            if not container:
                if meth not in ("GET", "HEAD"):
                    raise _S3Error(405, "MethodNotAllowed")
                names = rgw.list_buckets()
                self._reply(200, "\n".join(names).encode() + b"\n",
                            ctype="text/plain")
            elif not obj:
                if meth == "PUT":
                    try:
                        rgw.create_bucket(container, actor=actor)
                        self._reply(201)
                    except gw.BucketExists:
                        self._reply(202)  # swift: idempotent PUT
                elif meth == "DELETE":
                    rgw.delete_bucket(container, actor=actor)
                    self._reply(204)
                elif meth in ("GET", "HEAD"):
                    entries, _tr = rgw.list_objects(
                        container, prefix=q.get("prefix", ""),
                        marker=q.get("marker", ""),
                        max_keys=int(q.get("limit", 1000)),
                        actor=actor)
                    if q.get("format") == "json":
                        rows = json.dumps(
                            [{"name": e["Key"], "bytes": e["Size"],
                              "hash": e["ETag"]} for e in entries])
                        self._reply(200, rows.encode(),
                                    ctype="application/json")
                    else:
                        listing = "\n".join(e["Key"] for e in entries)
                        self._reply(200, listing.encode() + b"\n",
                                    ctype="text/plain")
                else:
                    raise _S3Error(405, "MethodNotAllowed")
            else:
                if meth == "PUT":
                    meta = {k[len("x-object-meta-"):]: v
                            for k, v in self.headers.items()
                            if k.lower().startswith("x-object-meta-")}
                    etag = rgw.put_object(container, obj, body,
                                          metadata=meta, actor=actor)
                    self._reply(201, extra={"ETag": etag})
                elif meth == "GET":
                    data, head = rgw.get_object(container, obj,
                                                actor=actor)
                    extra = {"ETag": head["etag"]}
                    extra.update({f"X-Object-Meta-{k}": v for k, v in
                                  head.get("meta", {}).items()})
                    self._reply(200, data,
                                ctype="application/octet-stream",
                                extra=extra)
                elif meth == "HEAD":
                    head = rgw.head_object(container, obj, actor=actor)
                    self.send_response(200)
                    self.send_header("Content-Length", str(head["size"]))
                    self.send_header("ETag", head["etag"])
                    self.end_headers()
                elif meth == "DELETE":
                    rgw.delete_object(container, obj, actor=actor)
                    self._reply(204)
                else:
                    raise _S3Error(405, "MethodNotAllowed")
        except gw.NoSuchBucket:
            raise _S3Error(404, "NoSuchContainer")
        except gw.NoSuchKey:
            raise _S3Error(404, "NoSuchObject")
        except gw.BucketNotEmpty:
            raise _S3Error(409, "Conflict")
        except gw.AccessDenied as e:
            raise _S3Error(403, "AccessDenied", str(e))

    def _route(self) -> None:
        body = self._read_body()
        try:
            if self.path.startswith("/auth/v1.0"):
                self._swift_auth()
                return
            if self.path.startswith("/swift/v1"):
                self._swift_route(body)
                return
            user = self._authenticate(body)
            parsed = urllib.parse.urlsplit(self.path)
            q = dict(urllib.parse.parse_qsl(parsed.query,
                                            keep_blank_values=True))
            parts = parsed.path.lstrip("/").split("/", 1)
            bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
            key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
            try:
                self._dispatch(bucket, key, q, body, user["uid"])
            except gw.NoSuchBucket:
                raise _S3Error(404, "NoSuchBucket")
            except gw.NoSuchVersion:
                raise _S3Error(404, "NoSuchVersion")
            except gw.NoSuchKey:
                raise _S3Error(404, "NoSuchKey")
            except gw.BucketExists:
                raise _S3Error(409, "BucketAlreadyExists")
            except gw.BucketNotEmpty:
                raise _S3Error(409, "BucketNotEmpty")
            except gw.AccessDenied as e:
                raise _S3Error(403, "AccessDenied", str(e))
            except _acl.InvalidAcl as e:
                raise _S3Error(400, "MalformedACLError", str(e))
        except _S3Error as e:
            self._error(e)
        except Exception as e:  # storage-layer failure
            self._error(_S3Error(500, "InternalError", repr(e)))

    do_GET = do_PUT = do_POST = do_DELETE = do_HEAD = _route

    # -- S3 ops -----------------------------------------------------------
    def _canned(self) -> str:
        return self.headers.get("x-amz-acl", "private") or "private"

    def _dispatch(self, bucket: str, key: str, q: Dict[str, str],
                  body: bytes, actor: str) -> None:
        rgw = self.server.frontend.rgw
        meth = self.command
        if not bucket:
            if meth != "GET":
                raise _S3Error(405, "MethodNotAllowed")
            names = "".join(
                f"<Bucket><Name>{escape(b)}</Name></Bucket>"
                for b in rgw.list_buckets())
            self._reply(200, (
                "<?xml version=\"1.0\"?><ListAllMyBucketsResult>"
                f"<Buckets>{names}</Buckets>"
                "</ListAllMyBucketsResult>").encode())
            return
        if not key:
            self._dispatch_bucket(rgw, bucket, q, body, actor)
            return
        self._dispatch_object(rgw, bucket, key, q, body, actor)

    def _dispatch_bucket(self, rgw, bucket: str, q: Dict[str, str],
                         body: bytes, actor: str) -> None:
        meth = self.command
        # subresources (reference rgw_rest_s3.cc op routing)
        if "acl" in q:
            if meth == "GET":
                self._reply(200, _acl.to_xml(
                    rgw.get_bucket_acl(bucket, actor=actor)).encode())
            elif meth == "PUT":
                if body:
                    policy = _acl.from_xml(body)
                else:
                    owner = rgw.get_bucket_acl(bucket,
                                               actor=actor)["owner"]
                    policy = _acl.canned_acl(owner, self._canned())
                rgw.put_bucket_acl(bucket, policy, actor=actor)
                self._reply(200)
            else:
                raise _S3Error(405, "MethodNotAllowed")
            return
        if "versioning" in q:
            if meth == "GET":
                st = rgw.get_versioning(bucket, actor=actor)
                inner = f"<Status>{st}</Status>" if st else ""
                self._reply(200, (
                    "<?xml version=\"1.0\"?>"
                    f"<VersioningConfiguration>{inner}"
                    "</VersioningConfiguration>").encode())
            elif meth == "PUT":
                import xml.etree.ElementTree as ET

                try:
                    root = ET.fromstring(body)
                    st = ""
                    for c in root.iter():
                        if _acl._local(c.tag) == "Status":
                            st = (c.text or "").strip()
                    rgw.set_versioning(bucket, st, actor=actor)
                except (ValueError, ET.ParseError) as e:
                    # ParseError is a SyntaxError, NOT a ValueError
                    raise _S3Error(400, "IllegalVersioningConfiguration"
                                        "Exception", str(e))
                self._reply(200)
            else:
                raise _S3Error(405, "MethodNotAllowed")
            return
        if "versions" in q:
            if meth != "GET":
                raise _S3Error(405, "MethodNotAllowed")
            rows, truncated, next_key = rgw.list_object_versions(
                bucket, prefix=q.get("prefix", ""),
                key_marker=q.get("key-marker", ""),
                max_keys=int(q.get("max-keys", 1000)), actor=actor,
                with_marker=True)
            xml_rows = []
            for r in rows:
                tag = ("DeleteMarker" if r["IsDeleteMarker"]
                       else "Version")
                inner = (
                    f"<Key>{escape(r['Key'])}</Key>"
                    f"<VersionId>{escape(r['VersionId'])}</VersionId>"
                    f"<IsLatest>{str(r['IsLatest']).lower()}"
                    "</IsLatest>")
                if not r["IsDeleteMarker"]:
                    inner += (f"<Size>{r['Size']}</Size>"
                              f"<ETag>&quot;{r['ETag']}&quot;</ETag>")
                xml_rows.append(f"<{tag}>{inner}</{tag}>")
            # S3 pagination contract: a truncated page names where the
            # next one starts — without these a client (or our own
            # lc_process) resuming from its last visible row can loop
            # or abandon the listing when the page's rows all filtered
            nxt = ""
            if truncated:
                next_vid = rows[-1]["VersionId"] if rows else ""
                nxt = (f"<NextKeyMarker>{escape(next_key)}"
                       "</NextKeyMarker>"
                       f"<NextVersionIdMarker>{escape(next_vid)}"
                       "</NextVersionIdMarker>")
            self._reply(200, (
                "<?xml version=\"1.0\"?><ListVersionsResult>"
                f"<Name>{escape(bucket)}</Name>"
                f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"
                f"{nxt}{''.join(xml_rows)}</ListVersionsResult>").encode())
            return
        if "lifecycle" in q:
            if meth == "GET":
                rules = rgw.get_lifecycle(bucket, actor=actor)
                xr = []
                for r in rules:
                    exp = ""
                    if "expiration_days" in r:
                        exp += (f"<Expiration><Days>"
                                f"{r['expiration_days']}"
                                "</Days></Expiration>")
                    if "noncurrent_days" in r:
                        exp += ("<NoncurrentVersionExpiration>"
                                "<NoncurrentDays>"
                                f"{r['noncurrent_days']}"
                                "</NoncurrentDays>"
                                "</NoncurrentVersionExpiration>")
                    xr.append(
                        f"<Rule><ID>{escape(r['id'])}</ID>"
                        f"<Prefix>{escape(r['prefix'])}</Prefix>"
                        f"<Status>{r['status']}</Status>{exp}</Rule>")
                self._reply(200, (
                    "<?xml version=\"1.0\"?>"
                    "<LifecycleConfiguration>"
                    f"{''.join(xr)}</LifecycleConfiguration>").encode())
            elif meth == "PUT":
                try:
                    rules = _parse_lifecycle_xml(body)
                    rgw.put_lifecycle(bucket, rules, actor=actor)
                except ValueError as e:
                    raise _S3Error(400, "MalformedXML", str(e))
                self._reply(200)
            elif meth == "DELETE":
                rgw.delete_lifecycle(bucket, actor=actor)
                self._reply(204)
            else:
                raise _S3Error(405, "MethodNotAllowed")
            return
        if meth == "PUT":
            rgw.create_bucket(bucket, actor=actor,
                              canned=self._canned())
            self._reply(200)
        elif meth == "DELETE":
            rgw.delete_bucket(bucket, actor=actor)
            self._reply(204)
        elif meth in ("GET", "HEAD"):
            entries, truncated = rgw.list_objects(
                bucket, prefix=q.get("prefix", ""),
                marker=q.get("marker", q.get("start-after", "")),
                max_keys=int(q.get("max-keys", 1000)), actor=actor)
            rows = "".join(
                f"<Contents><Key>{escape(e['Key'])}</Key>"
                f"<Size>{e['Size']}</Size>"
                f"<ETag>&quot;{e['ETag']}&quot;</ETag></Contents>"
                for e in entries)
            self._reply(200, (
                "<?xml version=\"1.0\"?><ListBucketResult>"
                f"<Name>{escape(bucket)}</Name>"
                f"<IsTruncated>{str(truncated).lower()}</IsTruncated>"
                f"{rows}</ListBucketResult>").encode())
        else:
            raise _S3Error(405, "MethodNotAllowed")

    def _dispatch_object(self, rgw, bucket: str, key: str,
                         q: Dict[str, str], body: bytes,
                         actor: str) -> None:
        meth = self.command
        vid = q.get("versionId")
        if "acl" in q:
            if meth == "GET":
                self._reply(200, _acl.to_xml(rgw.get_object_acl(
                    bucket, key, actor=actor)).encode())
            elif meth == "PUT":
                if body:
                    policy = _acl.from_xml(body)
                else:
                    owner = rgw.get_object_acl(bucket, key,
                                               actor=actor)["owner"]
                    bowner = rgw._bucket_meta(bucket).get("owner")
                    policy = _acl.canned_acl(owner, self._canned(),
                                             bucket_owner=bowner)
                rgw.put_object_acl(bucket, key, policy, actor=actor)
                self._reply(200)
            else:
                raise _S3Error(405, "MethodNotAllowed")
            return
        if meth == "PUT":
            if "partNumber" in q and "uploadId" in q:
                etag = rgw.upload_part(bucket, key, q["uploadId"],
                                       int(q["partNumber"]), body,
                                       actor=actor)
                self._reply(200, extra={"ETag": f'"{etag}"'})
            else:
                meta = {k[11:]: v for k, v in self.headers.items()
                        if k.lower().startswith("x-amz-meta-")}
                res = rgw.put_object2(bucket, key, body, metadata=meta,
                                      actor=actor,
                                      canned=self._canned())
                extra = {"ETag": f'"{res["etag"]}"'}
                if "version_id" in res:
                    extra["x-amz-version-id"] = res["version_id"]
                self._reply(200, extra=extra)
        elif meth == "POST":
            if "uploads" in q:
                uid = rgw.create_multipart_upload(bucket, key,
                                                  actor=actor)
                self._reply(200, (
                    "<?xml version=\"1.0\"?>"
                    "<InitiateMultipartUploadResult>"
                    f"<Bucket>{escape(bucket)}</Bucket>"
                    f"<Key>{escape(key)}</Key>"
                    f"<UploadId>{uid}</UploadId>"
                    "</InitiateMultipartUploadResult>").encode())
            elif "uploadId" in q:
                etag = rgw.complete_multipart_upload(bucket, key,
                                                     q["uploadId"],
                                                     actor=actor)
                self._reply(200, (
                    "<?xml version=\"1.0\"?>"
                    "<CompleteMultipartUploadResult>"
                    f"<ETag>&quot;{etag}&quot;</ETag>"
                    "</CompleteMultipartUploadResult>").encode())
            else:
                raise _S3Error(405, "MethodNotAllowed")
        elif meth == "GET":
            data, head = rgw.get_object(bucket, key, version_id=vid,
                                        actor=actor)
            extra = {"ETag": f'"{head["etag"]}"'}
            if head.get("vid"):
                extra["x-amz-version-id"] = head["vid"]
            extra.update({f"x-amz-meta-{k}": v
                          for k, v in head.get("meta", {}).items()})
            self._reply(200, data, ctype="application/octet-stream",
                        extra=extra)
        elif meth == "HEAD":
            head = rgw.head_object(bucket, key, version_id=vid,
                                   actor=actor)
            extra = {"ETag": f'"{head["etag"]}"',
                     "x-amz-object-size": str(head["size"])}
            if head.get("vid"):
                extra["x-amz-version-id"] = head["vid"]
            self.send_response(200)
            self.send_header("Content-Length", str(head["size"]))
            for k, v in extra.items():
                self.send_header(k, v)
            self.end_headers()
        elif meth == "DELETE":
            if "uploadId" in q:
                rgw.abort_multipart_upload(bucket, key, q["uploadId"],
                                           actor=actor)
                self._reply(204)
            else:
                res = rgw.delete_object(bucket, key, version_id=vid,
                                        actor=actor)
                extra = {}
                if res.get("version_id"):
                    extra["x-amz-version-id"] = res["version_id"]
                if res.get("delete_marker"):
                    extra["x-amz-delete-marker"] = "true"
                self._reply(204, extra=extra)
        else:
            raise _S3Error(405, "MethodNotAllowed")


def _parse_lifecycle_xml(body: bytes):
    """Minimal LifecycleConfiguration parser (reference
    rgw_lc_s3.cc): Rule{ID, Prefix/Filter.Prefix, Status,
    Expiration.Days, NoncurrentVersionExpiration.NoncurrentDays}."""
    import xml.etree.ElementTree as ET

    local = _acl._local

    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        raise ValueError(f"malformed lifecycle XML: {e}")
    rules = []
    for rule in root:
        if local(rule.tag) != "Rule":
            continue
        r = {}
        for c in rule:
            t = local(c.tag)
            if t == "ID":
                r["id"] = (c.text or "").strip()
            elif t == "Status":
                r["status"] = (c.text or "").strip()
            elif t == "Prefix":
                r["prefix"] = (c.text or "").strip()
            elif t == "Filter":
                for f in c:
                    if local(f.tag) == "Prefix":
                        r["prefix"] = (f.text or "").strip()
            elif t == "Expiration":
                for f in c:
                    if local(f.tag) == "Days":
                        r["expiration_days"] = int((f.text or "0"))
            elif t == "NoncurrentVersionExpiration":
                for f in c:
                    if local(f.tag) == "NoncurrentDays":
                        r["noncurrent_days"] = int((f.text or "0"))
        rules.append(r)
    if not rules:
        raise ValueError("no Rule elements")
    return rules


class RGWFrontend:
    """The daemon shell: ThreadingHTTPServer bound to host:port, one
    handler thread per connection (the civetweb/beast thread-pool
    role)."""

    def __init__(self, ioctx, host: str = "127.0.0.1", port: int = 0,
                 log=None) -> None:
        self.rgw = gw.RGW(ioctx)
        self.users = RGWUserAdmin(ioctx)
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.frontend = self
        # swift tempauth tokens: token -> (uid, expiry); transient and
        # TTL-bounded like the reference's
        self._swift_tokens: Dict[str, Tuple[str, float]] = {}
        self.swift_token_ttl = 3600.0
        self._thread: Optional[threading.Thread] = None
        self._log = log or (lambda lvl, msg: None)

    @property
    def addr(self) -> Tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "RGWFrontend":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="rgw-frontend",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=10)


# ---------------------------------------------------------------------------
# Client (SDK role, used by tools + tests)
# ---------------------------------------------------------------------------

class SigV4Session:
    """Minimal S3 client speaking real HTTP with SigV4 request signing
    (the boto-shaped half that proves the endpoint is the genuine
    article)."""

    def __init__(self, addr: Tuple[str, int], access_key: str,
                 secret_key: str, region: str = REGION) -> None:
        self.addr = addr
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def request(self, method: str, path: str, body: bytes = b"",
                query: str = "", headers: Optional[Dict] = None):
        import time as _time

        amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
        date = amz_date[:8]
        payload_hash = hashlib.sha256(body).hexdigest()
        host = f"{self.addr[0]}:{self.addr[1]}"
        hdrs = {"host": host, "x-amz-content-sha256": payload_hash,
                "x-amz-date": amz_date}
        for k, v in (headers or {}).items():
            hdrs[k.lower()] = v
        signed = ";".join(sorted(hdrs))
        canonical = _canonical_request(method, path, query, hdrs,
                                       signed, payload_hash)
        scope = f"{date}/{self.region}/{SERVICE}/aws4_request"
        sts = _string_to_sign(amz_date, scope, canonical)
        key = _derive_key(self.secret_key, date, self.region, SERVICE)
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        hdrs["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")
        conn = http.client.HTTPConnection(*self.addr, timeout=30)
        try:
            url = path + (f"?{query}" if query else "")
            conn.request(method, url, body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()
