"""RGW users + S3 signature auth (reference src/rgw/rgw_user.* +
rgw_auth_s3.cc).

Users live in a cluster-wide index object (`rgw.users`, omap via the
same atomic cls path the bucket indexes use): uid -> JSON
{display_name, access_key, secret_key, suspended}.  An access-key
reverse index (`rgw.users.keys`) resolves the key id presented by a
request to its owner — the reference's user metadata + key index
objects collapsed to two.

Auth is AWS Signature V4 (the reference's rgw::auth::s3 v4 flow,
rgw_auth_s3.cc AWSv4ComplMulti/get_v4_* helpers): the signing key is
derived HMAC(HMAC(HMAC(HMAC("AWS4"+secret, date), region), service),
"aws4_request") and the signature is HMAC(signing_key,
string_to_sign).  `authenticate()` takes the parsed elements (key id,
date, region, string-to-sign, signature) — HTTP canonicalization
happens in whatever frontend parses the request, exactly like the
reference splits completers from the signing core.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import secrets
from typing import Dict, List, Optional

from ceph_tpu.client.rados import RadosError

USERS_OID = "rgw.users"
KEYS_OID = "rgw.users.keys"


class AuthFailure(PermissionError):
    pass


class NoSuchUser(KeyError):
    pass


def _sign_v4(secret: str, date: str, region: str, service: str,
             string_to_sign: str) -> str:
    def h(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = h(("AWS4" + secret).encode(), date)
    k = h(k, region)
    k = h(k, service)
    k = h(k, "aws4_request")
    return hmac.new(k, string_to_sign.encode(),
                    hashlib.sha256).hexdigest()


class RGWUserAdmin:
    """User CRUD + key index (radosgw-admin's user subcommands)."""

    def __init__(self, ioctx) -> None:
        self.io = ioctx

    # -- storage -----------------------------------------------------------
    def _get(self, oid: str, key: str) -> Optional[bytes]:
        try:
            got = self.io.omap_get(oid, [key])
        except RadosError:
            return None
        return got.get(key)

    def _put(self, oid: str, kv: Dict[str, bytes]) -> None:
        self.io.omap_set(oid, kv)

    def _mdlog(self, uid: str, op: str) -> None:
        """User mutations feed the zone metadata log (rgw_sync mdlog
        role) so secondary zones replicate the account namespace."""
        from ceph_tpu.rgw.gateway import META_LOG_OID

        try:
            self.io.call(META_LOG_OID, "rgw", "mdlog_add",
                         json.dumps({"section": "user", "name": uid,
                                     "op": op}).encode())
        except RadosError:
            pass

    # -- user CRUD ---------------------------------------------------------
    def user_create(self, uid: str, display_name: str = "") -> Dict:
        if self._get(USERS_OID, uid) is not None:
            raise ValueError(f"user {uid!r} exists")
        access_key = "AK" + secrets.token_hex(8).upper()
        secret_key = secrets.token_urlsafe(30)
        user = {"uid": uid, "display_name": display_name or uid,
                "access_key": access_key, "secret_key": secret_key,
                "suspended": False}
        self._put(USERS_OID, {uid: json.dumps(user).encode()})
        self._put(KEYS_OID, {access_key: uid.encode()})
        self._mdlog(uid, "write")
        return user

    def user_info(self, uid: str) -> Dict:
        raw = self._get(USERS_OID, uid)
        if raw is None:
            raise NoSuchUser(uid)
        return json.loads(raw.decode())

    def user_ls(self) -> List[str]:
        try:
            return sorted(self.io.omap_get(USERS_OID))
        except RadosError:
            return []

    def user_rm(self, uid: str) -> None:
        from ceph_tpu.osd import types as t_
        from ceph_tpu.osd.types import OSDOp

        user = self.user_info(uid)
        self.io.operate(USERS_OID, [OSDOp(t_.OP_OMAP_RM, keys=[uid])])
        self.io.operate(KEYS_OID,
                        [OSDOp(t_.OP_OMAP_RM,
                               keys=[user["access_key"]])])
        self._mdlog(uid, "remove")

    def user_suspend(self, uid: str, suspended: bool = True) -> None:
        user = self.user_info(uid)
        user["suspended"] = suspended
        self._put(USERS_OID, {uid: json.dumps(user).encode()})
        self._mdlog(uid, "write")

    # -- auth --------------------------------------------------------------
    def resolve_key(self, access_key: str) -> Dict:
        uid = self._get(KEYS_OID, access_key)
        if uid is None:
            raise AuthFailure(f"unknown access key {access_key!r}")
        return self.user_info(uid.decode())

    def authenticate(self, access_key: str, date: str, region: str,
                     string_to_sign: str, signature: str,
                     service: str = "s3") -> Dict:
        """Verify an AWS SigV4 signature; returns the user on success
        (rgw::auth::s3 v4 authenticate role)."""
        user = self.resolve_key(access_key)
        if user.get("suspended"):
            raise AuthFailure(f"user {user['uid']!r} suspended")
        want = _sign_v4(user["secret_key"], date, region, service,
                        string_to_sign)
        if not hmac.compare_digest(want, signature):
            raise AuthFailure("signature mismatch")
        return user

    def sign(self, uid: str, date: str, region: str,
             string_to_sign: str, service: str = "s3") -> str:
        """Client-side signing helper (the SDK role, for tests/tools)."""
        user = self.user_info(uid)
        return _sign_v4(user["secret_key"], date, region, service,
                        string_to_sign)
