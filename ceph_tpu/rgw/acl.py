"""S3 access control lists (reference src/rgw/rgw_acl.h +
rgw_acl_s3.cc).

An ACL is a plain dict — {"owner": uid, "grants": [{"grantee": g,
"perm": p}, ...]} — stored inline in bucket metadata and object index
entries (the reference serializes RGWAccessControlPolicy into the
bucket instance / object attrs; same placement, JSON instead of
ceph-encode).

Grantee forms (reference ACLGranteeType):
  - a user id (CanonicalUser)
  - "*"     — the AllUsers group (anonymous included)
  - "auth"  — the AuthenticatedUsers group

Permissions: READ, WRITE, READ_ACP, WRITE_ACP, FULL_CONTROL, with
FULL_CONTROL implying the rest and the owner always holding
FULL_CONTROL (rgw_acl.h RGW_PERM_FULL_CONTROL semantics).

Canned ACLs mirror rgw_acl_s3.cc's (private, public-read,
public-read-write, authenticated-read, bucket-owner-read,
bucket-owner-full-control).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Dict, List, Optional
from xml.sax.saxutils import escape

PERMS = ("READ", "WRITE", "READ_ACP", "WRITE_ACP", "FULL_CONTROL")

ALL_USERS = "*"
AUTH_USERS = "auth"

_GROUP_URI = {
    ALL_USERS: "http://acs.amazonaws.com/groups/global/AllUsers",
    AUTH_USERS: "http://acs.amazonaws.com/groups/global/AuthenticatedUsers",
}
_URI_GROUP = {v: k for k, v in _GROUP_URI.items()}

CANNED = ("private", "public-read", "public-read-write",
          "authenticated-read", "bucket-owner-read",
          "bucket-owner-full-control")


class InvalidAcl(ValueError):
    pass


def canned_acl(owner: str, name: str = "private",
               bucket_owner: Optional[str] = None) -> Dict:
    """Build the policy for a canned ACL header value
    (reference rgw_acl_s3.cc create_canned)."""
    grants: List[Dict] = []
    if name == "private" or not name:
        pass
    elif name == "public-read":
        grants.append({"grantee": ALL_USERS, "perm": "READ"})
    elif name == "public-read-write":
        grants.append({"grantee": ALL_USERS, "perm": "READ"})
        grants.append({"grantee": ALL_USERS, "perm": "WRITE"})
    elif name == "authenticated-read":
        grants.append({"grantee": AUTH_USERS, "perm": "READ"})
    elif name == "bucket-owner-read":
        if bucket_owner and bucket_owner != owner:
            grants.append({"grantee": bucket_owner, "perm": "READ"})
    elif name == "bucket-owner-full-control":
        if bucket_owner and bucket_owner != owner:
            grants.append({"grantee": bucket_owner,
                           "perm": "FULL_CONTROL"})
    else:
        raise InvalidAcl(f"unknown canned ACL {name!r}")
    return {"owner": owner, "grants": grants}


def allows(acl: Optional[Dict], actor: Optional[str], perm: str) -> bool:
    """Does `actor` hold `perm` under `acl`?  The owner holds
    FULL_CONTROL implicitly; actor None means anonymous (matches only
    the AllUsers group)."""
    if perm not in PERMS:
        raise InvalidAcl(f"unknown permission {perm!r}")
    if acl is None:
        return False
    if actor is not None and actor == acl.get("owner"):
        return True
    for g in acl.get("grants", []):
        grantee = g.get("grantee")
        if not (grantee == ALL_USERS
                or (grantee == AUTH_USERS and actor is not None)
                or (actor is not None and grantee == actor)):
            continue
        if g.get("perm") == perm or g.get("perm") == "FULL_CONTROL":
            return True
    return False


def validate(acl: Dict) -> Dict:
    """Normalize + validate a policy dict (PUT ?acl body or API)."""
    if not isinstance(acl, dict) or not acl.get("owner"):
        raise InvalidAcl("policy requires an owner")
    grants = []
    for g in acl.get("grants", []):
        if g.get("perm") not in PERMS:
            raise InvalidAcl(f"unknown permission {g.get('perm')!r}")
        if not g.get("grantee"):
            raise InvalidAcl("grant requires a grantee")
        grants.append({"grantee": g["grantee"], "perm": g["perm"]})
    return {"owner": acl["owner"], "grants": grants}


# ---------------------------------------------------------------------------
# XML (the S3 REST wire form, reference rgw_acl_s3.cc to_xml/parse)
# ---------------------------------------------------------------------------

def to_xml(acl: Dict) -> str:
    rows = []
    for g in acl.get("grants", []):
        grantee = g["grantee"]
        if grantee in _GROUP_URI:
            gx = ("<Grantee xmlns:xsi=\"http://www.w3.org/2001/"
                  "XMLSchema-instance\" xsi:type=\"Group\">"
                  f"<URI>{_GROUP_URI[grantee]}</URI></Grantee>")
        else:
            gx = ("<Grantee xmlns:xsi=\"http://www.w3.org/2001/"
                  "XMLSchema-instance\" xsi:type=\"CanonicalUser\">"
                  f"<ID>{escape(grantee)}</ID></Grantee>")
        rows.append(f"<Grant>{gx}<Permission>{g['perm']}</Permission>"
                    "</Grant>")
    return ("<?xml version=\"1.0\"?><AccessControlPolicy>"
            f"<Owner><ID>{escape(acl['owner'])}</ID></Owner>"
            f"<AccessControlList>{''.join(rows)}</AccessControlList>"
            "</AccessControlPolicy>")


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def from_xml(body: bytes) -> Dict:
    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        raise InvalidAcl(f"malformed ACL XML: {e}")
    if _local(root.tag) != "AccessControlPolicy":
        raise InvalidAcl("expected AccessControlPolicy")
    owner = None
    grants: List[Dict] = []
    for child in root:
        if _local(child.tag) == "Owner":
            for sub in child:
                if _local(sub.tag) == "ID":
                    owner = (sub.text or "").strip()
        elif _local(child.tag) == "AccessControlList":
            for grant in child:
                if _local(grant.tag) != "Grant":
                    continue
                grantee = None
                perm = None
                for sub in grant:
                    t = _local(sub.tag)
                    if t == "Grantee":
                        for gsub in sub:
                            gt = _local(gsub.tag)
                            if gt == "ID":
                                grantee = (gsub.text or "").strip()
                            elif gt == "URI":
                                uri = (gsub.text or "").strip()
                                if uri not in _URI_GROUP:
                                    raise InvalidAcl(
                                        f"unknown group URI {uri!r}")
                                grantee = _URI_GROUP[uri]
                    elif t == "Permission":
                        perm = (sub.text or "").strip()
                if grantee is None or perm is None:
                    raise InvalidAcl("grant missing grantee/permission")
                grants.append({"grantee": grantee, "perm": perm})
    if not owner:
        raise InvalidAcl("policy missing owner")
    return validate({"owner": owner, "grants": grants})
