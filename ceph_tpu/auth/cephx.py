"""Cephx-role ticket authentication.

Reference: src/auth/cephx/CephxProtocol.h — a Kerberos-like scheme:
the mon (auth server) shares a secret with every entity (keyring) and
with the services (the rotating service key); a client proves identity
to the mon via challenge-response, receives a SESSION KEY sealed under
its own secret plus a TICKET (name + caps + the same session key)
sealed under the service secret, and then authenticates every daemon
session by presenting the ticket + an HMAC authorizer.  Daemons verify
with only the service secret — the mon is not on the data path.

Crypto is stdlib-only: seal() is encrypt-then-MAC with an
HMAC-SHA256 keystream (CTR-style) and an HMAC tag; proofs and
authorizers are plain HMACs.  (The reference uses AES; the protocol
shape — challenges, tickets, authorizers, expiry — is what's mirrored
here.)
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import struct
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from ceph_tpu.auth.keyring import Keyring, generate_secret
from ceph_tpu.core.encoding import Decoder, Encoder

TICKET_VALIDITY = 3600.0  # seconds (reference auth_service_ticket_ttl)


class AuthError(Exception):
    pass


# -- sealed boxes (encrypt-then-MAC over an HMAC keystream) ---------------

def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    counter = 0
    while len(out) < n:
        out += hmac.new(key, nonce + struct.pack("<Q", counter),
                        hashlib.sha256).digest()
        counter += 1
    return bytes(out[:n])


def seal(key: bytes, plaintext: bytes) -> bytes:
    nonce = secrets.token_bytes(16)
    ks = _keystream(key, nonce, len(plaintext))
    ct = bytes(a ^ b for a, b in zip(plaintext, ks))
    mac = hmac.new(key, b"seal" + nonce + ct, hashlib.sha256).digest()
    return nonce + mac + ct


def unseal(key: bytes, blob: bytes) -> bytes:
    if len(blob) < 48:
        raise AuthError("sealed blob too short")
    nonce, mac, ct = blob[:16], blob[16:48], blob[48:]
    want = hmac.new(key, b"seal" + nonce + ct, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want):
        raise AuthError("sealed blob MAC mismatch")
    ks = _keystream(key, nonce, len(ct))
    return bytes(a ^ b for a, b in zip(ct, ks))


# -- tickets ---------------------------------------------------------------

@dataclass
class Ticket:
    name: str
    caps: str
    session_key: bytes
    expires: float

    def encode(self) -> bytes:
        e = Encoder()
        e.start(1, 1)
        e.string(self.name).string(self.caps)
        e.blob(self.session_key).f64(self.expires)
        e.finish()
        return e.bytes()

    @classmethod
    def decode(cls, data: bytes) -> "Ticket":
        d = Decoder(data)
        d.start(1)
        t = cls(name=d.string(), caps=d.string(),
                session_key=d.blob(), expires=d.f64())
        d.end()
        return t


class CephxServer:
    """The mon-side auth service (reference CephxServiceHandler)."""

    def __init__(self, keyring: Keyring,
                 service_secret: Optional[bytes] = None) -> None:
        self.keyring = keyring
        self.service_secret = (service_secret
                               or keyring.get("service")
                               or generate_secret())
        self._challenges: Dict[str, Tuple[bytes, float]] = {}

    def get_challenge(self, name: str) -> bytes:
        ch = secrets.token_bytes(16)
        self._challenges[name] = (ch, time.time() + 60.0)
        return ch

    def handle_request(self, name: str, client_challenge: bytes,
                       proof: bytes, caps: str = "allow *",
                       now: Optional[float] = None) -> Tuple[bytes, bytes]:
        """Verify the proof, return (sealed_for_client, ticket_blob).

        proof = HMAC(entity_secret, server_challenge || client_challenge)
        sealed_for_client = seal(entity_secret, session_key || expires)
        ticket_blob = seal(service_secret, Ticket)
        """
        now = time.time() if now is None else now
        secret = self.keyring.get(name)
        if secret is None:
            raise AuthError(f"unknown entity {name!r}")
        got = self._challenges.pop(name, None)
        if got is None or got[1] < now:
            raise AuthError("no live challenge; restart the handshake")
        server_challenge = got[0]
        want = hmac.new(secret, server_challenge + client_challenge,
                        hashlib.sha256).digest()
        if not hmac.compare_digest(proof, want):
            raise AuthError(f"bad proof for {name!r}")
        session_key = generate_secret()
        expires = now + TICKET_VALIDITY
        ticket = Ticket(name, caps, session_key, expires)
        e = Encoder()
        e.blob(session_key).f64(expires)
        sealed_client = seal(secret, e.bytes())
        ticket_blob = seal(self.service_secret, ticket.encode())
        return sealed_client, ticket_blob

    def mint_authorizer(self, name: str, caps: str = "allow *",
                        target: str = "") -> bytes:
        """Self-issued authorizer for the auth service itself — the mon
        holds the service secret, so its dial-backs (map pushes) carry
        a ticket daemons can verify like any other."""
        session_key = generate_secret()
        ticket = Ticket(name, caps, session_key,
                        time.time() + TICKET_VALIDITY)
        blob = seal(self.service_secret, ticket.encode())
        return build_authorizer_blob(blob, session_key, target)


class CephxClient:
    """Client half: proves identity, keeps the ticket, builds
    per-connection authorizers (reference CephxClientHandler)."""

    def __init__(self, name: str, secret: bytes) -> None:
        self.name = name
        self.secret = secret
        self.session_key: Optional[bytes] = None
        self.ticket_blob: Optional[bytes] = None
        self.expires = 0.0

    def make_proof(self, server_challenge: bytes,
                   client_challenge: bytes) -> bytes:
        return hmac.new(self.secret, server_challenge + client_challenge,
                        hashlib.sha256).digest()

    def accept_reply(self, sealed_client: bytes, ticket_blob: bytes) -> None:
        d = Decoder(unseal(self.secret, sealed_client))
        self.session_key = d.blob()
        self.expires = d.f64()
        self.ticket_blob = ticket_blob

    @property
    def authenticated(self) -> bool:
        return (self.session_key is not None
                and time.time() < self.expires)

    def build_authorizer(self, target: str = "") -> bytes:
        """ticket + HMAC(session_key, stamp || target) — presented per
        session; `target` (the dialed daemon's address) binds the blob
        to one destination."""
        if not self.authenticated:
            raise AuthError("no live ticket")
        return build_authorizer_blob(self.ticket_blob, self.session_key,
                                     target)


def _authorizer_mac(session_key: bytes, stamp: float,
                    target: str, nonce: bytes) -> bytes:
    # every variable-length field is LENGTH-PREFIXED inside the MAC:
    # without framing, bytes could be moved between target and nonce
    # (e.g. re-encode with target="" and nonce=old_target+old_nonce) to
    # strip the destination binding while keeping the MAC valid
    t = target.encode()
    return hmac.new(
        session_key,
        b"authorizer" + struct.pack("<d", stamp)
        + struct.pack("<I", len(t)) + t
        + struct.pack("<I", len(nonce)) + nonce,
        hashlib.sha256).digest()


def build_authorizer_blob(ticket_blob: bytes, session_key: bytes,
                          target: str = "") -> bytes:
    """The MAC covers (stamp, target, a fresh nonce): target binding
    stops cross-daemon replay, the nonce + the verifier's seen-cache
    stop same-daemon replay within the clock-skew window (the
    reference's CVE-2018-1128 challenge fix, collapsed into the
    one-shot announce shape)."""
    e = Encoder()
    e.start(2, 1)
    stamp = time.time()
    nonce = secrets.token_bytes(16)
    e.blob(ticket_blob).f64(stamp)
    e.blob(_authorizer_mac(session_key, stamp, target, nonce))
    e.string(target)
    e.blob(nonce)
    e.finish()
    return e.bytes()


def verify_authorizer(service_secret: bytes, blob: bytes,
                      now: Optional[float] = None,
                      max_skew: float = 300.0,
                      expect_target: str = "",
                      seen: Optional[Dict[bytes, float]] = None) -> Ticket:
    """Daemon-side check: unseal the ticket with the service secret,
    validate expiry, target binding, the session-key HMAC and — when a
    `seen` cache is provided — reject replays of a previously-used
    authorizer (reference cephx_verify_authorizer + the CVE-2018-1128
    challenge)."""
    now = time.time() if now is None else now
    d = Decoder(blob)
    v = d.start(2)
    ticket_blob = d.blob()
    stamp = d.f64()
    mac = d.blob()
    target = d.string() if v >= 2 else ""
    nonce = d.blob() if v >= 2 else b""
    d.end()
    ticket = Ticket.decode(unseal(service_secret, ticket_blob))
    if ticket.expires < now:
        raise AuthError(f"ticket for {ticket.name!r} expired")
    if abs(now - stamp) > max_skew:
        raise AuthError("authorizer stamp outside clock skew window")
    if expect_target and v >= 2 and target != expect_target:
        # an EMPTY target on a v2 blob is also a mismatch: accepting it
        # would let a stripped binding through
        raise AuthError(
            f"authorizer bound to {target!r}, not {expect_target!r}")
    want = _authorizer_mac(ticket.session_key, stamp, target, nonce)
    if not hmac.compare_digest(mac, want):
        raise AuthError(f"authorizer MAC mismatch for {ticket.name!r}")
    if seen is not None:
        for k in [k for k, exp in seen.items() if exp < now]:
            del seen[k]
        if mac in seen:
            raise AuthError("authorizer replayed")
        # the entry must outlive the blob's validity, which ends at
        # stamp + max_skew (a fast client clock extends it past
        # now + max_skew)
        seen[mac] = stamp + max_skew
    return ticket
