"""Keyring — named shared secrets (reference: src/auth/KeyRing.cc,
the [entity] / key = ... files ceph tooling manages)."""

from __future__ import annotations

import base64
import os
import secrets
from typing import Dict, Optional


def generate_secret() -> bytes:
    return secrets.token_bytes(32)


class Keyring:
    def __init__(self) -> None:
        self._keys: Dict[str, bytes] = {}

    def add(self, name: str, secret: Optional[bytes] = None) -> bytes:
        key = secret if secret is not None else generate_secret()
        self._keys[name] = key
        return key

    def get(self, name: str) -> Optional[bytes]:
        return self._keys.get(name)

    def names(self):
        return sorted(self._keys)

    # -- file format (parity with the reference's keyring files) ---------
    def dump(self) -> str:
        out = []
        for name in self.names():
            b64 = base64.b64encode(self._keys[name]).decode()
            out.append(f"[{name}]\n\tkey = {b64}\n")
        return "".join(out)

    @classmethod
    def loads(cls, text: str) -> "Keyring":
        kr = cls()
        name = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("[") and line.endswith("]"):
                name = line[1:-1]
            elif line.startswith("key") and "=" in line and name:
                kr._keys[name] = base64.b64decode(
                    line.split("=", 1)[1].strip())
        return kr

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.dump())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "Keyring":
        with open(path) as f:
            return cls.loads(f.read())
