"""Authentication: cephx-role tickets over shared-secret keyrings
(reference: src/auth/, src/auth/cephx/)."""

from ceph_tpu.auth.cephx import (
    AuthError,
    CephxClient,
    CephxServer,
    Ticket,
    seal,
    unseal,
    verify_authorizer,
)
from ceph_tpu.auth.keyring import Keyring, generate_secret

__all__ = ["AuthError", "CephxClient", "CephxServer", "Ticket",
           "Keyring", "generate_secret", "seal", "unseal",
           "verify_authorizer"]
