"""no-unwatched-jit: every jit/pallas entry point goes through devwatch.

PR 10's device-runtime watcher only sees what flows through its
``instrumented_jit`` / ``instrumented_pallas_call`` wrappers
(``ceph_tpu/tpu/devwatch.py``).  One convenient ``jax.jit(...)``
anywhere else re-opens the observability hole the watcher closed:
that kernel's compiles are invisible to the ``osd.N.xla`` perf set,
the recompile-storm detector, the steady-state guard, the op-level
``compile_wait`` blame, and the crash flight recorder — the exact
blindness that cost the PR 3 CRUSH-sweep recompile hunt and PR 9's
discarded warmup trial.

Flagged anywhere in ``ceph_tpu/`` outside devwatch itself:

- any ``jax.jit`` attribute reference (call, decorator,
  ``functools.partial(jax.jit, ...)`` argument, alias assignment —
  the ATTRIBUTE is the violation, so aliasing cannot hide it);
- any ``*.pallas_call`` attribute reference (``pl.pallas_call``,
  ``pltpu.pallas_call``, fully-qualified spellings);
- ``from jax import jit`` / ``from jax.experimental.pallas import
  pallas_call`` style imports of the raw entry points.

Never baselineable (the failpoint-name-registry / span-discipline
shape): ``--write-baseline`` refuses to record these, so a direct
jit can never ship as accepted debt.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    Check, NEVER_BASELINE_PREFIXES, SourceFile, Violation, dotted,
    enclosing_scope,
)

# the raw entry points, by dotted attribute spelling
_JIT_ATTRS = {"jax.jit"}
_PALLAS_TAIL = "pallas_call"

# the one module allowed to touch the raw entry points
_EXEMPT = ("ceph_tpu/tpu/devwatch.py",)


class NoUnwatchedJit(Check):
    name = "no-unwatched-jit"
    description = ("direct jax.jit / pl.pallas_call outside "
                   "tpu/devwatch.py: compiles invisible to the "
                   "device-runtime watcher")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            if f.rel in _EXEMPT:
                continue
            for node in ast.walk(f.tree):
                detail = None
                if isinstance(node, ast.Attribute):
                    dn = dotted(node)
                    if dn in _JIT_ATTRS:
                        detail = dn
                    elif node.attr == _PALLAS_TAIL and dn:
                        detail = dn
                elif isinstance(node, ast.ImportFrom):
                    mod = node.module or ""
                    if mod == "jax" or mod.startswith("jax."):
                        for alias in node.names:
                            if alias.name in ("jit", _PALLAS_TAIL):
                                detail = f"from {mod} import {alias.name}"
                                break
                if detail is None:
                    continue
                out.append(Violation(
                    check=self.name, path=f.rel, line=node.lineno,
                    scope=enclosing_scope(f.tree, node.lineno),
                    detail=detail,
                    message=(
                        f"{detail}: raw jit/pallas entry point outside "
                        "tpu/devwatch.py — this kernel's compiles are "
                        "invisible to the device watcher (osd.N.xla, "
                        "storm detection, compile_wait blame, crash "
                        "flight recorder); use devwatch."
                        "instrumented_jit / instrumented_pallas_call "
                        "with a family= tag"),
                ))
        return out


# a direct jit is never accepted debt, anywhere in the tree
NEVER_BASELINE_PREFIXES.append((NoUnwatchedJit.name, "ceph_tpu/"))
