"""no-d2h-on-hot-path: the device-resident payload contract, enforced.

PR 6's staging pipeline keeps payloads device-resident from messenger
receive through encode/crc to store apply, with only metadata crossing
back to host.  The contract dies by a thousand cuts: one convenient
``np.asarray(...)`` / ``bytes(...)`` on a device buffer inside the
messenger fast-dispatch path or the StripeBatchQueue worker quietly
reintroduces the tunnel tax the whole refactor removed (the BENCH_r05
shape: 276 GB/s on-device, ~0 end-to-end).

Since PR 18 this is the (loop ∪ device_worker, may-d2h) cell of the
shared thread-role engine: roots (every ``async def``, fast-dispatch
``ms_dispatch``, loop-scheduled callbacks, ``StripeBatchQueue._worker``
and future callbacks that resolve on it) come from
``analysis/threadmodel.py``; this module owns only the host-
materialization primitives: ``np.asarray`` / ``np.array`` /
``jnp.asarray``, ``.tolist()``, ``.tobytes()``, and ``bytes(...)``
applied to a value.

Accepted legacy debt lives in the baseline like any other check —
EXCEPT in the new pipeline modules themselves (``tpu/staging.py``,
``ops/crc32c_device.py``): violations there are never baselineable
(``--write-baseline`` refuses to record them), so the pipeline's own
code hard-errors the build.  Sanctioned fetches (the engine's own
batched d2h, 4-byte metadata digests) annotate the line with
``# cephlint: disable=no-d2h-on-hot-path — why``.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ceph_tpu.analysis.checks.blocking import NoBlockingOnLoop
from ceph_tpu.analysis.framework import NEVER_BASELINE_PREFIXES, call_name
from ceph_tpu.analysis.threadmodel import (
    ROLE_DEVICE, ROLE_LOOP, FuncInfo, body_walk,
)

# host-materialization call names (module-qualified numpy/jax spellings
# the repo actually uses)
_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray",
                  "numpy.array", "jnp.asarray", "jnp.array"}
_MATERIALIZER_METHODS = {"tolist", "tobytes"}

# the new pipeline modules hard-error: debt here is never accepted
_HARD_PATHS = ("ceph_tpu/tpu/staging.py", "ceph_tpu/ops/crc32c_device.py")


class NoD2HOnHotPath(NoBlockingOnLoop):
    name = "no-d2h-on-hot-path"
    description = ("host materialization of device buffers reachable "
                   "from the messenger fast-dispatch or "
                   "StripeBatchQueue._worker call graphs")
    scopes = ("ceph_tpu",)

    roles = (ROLE_LOOP, ROLE_DEVICE)

    def _message(self, prim: str, chain: List[str]) -> str:
        return (f"{prim} materializes a device buffer on host: "
                f"reachable via {' -> '.join(chain)} (device-resident "
                "payload contract: only metadata crosses to host on "
                "the hot path — annotate sanctioned metadata fetches "
                "with a disable + rationale)")

    # -- primitives: host materializations --------------------------------
    def _primitives(self, fn: FuncInfo) -> List[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        for node in body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            base = cn.split(".")[-1]
            if cn in _MATERIALIZERS:
                out.append((node.lineno, f"{cn}()"))
            elif cn == "bytes" and node.args:
                out.append((node.lineno, "bytes()"))
            elif "." in cn and base in _MATERIALIZER_METHODS:
                out.append((node.lineno, f"{cn}()"))
        return out


# register the hard-error scope with the baseline writer: pipeline-
# module debt for this check can never be accepted silently
for _p in _HARD_PATHS:
    NEVER_BASELINE_PREFIXES.append((NoD2HOnHotPath.name, _p))
