"""no-d2h-on-hot-path: the device-resident payload contract, enforced.

PR 6's staging pipeline keeps payloads device-resident from messenger
receive through encode/crc to store apply, with only metadata crossing
back to host.  The contract dies by a thousand cuts: one convenient
``np.asarray(...)`` / ``bytes(...)`` on a device buffer inside the
messenger fast-dispatch path or the StripeBatchQueue worker quietly
reintroduces the tunnel tax the whole refactor removed (the BENCH_r05
shape: 276 GB/s on-device, ~0 end-to-end).

This check reuses the PR-3 fast-dispatch call graph (every ``async
def``, fast-dispatching ``ms_dispatch``, loop-scheduled callbacks) and
adds ``StripeBatchQueue._worker`` as a root, then flags host-
materialization primitives reachable from them: ``np.asarray`` /
``np.array`` / ``jnp.asarray``, ``.tolist()``, ``.tobytes()``, and
``bytes(...)`` applied to a value.

Accepted legacy debt lives in the baseline like any other check —
EXCEPT in the new pipeline modules themselves (``tpu/staging.py``,
``ops/crc32c_device.py``): violations there are never baselineable
(``--write-baseline`` refuses to record them), so the pipeline's own
code hard-errors the build.  Sanctioned fetches (the engine's own
batched d2h, 4-byte metadata digests) annotate the line with
``# cephlint: disable=no-d2h-on-hot-path — why``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ceph_tpu.analysis.checks.blocking import (
    NoBlockingOnLoop, _Func, _Module, _body_walk,
)
from ceph_tpu.analysis.framework import NEVER_BASELINE_PREFIXES, call_name

# host-materialization call names (module-qualified numpy/jax spellings
# the repo actually uses)
_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray",
                  "numpy.array", "jnp.asarray", "jnp.array"}
_MATERIALIZER_METHODS = {"tolist", "tobytes"}

# the new pipeline modules hard-error: debt here is never accepted
_HARD_PATHS = ("ceph_tpu/tpu/staging.py", "ceph_tpu/ops/crc32c_device.py")


class NoD2HOnHotPath(NoBlockingOnLoop):
    name = "no-d2h-on-hot-path"
    description = ("host materialization of device buffers reachable "
                   "from the messenger fast-dispatch or "
                   "StripeBatchQueue._worker call graphs")
    scopes = ("ceph_tpu",)

    # -- roots: fast-dispatch graph + the queue's device worker ----------
    def _find_roots(self, mods: Dict[str, _Module],
                    index: Dict[str, _Func]) -> Set[str]:
        roots = super()._find_roots(mods, index)
        worker = "ceph_tpu.tpu.queue:StripeBatchQueue._worker"
        if worker in index:
            roots.add(worker)
        return roots

    def _message(self, prim: str, chain: List[str]) -> str:
        return (f"{prim} materializes a device buffer on host: "
                f"reachable via {' -> '.join(chain)} (device-resident "
                "payload contract: only metadata crosses to host on "
                "the hot path — annotate sanctioned metadata fetches "
                "with a disable + rationale)")

    # -- primitives: host materializations --------------------------------
    def _primitives(self, fn: _Func) -> List[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        for node in _body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            base = cn.split(".")[-1]
            if cn in _MATERIALIZERS:
                out.append((node.lineno, f"{cn}()"))
            elif cn == "bytes" and node.args:
                out.append((node.lineno, "bytes()"))
            elif "." in cn and base in _MATERIALIZER_METHODS:
                out.append((node.lineno, f"{cn}()"))
        return out


# register the hard-error scope with the baseline writer: pipeline-
# module debt for this check can never be accepted silently
for _p in _HARD_PATHS:
    NEVER_BASELINE_PREFIXES.append((NoD2HOnHotPath.name, _p))
