"""shape-bucket-discipline: every kernel family declares its buckets,
every batch dispatch pads through the covering helper.

PR 17's shape-bucket ABI (``ceph_tpu/tpu/shapebucket.py``) makes the
compile surface of every devwatch kernel family FINITE: a family
declares its bucket grammar, dispatch sites pad to the covering
bucket, and any compile outside the declared set is a ``rogue`` —
counted on ``osd.N.xla``, WARN'd by the storm detector, and asserted
zero by the steady-state guard.  That contract only holds if

1. every ``instrumented_jit`` / ``instrumented_pallas_call``
   registration names a family that shapebucket DECLARES — a new
   family registered without a :class:`BucketSpec` makes every one of
   its compiles a false rogue (or forces the guard off), and

2. the batch coalescer (``ceph_tpu/tpu/queue.py``) never dispatches a
   batch at its raw width: a dispatch call in a function that never
   references ``covering`` is the PR 8 unpadded bypass reborn — one
   odd-width batch = one fresh XLA compile on the op path.

Never baselineable: an undeclared family or an unpadded dispatch can
never ship as accepted debt (the no-unwatched-jit shape).
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    Check, NEVER_BASELINE_PREFIXES, SourceFile, Violation, dotted,
    enclosing_scope,
)

# registration entry points whose family= tag must be declared
_REG_TAILS = ("instrumented_jit", "instrumented_pallas_call")

# files where every device dispatch must flow through covering()
_PAD_REQUIRED = ("ceph_tpu/tpu/queue.py",)

# the dispatch calls that hand a batch to a kernel family (PR 19 adds
# the clay array-codec kernels: their coupled-layer matmuls run in the
# gf256_clay family and are just as compile-sensitive to raw widths)
_DISPATCH_TAILS = ("encode_array", "gf_matmul_bytes", "crc32c_rows",
                   "encode_scatter", "recovery_gather",
                   "repair_planes", "decode_planes")


def _call_tail(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _family_literal(node: ast.Call):
    """The family= string literal of a registration call (also the
    functools.partial(instrumented_jit, family=...) spelling), or
    None when absent / not a literal."""
    for kw in node.keywords:
        if kw.arg == "family" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _is_registration(node: ast.Call) -> bool:
    tail = _call_tail(node)
    if tail in _REG_TAILS:
        return True
    # functools.partial(instrumented_jit, family="...") decorators
    if tail == "partial" and node.args:
        a0 = node.args[0]
        name = (a0.attr if isinstance(a0, ast.Attribute)
                else a0.id if isinstance(a0, ast.Name) else "")
        return name in _REG_TAILS
    return False


class ShapeBucketDiscipline(Check):
    name = "shape-bucket-discipline"
    description = ("kernel family registered without a declared "
                   "BucketSpec, or a batch dispatch in the coalescer "
                   "bypassing the covering() pad helper")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        from ceph_tpu.tpu import shapebucket

        declared = set(shapebucket.declared_families())
        out: List[Violation] = []
        for f in files:
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                if not _is_registration(node):
                    continue
                fam = _family_literal(node)
                if fam is None or fam in declared:
                    continue
                out.append(Violation(
                    check=self.name, path=f.rel, line=node.lineno,
                    scope=enclosing_scope(f.tree, node.lineno),
                    detail=f"undeclared-family:{fam}",
                    message=(
                        f"family {fam!r} registered without a "
                        "BucketSpec in tpu/shapebucket.py — every "
                        "compile it triggers is a rogue to the "
                        "steady-state guard; declare() its bucket "
                        "grammar (small_max/odd_max/ceiling/"
                        "free_args) next to the other families"),
                ))
            if f.rel in _PAD_REQUIRED:
                out.extend(self._unpadded_dispatches(f))
        return out

    def _unpadded_dispatches(self, f: SourceFile) -> List[Violation]:
        out: List[Violation] = []
        for fn in ast.walk(f.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            # does this function route widths through covering()?
            pads = any(
                (isinstance(n, ast.Attribute) and n.attr == "covering")
                or (isinstance(n, ast.Name) and n.id == "covering")
                for n in ast.walk(fn))
            if pads:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tail = _call_tail(node)
                if tail not in _DISPATCH_TAILS:
                    continue
                out.append(Violation(
                    check=self.name, path=f.rel, line=node.lineno,
                    scope=enclosing_scope(f.tree, node.lineno),
                    detail=f"unpadded-dispatch:{tail}",
                    message=(
                        f"{tail}() dispatched from {fn.name}() "
                        "without a shapebucket.covering() pad — an "
                        "arbitrary batch width here is a fresh XLA "
                        "compile per distinct size (the PR 8 "
                        "compile-contaminated queue wait); pad to "
                        "the covering bucket and slice the result"),
                ))
        return out


# an undeclared family / unpadded dispatch is never accepted debt
NEVER_BASELINE_PREFIXES.append((ShapeBucketDiscipline.name, "ceph_tpu/"))
