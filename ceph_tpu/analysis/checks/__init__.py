"""Check registry: one module per bug class this repo has shipped."""

from ceph_tpu.analysis.checks.blocking import NoBlockingOnLoop
from ceph_tpu.analysis.checks.codec import CodecSymmetry
from ceph_tpu.analysis.checks.d2h import NoD2HOnHotPath
from ceph_tpu.analysis.checks.failpoint_names import FailpointNameRegistry
from ceph_tpu.analysis.checks.jax_purity import JaxPurity
from ceph_tpu.analysis.checks.lane_capability import LaneCapability
from ceph_tpu.analysis.checks.lock_cycle import LockOrderCycle
from ceph_tpu.analysis.checks.locks import NamedLocks
from ceph_tpu.analysis.checks.shared_state import UnguardedSharedState
from ceph_tpu.analysis.checks.qos_classes import QosClassRegistry
from ceph_tpu.analysis.checks.shape_bucket import ShapeBucketDiscipline
from ceph_tpu.analysis.checks.silent_except import SilentExcept
from ceph_tpu.analysis.checks.sleep_poll import NoSleepPoll
from ceph_tpu.analysis.checks.span_discipline import SpanDiscipline
from ceph_tpu.analysis.checks.unverified_read import NoUnverifiedRead
from ceph_tpu.analysis.checks.unwatched_jit import NoUnwatchedJit

ALL_CHECKS = (
    NoBlockingOnLoop(),
    NamedLocks(),
    CodecSymmetry(),
    NoSleepPoll(),
    SilentExcept(),
    JaxPurity(),
    NoD2HOnHotPath(),
    FailpointNameRegistry(),
    QosClassRegistry(),
    SpanDiscipline(),
    NoUnwatchedJit(),
    NoUnverifiedRead(),
    ShapeBucketDiscipline(),
    LaneCapability(),
    LockOrderCycle(),
    UnguardedSharedState(),
)

CHECKS_BY_NAME = {c.name: c for c in ALL_CHECKS}
