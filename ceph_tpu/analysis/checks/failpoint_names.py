"""failpoint-name-registry: failpoint call sites must use names
declared in `ceph_tpu.core.failpoint.POINTS`.

A failpoint is a CONTRACT between an instrumented site and the test /
operator arming it by name; a typo'd site is a dead injection point
that silently never fires (the schedule "passes" by testing nothing),
and a typo'd arming raises at arm() time only because the same table
gates it.  This check closes the remaining hole — the call sites.
Also flagged: non-literal names (a dynamic name evades both the
registry and every grep), and literal names in arm()/enabled() calls,
for the same reason.

Baseline-free from day one: failpoints ship with this PR, so there is
no accepted debt — every violation is a hard error and
``--write-baseline`` refuses to record them.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    NEVER_BASELINE_PREFIXES, Check, SourceFile, Violation, call_name,
    enclosing_scope,
)

# call names whose FIRST string argument is a failpoint name
_NAME_CALLS = ("failpoint", "enabled", "arm", "disarm", "hits", "fired")


def _is_fp_call(node: ast.Call) -> str:
    """Returns the bare function name when `node` is a failpoint-
    registry call (failpoint(...), fp.failpoint(...), fpt.arm(...)),
    else ''."""
    name = call_name(node)
    base = name.rsplit(".", 1)[-1]
    if base not in _NAME_CALLS:
        return ""
    if base == "failpoint":
        # failpoint(...) or <alias>.failpoint(...) — the module is
        # conventionally imported as fp/fpt/failpoint
        head = name.rsplit(".", 1)[0] if "." in name else ""
        if head in ("", "fp", "fpt", "failpoint"):
            return base
        return ""
    # the other names are common words: require the fp/fpt module
    # alias so Event.wait-style calls don't false-positive
    if "." not in name:
        return ""
    head = name.rsplit(".", 1)[0]
    return base if head in ("fp", "fpt", "failpoint") else ""


class FailpointNameRegistry(Check):
    name = "failpoint-name-registry"
    description = ("failpoint()/arm() names must be declared in "
                   "failpoint.POINTS (typo = dead injection point)")
    scopes = ("ceph_tpu", "tools")

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        from ceph_tpu.core.failpoint import POINTS

        out: List[Violation] = []
        for f in files:
            if f.rel.endswith("core/failpoint.py"):
                continue  # the registry itself
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                base = _is_fp_call(node)
                if not base or not node.args:
                    continue
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    out.append(Violation(
                        check=self.name, path=f.rel, line=node.lineno,
                        scope=enclosing_scope(f.tree, node.lineno),
                        detail=f"{base}(<dynamic>)",
                        message=(f"{base}() name must be a string "
                                 "literal — a dynamic name evades the "
                                 "registry and every grep"),
                    ))
                    continue
                if arg.value not in POINTS:
                    out.append(Violation(
                        check=self.name, path=f.rel, line=node.lineno,
                        scope=enclosing_scope(f.tree, node.lineno),
                        detail=f"{base}({arg.value!r})",
                        message=(f"failpoint name {arg.value!r} is not "
                                 "declared in failpoint.POINTS — a "
                                 "typo'd site never fires"),
                    ))
        return out


# failpoint plumbing must stay correct-by-construction: refuse to
# baseline ANY violation of this check, anywhere
NEVER_BASELINE_PREFIXES.append((FailpointNameRegistry.name, ""))
