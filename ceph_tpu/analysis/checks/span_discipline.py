"""span-discipline: spans finish on every path; stage names are declared.

Two contracts, both rooted in PR 8's observability layer:

1. **Every ``start_span`` reaches ``finish()``.**  A span that never
   finishes never archives — the trace silently loses a subtree, and
   nothing fails.  Accepted shapes: the span is a ``with`` context
   manager, or its assignment target (name or dotted attribute) has a
   matching ``.finish()`` call in the enclosing function (nested
   closures count — commit callbacks finish their op's span), with a
   module-wide fallback for handles finished by a sibling method
   (``op.span`` set in submit, finished in the reply dispatcher).
   A ``start_span`` that is neither assigned nor entered is always a
   violation — nothing can ever finish it.

2. **Stage names come from the registry.**  Timeline/stage names used
   with ``mark_event`` / ``PG._op_stage`` must be string literals
   declared in ``tracing.STAGES`` (a typo'd stage is a dead timeline
   row that never feeds its latency histogram), and a ``annotate``
   call whose argument is a PLAIN string literal must name a declared
   stage too — free-form detail annotations use f-strings/variables,
   which are exempt.

Never baselineable: the observability layer ships with this check, so
there is no accepted debt — like the failpoint-name registry.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set

from ceph_tpu.analysis.framework import (
    NEVER_BASELINE_PREFIXES, Check, SourceFile, Violation, call_name,
    dotted, enclosing_scope,
)

# files that implement the machinery itself (the registry, the tracer,
# the tracker): their internal uses of these names are the mechanism,
# not call sites
_SELF = ("core/tracing.py", "core/optracker.py")


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class SpanDiscipline(Check):
    name = "span-discipline"
    description = ("start_span must reach finish() on all paths; "
                   "mark_event/_op_stage/literal-annotate names must "
                   "be declared in tracing.STAGES")
    scopes = ("ceph_tpu", "tools")

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        from ceph_tpu.core.tracing import STAGES

        out: List[Violation] = []
        for f in files:
            if any(f.rel.endswith(s) for s in _SELF):
                continue
            out.extend(self._check_stage_names(f, STAGES))
            out.extend(self._check_span_finish(f))
        return out

    # -- stage-name registry ------------------------------------------------
    def _check_stage_names(self, f: SourceFile,
                           stages) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            base = call_name(node).rsplit(".", 1)[-1]
            if base == "mark_event" and node.args:
                arg = node.args[0]
            elif base == "_op_stage" and len(node.args) >= 2:
                # PG._op_stage(msg, "<stage>", ...) — stage is arg 2
                # at a call site, arg index differs for the bound form
                arg = node.args[1] if not isinstance(
                    node.args[0], ast.Constant) else node.args[0]
            elif base == "annotate" and node.args:
                arg = node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue  # f-string/variable detail: free-form
            else:
                continue
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                out.append(Violation(
                    check=self.name, path=f.rel, line=node.lineno,
                    scope=enclosing_scope(f.tree, node.lineno),
                    detail=f"{base}(<dynamic>)",
                    message=(f"{base}() stage name must be a string "
                             "literal — a dynamic name evades the "
                             "registry and every grep"),
                ))
                continue
            if arg.value not in stages:
                out.append(Violation(
                    check=self.name, path=f.rel, line=node.lineno,
                    scope=enclosing_scope(f.tree, node.lineno),
                    detail=f"{base}({arg.value!r})",
                    message=(f"stage name {arg.value!r} is not declared "
                             "in tracing.STAGES — a typo'd stage is a "
                             "dead timeline row"),
                ))
        return out

    # -- finish-on-all-paths --------------------------------------------------
    def _check_span_finish(self, f: SourceFile) -> List[Violation]:
        out: List[Violation] = []
        # module-wide set of dotted names that have a .finish() call
        module_finished: Set[str] = set()
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "finish"):
                base = dotted(node.func.value)
                if base:
                    module_finished.add(base)

        # map every start_span call to its innermost enclosing function
        # (or module) and the targets it is bound to
        func_of: Dict[ast.AST, ast.AST] = {}
        for fn in _functions(f.tree):
            for child in ast.walk(fn):
                func_of.setdefault(child, fn)

        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node).rsplit(".", 1)[-1] != "start_span":
                continue
            scope_node = func_of.get(node, f.tree)
            if self._span_handled(node, scope_node, module_finished):
                continue
            out.append(Violation(
                check=self.name, path=f.rel, line=node.lineno,
                scope=enclosing_scope(f.tree, node.lineno),
                detail="start_span-unfinished",
                message=("start_span() result is neither a `with` "
                         "context manager nor bound to a target with "
                         "a matching .finish() — the span can never "
                         "archive"),
            ))
        return out

    @staticmethod
    def _span_handled(call: ast.Call, scope: ast.AST,
                      module_finished: Set[str]) -> bool:
        targets: List[str] = []
        for node in ast.walk(scope):
            # with tracer.start_span(...) [as s]: finish via __exit__
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.context_expr is call:
                        return True
            if isinstance(node, ast.Assign) and node.value is call:
                for t in node.targets:
                    name = dotted(t)
                    if name:
                        targets.append(name)
            if (isinstance(node, (ast.AnnAssign, ast.AugAssign))
                    and getattr(node, "value", None) is call):
                name = dotted(node.target)
                if name:
                    targets.append(name)
            # span = x or tr.start_span(...) style defaults
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.BoolOp, ast.IfExp)):
                sub = ast.walk(node.value)
                if any(s is call for s in sub):
                    for t in node.targets:
                        name = dotted(t)
                        if name:
                            targets.append(name)
        if not targets:
            return False
        # accept when the enclosing function (closures included) calls
        # .finish() on the same target; fall back to a module-wide
        # match for handles finished by a sibling method
        finished_here: Set[str] = set()
        for node in ast.walk(scope):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "finish"):
                base = dotted(node.func.value)
                if base:
                    finished_here.add(base)
        for t in targets:
            # an attribute target like `rnd.span` matches a finish on
            # `rnd.span` or on any alias ending with the same attr
            # (`self._round.span.finish()` / `op.span.finish()`)
            tail = t.rsplit(".", 1)[-1]
            for got in finished_here | module_finished:
                if got == t or got.rsplit(".", 1)[-1] == tail:
                    return True
        return False


# the observability layer ships WITH this check: no accepted debt,
# violations are hard errors everywhere
NEVER_BASELINE_PREFIXES.append((SpanDiscipline.name, ""))
