"""no-blocking-on-loop: the fast-dispatch carve-out, enforced.

PR 2 moved write-acks / MOSDOp-enqueue / pings inline onto the
messenger event loop (``ms_can_fast_dispatch``) with a comment-level
contract: those handlers never block — no store work, no lock waits,
no RPCs.  A handler that breaks the contract wedges the loop that must
read every peer's replies, which presents as a cluster-wide liveness
hang (the exact shape of the PR 1 EAGAIN storms).

This check builds a call graph whose roots are

  - every ``async def`` (they run on some event loop),
  - ``ms_dispatch`` of every class whose ``ms_can_fast_dispatch`` is
    not literally ``return False``,
  - callbacks scheduled onto the loop via ``call_soon`` /
    ``call_soon_threadsafe`` / ``call_later`` / ``_loop_call``,

and flags blocking primitives reachable from them: ``time.sleep``,
``.acquire()`` (without ``blocking=False``), ``with <lock>``,
``.wait()`` / ``.wait_for()``, ``.result()``, ``.join()``, sync
``open()``, sync socket ops, and ``apply_transaction``.  Calls
directly under ``await`` are the loop doing its job and are exempt.

Resolution is deliberately conservative (``self.m`` within the class
and its same-repo bases, bare names within the module, ``mod.f``
through imports): an unresolvable call is not followed rather than
guessed.  Short mutex holds that are genuinely fine annotate the site
or live in the baseline — the point is that NEW inline handlers get
reviewed against the contract by a machine.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name, dotted,
)

_LOCKISH = re.compile(r"(^|_)(lock|rlock|lk|lck|mutex|guard|cond|cv)$",
                      re.IGNORECASE)
_SLEEPS = {"time.sleep", "_time.sleep"}
_SYNC_SOCKET = {"recv", "sendall", "accept"}
_SCHED_ARG0 = {"call_soon", "call_soon_threadsafe", "_loop_call"}
_SCHED_ARG1 = {"call_later", "call_at"}


def _body_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs or
    lambdas — those only block if somebody calls them, and then the
    call site is the finding."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _awaited_calls(fn: ast.AST) -> Set[int]:
    return {id(n.value) for n in _body_walk(fn)
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)}


def _returns_false_only(fn: ast.FunctionDef) -> bool:
    body = [st for st in fn.body
            if not (isinstance(st, ast.Expr)
                    and isinstance(st.value, ast.Constant)
                    and isinstance(st.value.value, str))]
    return (len(body) == 1 and isinstance(body[0], ast.Return)
            and isinstance(body[0].value, ast.Constant)
            and body[0].value.value is False)


class _Module:
    def __init__(self, f: SourceFile) -> None:
        self.file = f
        self.modname = f.rel[:-3].replace("/", ".")
        self.funcs: Dict[str, ast.AST] = {}       # module-level defs
        self.classes: Dict[str, "_Class"] = {}
        self.imports: Dict[str, str] = {}          # local -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in f.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = _Class(node)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname
                                 or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        node.module, alias.name)


class _Class:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.bases = [dotted(b) for b in node.bases]
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


class _Func:
    """One analyzable function with its lexical context."""

    def __init__(self, mod: _Module, cls: Optional[str],
                 name: str, node: ast.AST) -> None:
        self.mod = mod
        self.cls = cls
        self.name = name
        self.node = node

    @property
    def qual(self) -> str:
        local = f"{self.cls}.{self.name}" if self.cls else self.name
        return f"{self.mod.modname}:{local}"

    @property
    def local(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class NoBlockingOnLoop(Check):
    name = "no-blocking-on-loop"
    description = ("blocking primitives reachable from the messenger "
                   "event loop or a fast-dispatched handler")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        mods = {m.modname: m for m in (_Module(f) for f in files)}
        index: Dict[str, _Func] = {}
        for mod in mods.values():
            for name, node in mod.funcs.items():
                fn = _Func(mod, None, name, node)
                index[fn.qual] = fn
            for cname, cls in mod.classes.items():
                for mname, node in cls.methods.items():
                    fn = _Func(mod, cname, mname, node)
                    index[fn.qual] = fn

        roots = self._find_roots(mods, index)
        # BFS with parent pointers for example chains
        parent: Dict[str, Optional[str]] = {q: None for q in roots}
        frontier = list(roots)
        while frontier:
            q = frontier.pop()
            for callee in self._edges(index[q], mods):
                if callee.qual not in parent:
                    parent[callee.qual] = q
                    frontier.append(callee.qual)

        out: List[Violation] = []
        reported: Set[Tuple[str, int]] = set()
        for q in parent:
            fn = index[q]
            for line, prim in self._primitives(fn):
                site = (fn.mod.file.rel, line)
                if site in reported:
                    continue
                reported.add(site)
                chain: List[str] = []
                cur: Optional[str] = q
                while cur is not None:
                    chain.append(index[cur].local)
                    cur = parent[cur]
                chain.reverse()
                out.append(Violation(
                    check=self.name, path=fn.mod.file.rel, line=line,
                    scope=fn.local, detail=prim,
                    message=self._message(prim, chain),
                ))
        return out

    def _message(self, prim: str, chain: List[str]) -> str:
        """Violation text hook — subclasses reusing the call-graph
        machinery (no-d2h-on-hot-path) state their own contract."""
        return (f"{prim} can block the event loop: reachable "
                f"via {' -> '.join(chain)} (fast-dispatch/"
                "loop contract: no store work, no lock "
                "waits, no RPCs)")

    # -- roots ------------------------------------------------------------
    def _find_roots(self, mods: Dict[str, _Module],
                    index: Dict[str, _Func]) -> Set[str]:
        roots: Set[str] = set()
        for fn in index.values():
            if isinstance(fn.node, ast.AsyncFunctionDef):
                roots.add(fn.qual)
        # fast-dispatching classes: their ms_dispatch runs inline
        for mod in mods.values():
            for cname, cls in mod.classes.items():
                can = cls.methods.get("ms_can_fast_dispatch")
                if can is None or _returns_false_only(can):
                    continue
                disp = self._resolve_method(mod, cname, "ms_dispatch", mods)
                if disp is not None:
                    roots.add(disp.qual)
        # loop-scheduled callbacks: call_soon(self.cb) etc.
        for fn in list(index.values()):
            for node in _body_walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                base = call_name(node).split(".")[-1]
                arg = None
                if base in _SCHED_ARG0 and node.args:
                    arg = node.args[0]
                elif base in _SCHED_ARG1 and len(node.args) > 1:
                    arg = node.args[1]
                if arg is None:
                    continue
                target = self._resolve_call(fn, dotted(arg), mods)
                if target is not None:
                    roots.add(target.qual)
        return roots

    # -- call graph -------------------------------------------------------
    def _edges(self, fn: _Func, mods: Dict[str, _Module]) -> List[_Func]:
        out: List[_Func] = []
        for node in _body_walk(fn.node):
            if isinstance(node, ast.Call):
                target = self._resolve_call(fn, call_name(node), mods)
                if target is not None:
                    out.append(target)
        return out

    def _resolve_call(self, fn: _Func, cn: str,
                      mods: Dict[str, _Module]) -> Optional[_Func]:
        if not cn:
            return None
        parts = cn.split(".")
        mod = fn.mod
        if parts[0] == "self" and len(parts) == 2 and fn.cls:
            return self._resolve_method(mod, fn.cls, parts[1], mods)
        if len(parts) == 1:
            if parts[0] in mod.funcs:
                return _Func(mod, None, parts[0], mod.funcs[parts[0]])
            fi = mod.from_imports.get(parts[0])
            if fi:
                src = mods.get(fi[0])
                if src and fi[1] in src.funcs:
                    return _Func(src, None, fi[1], src.funcs[fi[1]])
            return None
        if len(parts) == 2:
            target_mod = mods.get(mod.imports.get(parts[0], ""))
            if target_mod and parts[1] in target_mod.funcs:
                return _Func(target_mod, None, parts[1],
                             target_mod.funcs[parts[1]])
        return None

    def _resolve_method(self, mod: _Module, cname: str, mname: str,
                        mods: Dict[str, _Module],
                        depth: int = 0) -> Optional[_Func]:
        if depth > 8:
            return None
        cls = mod.classes.get(cname)
        if cls is None:
            return None
        if mname in cls.methods:
            return _Func(mod, cname, mname, cls.methods[mname])
        for base in cls.bases:
            bname = base.split(".")[-1]
            if bname in mod.classes and bname != cname:
                hit = self._resolve_method(mod, bname, mname, mods,
                                           depth + 1)
                if hit is not None:
                    return hit
            fi = mod.from_imports.get(bname)
            if fi:
                src = mods.get(fi[0])
                if src and fi[1] in src.classes:
                    hit = self._resolve_method(src, fi[1], mname, mods,
                                               depth + 1)
                    if hit is not None:
                        return hit
        return None

    # -- blocking primitives ----------------------------------------------
    def _primitives(self, fn: _Func) -> List[Tuple[int, str]]:
        awaited = _awaited_calls(fn.node)
        out: List[Tuple[int, str]] = []
        for node in _body_walk(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = dotted(item.context_expr)
                    if name and _LOCKISH.search(name.split(".")[-1]):
                        out.append((node.lineno, f"with {name}"))
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            cn = call_name(node)
            base = cn.split(".")[-1]
            if cn in _SLEEPS:
                out.append((node.lineno, "time.sleep"))
            elif cn == "open":
                out.append((node.lineno, "sync open()"))
            elif "." in cn and base == "acquire":
                blocking = True
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value is False:
                    blocking = False
                for kw in node.keywords:
                    if kw.arg == "blocking" and isinstance(
                            kw.value, ast.Constant) and \
                            kw.value.value is False:
                        blocking = False
                if blocking:
                    out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base in ("wait", "wait_for", "result"):
                out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base == "join" and not node.args:
                out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base in _SYNC_SOCKET:
                out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base == "apply_transaction":
                out.append((node.lineno, f"{cn}()"))
        return out
