"""no-blocking-on-loop: the fast-dispatch carve-out, enforced.

PR 2 moved write-acks / MOSDOp-enqueue / pings inline onto the
messenger event loop (``ms_can_fast_dispatch``) with a comment-level
contract: those handlers never block — no store work, no lock waits,
no RPCs.  A handler that breaks the contract wedges the loop that must
read every peer's replies, which presents as a cluster-wide liveness
hang (the exact shape of the PR 1 EAGAIN storms).

Since PR 18 this is a view over the shared thread-role engine
(``analysis/threadmodel.py``): the check is exactly the (loop,
may-block) cell of the role/capability lattice.  Roots and call-graph
propagation live in the engine; this module owns only the blocking
primitives: ``time.sleep``, ``.acquire()`` (without
``blocking=False``), ``with <lock>``, ``.wait()`` / ``.wait_for()``,
``.result()``, ``.join()``, sync ``open()``, sync socket ops, and
``apply_transaction``.  Calls directly under ``await`` are the loop
doing its job and are exempt.

Resolution is deliberately conservative (``self.m`` within the class
and its same-repo bases, bare names within the module, ``mod.f``
through imports): an unresolvable call is not followed rather than
guessed.  Short mutex holds that are genuinely fine annotate the site
or live in the baseline — the point is that NEW inline handlers get
reviewed against the contract by a machine.
"""

from __future__ import annotations

import ast
import re
from typing import List, Sequence, Set, Tuple

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name, dotted,
)
from ceph_tpu.analysis.threadmodel import (
    ROLE_LOOP, FuncInfo, ThreadModel, awaited_calls, body_walk,
)

_LOCKISH = re.compile(r"(^|_)(lock|rlock|lk|lck|mutex|guard|cond|cv)$",
                      re.IGNORECASE)
_SLEEPS = {"time.sleep", "_time.sleep"}
_SYNC_SOCKET = {"recv", "sendall", "accept"}


class NoBlockingOnLoop(Check):
    name = "no-blocking-on-loop"
    description = ("blocking primitives reachable from the messenger "
                   "event loop or a fast-dispatched handler")
    scopes = ("ceph_tpu",)

    # the (role, capability) cells this check owns
    roles: Tuple[str, ...] = (ROLE_LOOP,)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        tm = ThreadModel.of(files)
        out: List[Violation] = []
        reported: Set[Tuple[str, int]] = set()
        for role in self.roles:
            for q in tm.reach[role]:
                fn = tm.program.index.get(q)
                if fn is None:
                    continue
                for line, prim in self._primitives(fn):
                    site = (fn.mod.file.rel, line)
                    if site in reported:
                        continue
                    reported.add(site)
                    out.append(Violation(
                        check=self.name, path=fn.mod.file.rel,
                        line=line, scope=fn.local, detail=prim,
                        message=self._message(prim, tm.chain(role, q)),
                    ))
        return out

    def _message(self, prim: str, chain: List[str]) -> str:
        """Violation text hook — subclasses reusing the engine
        (no-d2h-on-hot-path) state their own contract."""
        return (f"{prim} can block the event loop: reachable "
                f"via {' -> '.join(chain)} (fast-dispatch/"
                "loop contract: no store work, no lock "
                "waits, no RPCs)")

    # -- blocking primitives ----------------------------------------------
    def _primitives(self, fn: FuncInfo) -> List[Tuple[int, str]]:
        awaited = awaited_calls(fn.node)
        out: List[Tuple[int, str]] = []
        for node in body_walk(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = dotted(item.context_expr)
                    if name and _LOCKISH.search(name.split(".")[-1]):
                        out.append((node.lineno, f"with {name}"))
            if not isinstance(node, ast.Call) or id(node) in awaited:
                continue
            cn = call_name(node)
            base = cn.split(".")[-1]
            if cn in _SLEEPS:
                out.append((node.lineno, "time.sleep"))
            elif cn == "open":
                out.append((node.lineno, "sync open()"))
            elif "." in cn and base == "acquire":
                blocking = True
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and node.args[0].value is False:
                    blocking = False
                for kw in node.keywords:
                    if kw.arg == "blocking" and isinstance(
                            kw.value, ast.Constant) and \
                            kw.value.value is False:
                        blocking = False
                if blocking:
                    out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base in ("wait", "wait_for", "result"):
                out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base == "join" and not node.args:
                out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base in _SYNC_SOCKET:
                out.append((node.lineno, f"{cn}()"))
            elif "." in cn and base == "apply_transaction":
                out.append((node.lineno, f"{cn}()"))
        return out
