"""qos-class-registry: QoS class names at enqueue sites must be
declared in the profile registry (``ceph_tpu.osd.qos.KNOWN_QOS_CLASSES``).

A ``qos_class=`` literal is a CONTRACT with the dmClock profile table:
a typo'd name silently rides the ``best_effort`` triple — the
reservation/limit the site meant to claim never applies, and nothing
fails (the scheduler is work-conserving, so the ops still flow and the
fairness regression only shows under saturation).  This is the
failpoint-name-registry shape applied to scheduler classes: literal
names are validated against the one table; dynamic values are the
sanctioned ``classify_op`` resolver path and pass.

Baseline-free from day one: the registry ships with this PR, so there
is no accepted debt — every violation is a hard error and
``--write-baseline`` refuses to record them.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    NEVER_BASELINE_PREFIXES, Check, SourceFile, Violation,
    enclosing_scope,
)


class QosClassRegistry(Check):
    name = "qos-class-registry"
    description = ("qos_class= literals at enqueue sites must exist in "
                   "qos.KNOWN_QOS_CLASSES (typo = silent best_effort)")
    scopes = ("ceph_tpu", "tools")

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        from ceph_tpu.osd.qos import KNOWN_QOS_CLASSES

        out: List[Violation] = []
        for f in files:
            if f.rel.endswith("osd/qos.py"):
                continue  # the registry itself
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                for kw in node.keywords:
                    if kw.arg != "qos_class":
                        continue
                    v = kw.value
                    if not (isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        continue  # dynamic = the classify_op path
                    if v.value not in KNOWN_QOS_CLASSES:
                        out.append(Violation(
                            check=self.name, path=f.rel,
                            line=node.lineno,
                            scope=enclosing_scope(f.tree, node.lineno),
                            detail=f"qos_class={v.value!r}",
                            message=(f"QoS class {v.value!r} is not in "
                                     "qos.KNOWN_QOS_CLASSES — a typo'd "
                                     "class silently rides best_effort"),
                        ))
        return out


# scheduler-class plumbing must stay correct-by-construction: refuse
# to baseline ANY violation of this check, anywhere
NEVER_BASELINE_PREFIXES.append((QosClassRegistry.name, ""))
