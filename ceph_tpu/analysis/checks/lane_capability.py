"""lane-capability: the role/capability lattice cells no older check
owned, enforced.  NEVER baselineable.

The thread-role engine (``analysis/threadmodel.py``) assigns every
function the set of lanes that can execute it.  Each lane carries a
capability set; ``DENIED_CAPS`` names what a lane must never do:

  ==============  =========  ============  ========  ===========
  role            may-block  may-pg-lock   may-d2h   may-compile
  ==============  =========  ============  ========  ===========
  loop            NO [PR3]   NO [PR5]      NO [PR6]  NO [PR17]
  device_worker   yes        NO [PR5]      NO [PR6]  yes
  shard_worker    yes        yes           yes       yes
  fanout          yes        yes           yes       yes
  commit          yes        yes           yes       yes
  timer           yes        yes           yes       yes
  thread          yes        yes           yes       yes
  ==============  =========  ============  ========  ===========

(loop, may-block) is ``no-blocking-on-loop`` and (loop|device,
may-d2h) is ``no-d2h-on-hot-path`` — those keep their names and their
baselines.  THIS check enforces the remaining denied cells:

- **may-take-pg-lock** from ``loop`` or ``device_worker``: the PR 5
  invariant as code.  A pg lock (``pg.lock`` / ``self.lock`` inside
  ``PG`` / ``maintenance_guard``) acquired on the messenger loop or
  the device worker deadlocks against lanes that hold the pg lock
  while waiting on a stripe future or a peer reply — decode
  completions were moved to fresh threads for exactly this reason.

- **may-compile** from ``loop``: creating a jit/pallas entry point on
  the event loop stalls every peer's frames behind an XLA compile
  (PR 10 measured 89% of a workload's wall inside compiles).

Both are structural deadlock/liveness lanes, so violations are NEVER
baselineable anywhere under ceph_tpu/ — fix the lane handoff (spawn a
fresh thread, enqueue to the shard queue) or prove the site safe and
annotate it inline with a rationale.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set, Tuple

from ceph_tpu.analysis.framework import (
    NEVER_BASELINE_PREFIXES, Check, SourceFile, Violation, call_name,
    dotted,
)
from ceph_tpu.analysis.threadmodel import (
    CAP_COMPILE, CAP_PG_LOCK, DENIED_CAPS, ROLE_DEVICE, ROLE_LOOP,
    FuncInfo, ThreadModel, body_walk,
)

# compile entry points: creating (or invoking the creation of) a
# traced callable — each distinct shape through one of these is an XLA
# compile
_COMPILE_CALLS = {"jax.jit", "pl.pallas_call", "pallas.pallas_call"}
_COMPILE_BASES = {"instrumented_jit", "pallas_call"}


def _nonblocking(call: ast.Call) -> bool:
    """``.acquire(blocking=False)`` / ``.acquire(False)``: a
    try-acquire returns instead of waiting — it cannot deadlock the
    lane, so the capability rule does not apply."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value is False:
        return True
    return any(kw.arg == "blocking"
               and isinstance(kw.value, ast.Constant)
               and kw.value.value is False
               for kw in call.keywords)


def _is_pg_lock(name: str, fn: FuncInfo) -> bool:
    """True when a dotted expression names a pg-lane lock: the PG's
    own mutex or the maintenance guard."""
    if not name:
        return False
    parts = name.split(".")
    if parts[-1] == "maintenance_guard":
        return True
    if parts[-1] != "lock" or len(parts) < 2:
        return False
    owner = parts[-2]
    if owner in ("pg", "_pg"):
        return True
    # self.lock inside the PG class itself
    return owner == "self" and fn.cls == "PG"


class LaneCapability(Check):
    name = "lane-capability"
    description = ("per-role capability lattice: pg locks unreachable "
                   "from the loop/device lanes, compiles unreachable "
                   "from the loop")
    scopes = ("ceph_tpu",)

    # (role, capability) cells enforced HERE (the rest belong to
    # no-blocking-on-loop / no-d2h-on-hot-path)
    CELLS: Tuple[Tuple[str, str], ...] = (
        (ROLE_LOOP, CAP_PG_LOCK),
        (ROLE_DEVICE, CAP_PG_LOCK),
        (ROLE_LOOP, CAP_COMPILE),
    )

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        tm = ThreadModel.of(files)
        out: List[Violation] = []
        reported: Set[Tuple[str, int, str]] = set()
        for role, cap in self.CELLS:
            assert cap in DENIED_CAPS.get(role, ()), \
                f"lattice drift: {role} is not denied {cap}"
            for q in tm.reach[role]:
                fn = tm.program.index.get(q)
                if fn is None:
                    continue
                finder = (self._pg_lock_sites if cap == CAP_PG_LOCK
                          else self._compile_sites)
                for line, prim in finder(fn):
                    site = (fn.mod.file.rel, line, cap)
                    if site in reported:
                        continue
                    reported.add(site)
                    chain = " -> ".join(tm.chain(role, q))
                    out.append(Violation(
                        check=self.name, path=fn.mod.file.rel,
                        line=line, scope=fn.local,
                        detail=f"{role}:{cap}:{prim}",
                        message=(
                            f"{prim} on the {role} lane (reachable via "
                            f"{chain}) — the {role} lane lacks the "
                            f"{cap} capability; hand off to a thread "
                            "or the shard queue instead"),
                    ))
        return out

    # -- primitive finders -------------------------------------------------
    def _pg_lock_sites(self, fn: FuncInfo) -> List[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        for node in body_walk(fn.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = dotted(item.context_expr)
                    if _is_pg_lock(name, fn):
                        out.append((node.lineno, f"with {name}"))
            elif isinstance(node, ast.Call):
                cn = call_name(node)
                if cn.endswith(".acquire") and _is_pg_lock(
                        cn.rsplit(".", 1)[0], fn) and \
                        not _nonblocking(node):
                    out.append((node.lineno, f"{cn}()"))
        return out

    def _compile_sites(self, fn: FuncInfo) -> List[Tuple[int, str]]:
        out: List[Tuple[int, str]] = []
        for node in body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            cn = call_name(node)
            if cn in _COMPILE_CALLS or cn.split(".")[-1] in _COMPILE_BASES:
                out.append((node.lineno, f"{cn}()"))
        return out


# structural deadlock lanes: debt here is never accepted, anywhere
NEVER_BASELINE_PREFIXES.append((LaneCapability.name, "ceph_tpu/"))
