"""unguarded-shared-state: cross-lane data races on instance state.

For every class the check infers, per ``self._x`` attribute, which
named-lock regions its writes happen under.  An attribute that is
written under a lock on one path has a de-facto guard contract; an
access (read OR write) that touches the same attribute while holding
none of its guard locks, from a method that a DIFFERENT thread role
can execute (per the shared thread-role engine), is the classic
half-guarded race: the locked path paid for atomicity the unlocked
path silently voids.  This is exactly the PR 13 recovery-counter bug
shape (``note_recovery_grant`` mutating QoS counters with and without
``qos.recovery`` held) found by machine instead of by bench anomaly.

Mechanics, deliberately conservative:

- Guard tracking is lexical: ``with self.X:`` (where ``X`` is a lock
  attribute — constructed from ``make_lock``/``threading.Lock``-family
  calls, or lockish-named) extends the held set for the region body.
- Caller-held inference: a private method (``self._m``) called ONLY
  from regions that hold lock L is analyzed as holding L — this is the
  ``_locked``-suffix convention, inferred instead of trusted, computed
  to fixpoint over in-class call chains.  Public methods get no such
  credit: external callers owe no locks.
- Writes are assignments, augmented assignments, subscript stores, and
  mutator calls (``append``/``add``/``pop``/``update``/...) on the
  attribute.  Everything else is a read.
- ``__init__`` is construction-time single-threaded and exempt.
- Roles come from ``ThreadModel.roles_of``: the violation fires only
  when the unguarded accessor's role set differs from the guarded
  writers' — same-lane sequential access is not a race.

One violation per (class, attribute): the baseline key is line-free
and survives refactors.  True positives get fixed; benign patterns
(monotonic flags read for shutdown hints, GIL-atomic snapshots for
stats) annotate the site inline with a rationale or live in the
baseline — the point is every NEW half-guarded attribute gets a
machine review.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, NamedTuple, Optional, Sequence, Set

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name,
)
from ceph_tpu.analysis.threadmodel import ThreadModel

_LOCKISH = re.compile(r"(^|_)(lock|rlock|lk|lck|mutex|guard|cond|cv)$",
                      re.IGNORECASE)
_LOCK_CTORS = {"make_lock", "Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"append", "appendleft", "add", "pop", "popleft", "update",
             "discard", "remove", "clear", "extend", "setdefault",
             "insert", "rotate"}


class _Access(NamedTuple):
    meth: str           # local method name
    qual: str           # mod:Class.meth for role lookup
    line: int
    write: bool
    held: frozenset     # lock attr names held at the access


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _wait_for_lock(call: ast.Call, locks: Set[str]) -> Optional[str]:
    """``self.X.wait_for(pred)`` with X a lock attr: the predicate
    runs with X held (threading.Condition contract)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "wait_for":
        owner = _self_attr(f.value)
        if owner is not None and owner in locks:
            return owner
    return None


class UnguardedSharedState(Check):
    name = "unguarded-shared-state"
    description = ("instance attributes written under a lock on one "
                   "path but accessed lock-free from a different "
                   "thread role on another")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        tm = ThreadModel.of(files)
        out: List[Violation] = []
        for mod in tm.program.mods.values():
            for cname in sorted(mod.classes):
                out.extend(self._check_class(tm, mod, cname))
        return out

    # -- per-class analysis ------------------------------------------------
    def _check_class(self, tm: ThreadModel, mod, cname: str
                     ) -> List[Violation]:
        methods = [fn for fn in tm.program.index.values()
                   if fn.mod is mod and fn.cls == cname]
        if not methods:
            return []
        locks = self._lock_attrs(methods)
        caller_held = self._caller_held(methods, locks)
        accesses: Dict[str, List[_Access]] = {}
        for fn in methods:
            if fn.local.endswith("__init__"):
                continue
            extra = caller_held.get(fn.local, frozenset())
            writes = self._write_nodes(fn.node)
            for attr, line, held in self._held_accesses(fn.node, locks):
                if attr in locks:
                    continue
                accesses.setdefault(attr, []).append(_Access(
                    meth=fn.local, qual=fn.qual, line=line,
                    write=(line, attr) in writes, held=held | extra))
        out: List[Violation] = []
        for attr in sorted(accesses):
            out.extend(self._judge(tm, mod, cname, attr, accesses[attr]))
        return out

    def _judge(self, tm: ThreadModel, mod, cname: str, attr: str,
               accs: List[_Access]) -> List[Violation]:
        guarded_writes = [a for a in accs if a.write and a.held]
        if not guarded_writes:
            return []
        guard_locks: Set[str] = set()
        writer_roles: Set[str] = set()
        for a in guarded_writes:
            guard_locks |= a.held
            writer_roles |= tm.roles_of(a.qual)
        out: List[Violation] = []
        seen: Set = set()
        for a in accs:
            if a.held & guard_locks:
                continue
            aroles = tm.roles_of(a.qual)
            if aroles == writer_roles:
                continue  # same lane end to end: sequential
            if (a.meth, a.line) in seen:
                continue
            seen.add((a.meth, a.line))
            w = guarded_writes[0]
            kind = "written" if a.write else "read"
            out.append(Violation(
                check=self.name, path=mod.file.rel, line=a.line,
                scope=cname, detail=attr,
                message=(
                    f"self.{attr} is written under "
                    f"{'/'.join(sorted(guard_locks))} in {w.meth} "
                    f"(lanes: {','.join(sorted(writer_roles))}) but "
                    f"{kind} lock-free in {a.meth} (lanes: "
                    f"{','.join(sorted(aroles))}) at line {a.line} — "
                    "take the guard lock, or annotate why the "
                    "unguarded access is safe"),
            ))
        return out

    # -- caller-held inference ---------------------------------------------
    def _caller_held(self, methods, locks: Set[str]
                     ) -> Dict[str, frozenset]:
        """The ``_locked``-suffix convention, inferred: locks held at
        EVERY in-class ``self._m(...)`` call site accrue to the private
        method ``_m``.  Fixpoint over call chains (a private helper
        called only from other lock-holding private helpers inherits
        through them).  Public methods always resolve to the empty set
        — callers outside the class owe nothing."""
        names = {fn.local.rsplit(".", 1)[-1] for fn in methods}
        private = {n for n in names
                   if n.startswith("_") and not n.startswith("__")}
        # method -> [(caller short name, lexical held at call site)]
        sites: Dict[str, List] = {}
        for fn in methods:
            short = fn.local.rsplit(".", 1)[-1]
            for callee, held in self._self_call_sites(fn.node, locks):
                if callee in private:
                    sites.setdefault(callee, []).append((short, held))
        held_of: Dict[str, frozenset] = {
            n: frozenset(locks) if n in sites else frozenset()
            for n in private}

        def resolve(name: str) -> frozenset:
            return held_of.get(name, frozenset())

        changed = True
        while changed:
            changed = False
            for n, ss in sites.items():
                eff = None
                for caller, held in ss:
                    h = held | resolve(caller)
                    eff = h if eff is None else (eff & h)
                eff = eff or frozenset()
                if eff != held_of[n]:
                    held_of[n] = eff
                    changed = True
        return {fn.local: held_of.get(fn.local.rsplit(".", 1)[-1],
                                      frozenset())
                for fn in methods}

    def _self_call_sites(self, fn_node: ast.AST, locks: Set[str]):
        """(callee short name, lexical held frozenset) for every
        ``self._m(...)`` call in the method."""
        out: List = []

        def rec(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, ast.With):
                for item in node.items:
                    rec(item.context_expr, held)
                grabbed = {a for item in node.items
                           for a in [_self_attr(item.context_expr)]
                           if a and a in locks}
                inner = held | frozenset(grabbed)
                for b in node.body:
                    rec(b, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # closures run later with NO inherited locks, but
                # their own with-regions still guard their calls
                for b in node.body:
                    rec(b, frozenset())
                return
            if isinstance(node, ast.Lambda):
                rec(node.body, frozenset())
                return
            if isinstance(node, ast.Call):
                waiter = _wait_for_lock(node, locks)
                if waiter is not None:
                    # Condition.wait_for runs its predicate HOLDING
                    # the condition's lock
                    inner = held | frozenset({waiter})
                    for a in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        rec(a.body if isinstance(a, ast.Lambda) else a,
                            inner)
                    return
                attr = _self_attr(node.func)
                if attr:
                    out.append((attr, held))
            for child in ast.iter_child_nodes(node):
                rec(child, held)

        for stmt in getattr(fn_node, "body", []):
            rec(stmt, frozenset())
        return out

    # -- lock attribute discovery ------------------------------------------
    def _lock_attrs(self, methods) -> Set[str]:
        out: Set[str] = set()
        for fn in methods:
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1:
                    attr = _self_attr(node.targets[0])
                    if attr is None:
                        continue
                    if isinstance(node.value, ast.Call) and \
                            call_name(node.value).split(".")[-1] in \
                            _LOCK_CTORS:
                        out.add(attr)
                    elif _LOCKISH.search(attr):
                        out.add(attr)
                elif isinstance(node, ast.With):
                    for item in node.items:
                        attr = _self_attr(item.context_expr)
                        if attr and _LOCKISH.search(attr):
                            out.add(attr)
        return out

    # -- write classification ----------------------------------------------
    def _write_nodes(self, fn_node: ast.AST) -> Set:
        """(line, attr) pairs that are WRITES (assign / augassign /
        subscript store / mutator call)."""
        out: Set = set()

        def note(expr: ast.AST) -> None:
            attr = _self_attr(expr)
            if attr:
                out.add((expr.lineno, attr))

        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for el in ast.walk(t):
                        if isinstance(el, ast.Subscript):
                            note(el.value)
                        else:
                            note(el)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                note(node.target)
                if isinstance(node.target, ast.Subscript):
                    note(node.target.value)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                    note(f.value)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        note(t.value)
                    else:
                        note(t)
        return out

    # -- held-set tracking -------------------------------------------------
    def _held_accesses(self, fn_node: ast.AST, locks: Set[str]):
        """Yield (attr, line, held frozenset) for every ``self.X``
        touch, with the lexical set of held lock attrs."""
        out: List = []

        def rec(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, ast.With):
                grabbed = set()
                for item in node.items:
                    rec(item.context_expr, held)
                    attr = _self_attr(item.context_expr)
                    if attr and attr in locks:
                        grabbed.add(attr)
                inner = held | frozenset(grabbed)
                for b in node.body:
                    rec(b, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure executes later holding NONE of the locks
                # lexically around its definition; its accesses still
                # belong to this attribute's access inventory
                for b in node.body:
                    rec(b, frozenset())
                return
            if isinstance(node, ast.Lambda):
                rec(node.body, frozenset())
                return
            if isinstance(node, ast.Call):
                waiter = _wait_for_lock(node, locks)
                if waiter is not None:
                    # Condition.wait_for runs its predicate HOLDING
                    # the condition's lock
                    inner = held | frozenset({waiter})
                    rec(node.func, held)
                    for a in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        rec(a.body if isinstance(a, ast.Lambda) else a,
                            inner)
                    return
            attr = _self_attr(node)
            if attr is not None:
                out.append((attr, node.lineno, held))
            for child in ast.iter_child_nodes(node):
                rec(child, held)

        # start at the statements, not the FunctionDef itself (the
        # nested-def bail-out would otherwise eat the whole method)
        for stmt in getattr(fn_node, "body", []):
            rec(stmt, frozenset())
        return out
