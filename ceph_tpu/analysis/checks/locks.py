"""named-locks: every threading.Lock()/RLock() under ceph_tpu/ must be
created through core.lockdep.make_lock(name).

Rationale: lockdep (the reference src/common/lockdep.cc port) can only
order-check locks it can NAME.  A raw threading.Lock is invisible to
the cycle detector, so a deadlock involving it stays a rare production
hang instead of a deterministic test failure.  ceph_tpu/core/lockdep.py
itself is exempt (it IS the factory).

Legitimate raw locks exist — a Lock released by a different thread
than its acquirer (pg.maintenance_guard) cannot become an RLock-backed
DMutex — and annotate themselves with
``# cephlint: disable=named-locks — <why>``.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name, enclosing_scope,
)


class NamedLocks(Check):
    name = "named-locks"
    description = ("threading.Lock()/RLock() must be created via "
                   "core.lockdep.make_lock(name)")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            if f.rel.endswith("core/lockdep.py"):
                continue
            # only flag the bare names when they alias threading's
            # (``from threading import Lock``), not some local Lock
            imported_bare = set()
            for node in ast.walk(f.tree):
                if (isinstance(node, ast.ImportFrom)
                        and node.module == "threading"):
                    for alias in node.names:
                        if alias.name in ("Lock", "RLock"):
                            imported_bare.add(alias.asname or alias.name)
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                if cn in ("threading.Lock", "threading.RLock") or (
                        cn in imported_bare):
                    kind = cn.rsplit(".", 1)[-1]
                    out.append(Violation(
                        check=self.name, path=f.rel, line=node.lineno,
                        scope=enclosing_scope(f.tree, node.lineno),
                        detail=kind,
                        message=(f"raw threading.{kind}() — create via "
                                 "core.lockdep.make_lock(name) so lockdep "
                                 "can order-check it"),
                    ))
        return out
