"""silent-except: `except Exception: pass` (or bare except) swallows
everything — including the bug you are currently hunting.

The PR 1/PR 2 postmortems both lost hours to handlers that ate a
TypeError and presented as a liveness hang.  A handler may still
swallow broadly, but it must either NARROW the type to what the
best-effort operation actually throws (`except (ConnectionError,
OSError)` around a socket close) or LOG the exception so the ring
buffer shows it at crash-dump time.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, dotted, enclosing_scope,
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Attribute):
        return dotted(t).split(".")[-1] in _BROAD
    if isinstance(t, ast.Tuple):
        return any(_is_broad(ast.ExceptHandler(type=el, name=None, body=[]))
                   for el in t.elts)
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/... — no logging, no fallback assignment."""
    return all(
        isinstance(st, ast.Pass)
        or (isinstance(st, ast.Expr)
            and isinstance(st.value, ast.Constant)
            and st.value.value is Ellipsis)
        for st in handler.body)


class SilentExcept(Check):
    name = "silent-except"
    description = ("`except Exception: pass` must narrow the type or "
                   "log the exception")
    scopes = ("ceph_tpu", "tools")

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            for node in ast.walk(f.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if _is_broad(node) and _is_silent(node):
                    kind = ("bare except" if node.type is None
                            else "except Exception")
                    out.append(Violation(
                        check=self.name, path=f.rel, line=node.lineno,
                        scope=enclosing_scope(f.tree, node.lineno),
                        detail=kind,
                        message=(f"{kind}: pass — narrow to the exceptions "
                                 "the operation actually throws, or log "
                                 "before swallowing"),
                    ))
        return out
