"""no-unverified-read: every store read must pass the per-extent
verify gate in ``ObjectStore.read`` (store/objectstore.py).

The read-time integrity contract has exactly one enforcement point:
the base-class ``read()`` fetches a covering span via the backend's
``_read_span`` hook, applies the corruption seam, verifies the served
extents against their at-rest seals, and only then slices.  Any path
around it is a silent-corruption conduit — rotted media served to a
client as if it were the acked bytes.  Three bypass shapes exist and
all are flagged:

  * calling a backend's raw ``_read_span`` hook anywhere outside
    store/objectstore.py (the hook returns UNVERIFIED bytes by
    contract; only the gate may consume it),
  * an ObjectStore subclass overriding ``read`` (shadowing the gate:
    the override's reads never verify unless it reimplements the
    whole discipline — backends implement ``_read_span`` instead),
  * hard-disabling the gate with a literal ``verify_reads = False``
    in production code (ceph_tpu/) — the knob exists for the bench
    comparison and the conf observer, both of which assign a
    runtime-computed value, never a constant.

Baseline-free from day one: the gate ships with this PR, so there is
no accepted debt — every violation is a hard error and
``--write-baseline`` refuses to record them.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    NEVER_BASELINE_PREFIXES, Check, SourceFile, Violation, call_name,
    enclosing_scope,
)

_GATE_FILE = "store/objectstore.py"


def _is_objectstore_subclass(node: ast.ClassDef) -> bool:
    for b in node.bases:
        name = (b.id if isinstance(b, ast.Name)
                else b.attr if isinstance(b, ast.Attribute) else "")
        if name.endswith("ObjectStore"):
            return True
    return False


class NoUnverifiedRead(Check):
    name = "no-unverified-read"
    description = ("store reads must go through the ObjectStore.read "
                   "verify gate — no raw _read_span calls, read() "
                   "overrides, or literal verify_reads=False")
    scopes = ("ceph_tpu", "tools")

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            if f.rel.endswith(_GATE_FILE):
                continue  # the gate itself
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call):
                    base = call_name(node).rsplit(".", 1)[-1]
                    if base == "_read_span":
                        out.append(Violation(
                            check=self.name, path=f.rel,
                            line=node.lineno,
                            scope=enclosing_scope(f.tree, node.lineno),
                            detail="_read_span(...)",
                            message=("_read_span returns UNVERIFIED "
                                     "bytes — only ObjectStore.read "
                                     "(the verify gate) may call it; "
                                     "use store.read()"),
                        ))
                elif isinstance(node, ast.ClassDef):
                    if not _is_objectstore_subclass(node):
                        continue
                    for item in node.body:
                        if (isinstance(item, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))
                                and item.name == "read"):
                            out.append(Violation(
                                check=self.name, path=f.rel,
                                line=item.lineno,
                                scope=f"{node.name}.read",
                                detail="def read(...) override",
                                message=("overriding ObjectStore.read "
                                         "shadows the extent verify "
                                         "gate — implement _read_span "
                                         "instead"),
                            ))
                elif (isinstance(node, (ast.Assign, ast.AnnAssign))
                      and f.rel.startswith("ceph_tpu/")):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    named = any(
                        (isinstance(t, ast.Attribute)
                         and t.attr == "verify_reads")
                        or (isinstance(t, ast.Name)
                            and t.id == "verify_reads")
                        for t in targets)
                    v = node.value
                    if (named and isinstance(v, ast.Constant)
                            and not v.value):
                        out.append(Violation(
                            check=self.name, path=f.rel,
                            line=node.lineno,
                            scope=enclosing_scope(f.tree, node.lineno),
                            detail="verify_reads = False",
                            message=("hard-disabling the read verify "
                                     "gate in production code serves "
                                     "rotted media as acked bytes — "
                                     "gate via conf "
                                     "(store_verify_read) instead"),
                        ))
        return out


# the read-integrity gate must stay correct-by-construction: refuse
# to baseline ANY violation of this check, anywhere
NEVER_BASELINE_PREFIXES.append((NoUnverifiedRead.name, ""))
