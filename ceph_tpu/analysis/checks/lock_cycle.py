"""lock-order-cycle: static lock-order cycle detection.  NEVER
baselineable.

Runtime lockdep (``core/lockdep.py``, the src/common/lockdep.cc port)
learns "held -> acquiring" edges only on paths that actually EXECUTE —
a deadlock on the untested interleaving stays a production hang.  This
check builds the whole-program acquisition graph statically:

1. **Lock classes** — every ``make_lock(name)`` call defines one.
   F-string names (``make_lock(f"osd{n}.pg{pgid}")``) become patterns
   with ``{}`` placeholders (``osd{}.pg{}``): one static class covers
   every runtime instance, and ``classify()`` maps a runtime instance
   name back to its class for the runtime ⊆ static cross-check in
   tier-1 (tests/test_lockdep.py).

2. **Acquisition regions** — nested ``with <lock>:`` regions, where
   ``<lock>`` resolves through ``self.attr`` assignments (including
   ``threading.Condition(make_lock(...))`` wrappers), module globals,
   function locals, locals constructed from known classes, and — as a
   last resort — attributes whose name maps to exactly ONE lock class
   program-wide.  Unresolvable lockish expressions are recorded (the
   dump shows them) but create no edges: conservative, not guessed.

3. **Edges across the call graph** — holding A while acquiring B adds
   A -> B, whether B is taken in the same body or anywhere in the
   transitive closure of calls made inside A's region.  Call
   resolution layers, in order: the shared Program resolver
   (``self.meth`` / module functions), a TYPE map for cross-object
   calls (``self.backend.submit()`` follows ``self.backend:
   PGBackend = ECBackend(...)`` — annotations, constructor calls, and
   annotated ctor parameters all feed it, multi-valued where branches
   assign different classes), annotated function parameters
   (``store: MemStore``), nested defs (a closure's acquisitions
   belong to whoever calls it — passing one as a callback argument
   counts as a call, that's how ``reply_once`` reaches the commit
   path), and finally a bounded fallback: a method name defined by at
   most ``_FALLBACK_OWNERS`` classes program-wide resolves to ALL of
   them (duck-typed seams like ``osd.send_to_osd`` stay modeled).

A cycle in the class graph is a potential ABBA deadlock and fails the
build (never baselineable); re-entrant same-class nesting is NOT an
edge, matching runtime lockdep's re-entrancy rule.  The full graph
dumps via ``tools/cephlint.py --lock-graph=dot|json``.

The static graph over-approximates (context-insensitive closure, no
path feasibility): it may contain edges no execution performs.  That
is the correct direction — the tier-1 contract is *runtime-observed
edges ⊆ static graph*, so a runtime edge the model cannot see means an
unmodeled call path and fails the cross-check test loudly.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ceph_tpu.analysis.framework import (
    NEVER_BASELINE_PREFIXES, Check, SourceFile, Violation, call_name,
    dotted,
)
from ceph_tpu.analysis.threadmodel import FuncInfo, Module, Program

_LOCKISH = re.compile(r"(^|_)(lock|rlock|lk|lck|mutex|guard|cond|cv)$",
                      re.IGNORECASE)

# plain (unnamed) sync-primitive constructors: a self.X assigned one
# of these is a REAL lock but not a make_lock class — record it so no
# name-based fallback binds the attr to a named class it isn't
_PLAIN_SYNC_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
                     "BoundedSemaphore", "Event"}


def _lock_name_from_call(node: ast.AST) -> Optional[str]:
    """The lock-class pattern of a ``make_lock(...)`` call (possibly
    wrapped in ``threading.Condition(...)``), else None.  F-string
    fields become ``{}`` placeholders."""
    if not isinstance(node, ast.Call):
        return None
    cn = call_name(node)
    base = cn.split(".")[-1]
    if base == "Condition" and node.args:
        return _lock_name_from_call(node.args[0])
    if base != "make_lock" or not node.args:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts: List[str] = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("{}")
        return "".join(parts)
    return None


def _pattern_regex(pattern: str) -> "re.Pattern[str]":
    out = []
    for piece in pattern.split("{}"):
        out.append(re.escape(piece))
    return re.compile("^" + ".+?".join(out) + "$")


class LockModel:
    """The whole-program static acquisition graph."""

    _CACHE: Dict[Tuple[int, ...], "LockModel"] = {}

    def __init__(self, program: Program) -> None:
        self.program = program
        # class pattern -> "path:line" of a defining make_lock call
        self.classes: Dict[str, str] = {}
        # (modname, class-or-None, attr) -> pattern
        self._attr: Dict[Tuple[str, Optional[str], str], str] = {}
        # qual -> [(pattern, with-node)]
        self._regions: Dict[str, List[Tuple[str, ast.With]]] = {}
        # qual -> function-local var -> pattern
        self._locals: Dict[str, Dict[str, str]] = {}
        # lockish with-exprs we could not resolve: (path, line, expr)
        self.unresolved: List[Tuple[str, int, str]] = []
        # a -> b -> example site string
        self.edges: Dict[str, Dict[str, str]] = {}
        # (modname, class, attr) -> {(modname, class)} instance types
        self._attr_types: Dict[Tuple[str, str, str],
                               Set[Tuple[str, str]]] = {}
        # method name -> {(modname, class)} every class defining it
        self._method_owners: Dict[str, Set[Tuple[str, str]]] = {}
        # module-level VAR = ClassName(...) singletons
        self._mod_instances: Dict[Tuple[str, str], Tuple[str, str]] = {}
        self._ctor_cache: Dict[str, Dict[str, Tuple[Module, str]]] = {}
        self._nested_cache: Dict[str, Dict[str, ast.AST]] = {}
        # attrs assigned a PLAIN (unnamed) sync primitive — known
        # locks that are NOT a make_lock class, so the attr-name
        # fallback must never bind them to one
        self._plain_lock_attrs: Set[Tuple[str, str, str]] = set()
        self._collect_defs()
        self._attr_by_name: Dict[str, Set[str]] = {}
        for (_m, _c, attr), pat in self._attr.items():
            self._attr_by_name.setdefault(attr, set()).add(pat)
        self._collect_types()
        self._collect_regions()
        self._build_edges()

    @classmethod
    def of(cls, files: Sequence[SourceFile]) -> "LockModel":
        key = tuple(id(f.tree) for f in files)
        hit = cls._CACHE.get(key)
        if hit is None:
            hit = cls._CACHE[key] = cls(Program.of(files))
        return hit

    # -- definitions ------------------------------------------------------
    def _note_class(self, pattern: str, mod: Module, line: int) -> None:
        self.classes.setdefault(pattern, f"{mod.file.rel}:{line}")

    def _collect_defs(self) -> None:
        for mod in self.program.mods.values():
            # module-level: VAR = make_lock(...)
            for node in mod.file.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    pat = _lock_name_from_call(node.value)
                    if pat:
                        self._attr[(mod.modname, None,
                                    node.targets[0].id)] = pat
                        self._note_class(pat, mod, node.lineno)
            # attribute + local assignments anywhere in the module
            for fn in self._functions(mod):
                for node in ast.walk(fn.node):
                    if not isinstance(node, ast.Assign) or \
                            len(node.targets) != 1:
                        continue
                    pat = _lock_name_from_call(node.value)
                    if not pat:
                        tgt = node.targets[0]
                        if (fn.cls and isinstance(node.value, ast.Call)
                                and (call_name(node.value).split(".")[-1]
                                     in _PLAIN_SYNC_CTORS)
                                and isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            self._plain_lock_attrs.add(
                                (mod.modname, fn.cls, tgt.attr))
                        continue
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and fn.cls:
                        self._attr[(mod.modname, fn.cls, tgt.attr)] = pat
                        self._note_class(pat, mod, node.lineno)
                    elif isinstance(tgt, ast.Name):
                        self._locals.setdefault(fn.qual, {})[tgt.id] = pat
                        self._note_class(pat, mod, node.lineno)

    def _functions(self, mod: Module) -> List[FuncInfo]:
        return [fn for fn in self.program.index.values()
                if fn.mod is mod]

    # -- instance types ----------------------------------------------------
    def _resolve_class(self, mod: Module,
                       name: str) -> Optional[Tuple[str, str]]:
        """A class NAME visible from ``mod`` -> (modname, class)."""
        if not name:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in mod.classes:
                return (mod.modname, parts[0])
            fi = mod.from_imports.get(parts[0])
            if fi and fi[0] in self.program.mods \
                    and fi[1] in self.program.mods[fi[0]].classes:
                return (fi[0], fi[1])
            return None
        src = self.program.mods.get(mod.imports.get(parts[0], ""))
        if src and parts[-1] in src.classes:
            return (src.modname, parts[-1])
        return None

    def _ann_type(self, mod: Module,
                  ann: Optional[ast.AST]) -> Optional[Tuple[str, str]]:
        """``x: ClassName`` / ``Optional[ClassName]`` / ``"ClassName"``
        -> the named class, when it resolves to a program class."""
        if ann is None:
            return None
        if isinstance(ann, ast.Subscript):
            # Optional[X] / "X | None" style wrappers: the payload type
            return self._ann_type(mod, ann.slice)
        if isinstance(ann, ast.BinOp):  # X | None
            return (self._ann_type(mod, ann.left)
                    or self._ann_type(mod, ann.right))
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return self._resolve_class(mod, ann.value)
        name = dotted(ann)
        return self._resolve_class(mod, name) if name else None

    def _param_types(self, fn: FuncInfo) -> Dict[str, Tuple[str, str]]:
        a = fn.node.args
        out: Dict[str, Tuple[str, str]] = {}
        for arg in list(getattr(a, "posonlyargs", [])) + list(a.args) \
                + list(a.kwonlyargs):
            t = self._ann_type(fn.mod, arg.annotation)
            if t:
                out[arg.arg] = t
        return out

    def _collect_types(self) -> None:
        prog = self.program
        for mod in prog.mods.values():
            for cname, ci in mod.classes.items():
                for mname in ci.methods:
                    self._method_owners.setdefault(mname, set()).add(
                        (mod.modname, cname))
            for node in mod.file.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call):
                    t = self._resolve_class(mod, call_name(node.value))
                    if t:
                        self._mod_instances[
                            (mod.modname, node.targets[0].id)] = t
        for fn in prog.index.values():
            if not fn.cls:
                continue
            params = self._param_types(fn)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val, ann = node.targets[0], node.value, None
                elif isinstance(node, ast.AnnAssign):
                    tgt, val, ann = node.target, node.value, node.annotation
                else:
                    continue
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                key = (fn.mod.modname, fn.cls, tgt.attr)
                t = self._ann_type(fn.mod, ann)
                if t:
                    self._attr_types.setdefault(key, set()).add(t)
                for t in self._value_types(fn.mod, val, params):
                    self._attr_types.setdefault(key, set()).add(t)

    def _value_types(self, mod, val, params) -> Set[Tuple[str, str]]:
        """Possible instance types of an assigned value.  The attr map
        is multi-valued, so conditional forms contribute EVERY branch:
        ``kv if kv is not None else MemDB()`` types as both the param
        and MemDB."""
        out: Set[Tuple[str, str]] = set()
        if isinstance(val, ast.Call):
            t = self._resolve_class(mod, call_name(val))
            if t:
                out.add(t)
        elif isinstance(val, ast.Name) and val.id in params:
            out.add(params[val.id])
        elif isinstance(val, ast.IfExp):
            out |= self._value_types(mod, val.body, params)
            out |= self._value_types(mod, val.orelse, params)
        elif isinstance(val, ast.BoolOp):
            for v in val.values:
                out |= self._value_types(mod, v, params)
        return out

    def _attr_types_for(self, modname: str, cname: str,
                        attr: str) -> Set[Tuple[str, str]]:
        """Instance types of ``<cname>.<attr>``, walking bases."""
        out: Set[Tuple[str, str]] = set()
        seen: Set[Tuple[str, str]] = set()
        stack = [(modname, cname)]
        while stack:
            m, c = stack.pop()
            if (m, c) in seen:
                continue
            seen.add((m, c))
            out |= self._attr_types.get((m, c, attr), set())
            mod = self.program.mods.get(m)
            ci = mod.classes.get(c) if mod else None
            if ci is None:
                continue
            for base in ci.bases:
                t = self._resolve_class(mod, base)
                if t:
                    stack.append(t)
        return out

    def _owner_types(self, fn: FuncInfo,
                     owner: List[str]) -> Set[Tuple[str, str]]:
        """Instance types of a dotted owner chain (``self.osd.msgr``)."""
        base = owner[0]
        cur: Set[Tuple[str, str]] = set()
        if base == "self" and fn.cls:
            cur = {(fn.mod.modname, fn.cls)}
        else:
            ctor = self._ctors(fn).get(base)
            if ctor is not None:
                cur = {(ctor[0].modname, ctor[1])}
            else:
                t = (self._param_types(fn).get(base)
                     or self._mod_instances.get((fn.mod.modname, base)))
                if t:
                    cur = {t}
        for attr in owner[1:]:
            nxt: Set[Tuple[str, str]] = set()
            for m, c in cur:
                nxt |= self._attr_types_for(m, c, attr)
            cur = nxt
            if not cur:
                break
        return cur

    def _ctors(self, fn: FuncInfo) -> Dict[str, Tuple[Module, str]]:
        hit = self._ctor_cache.get(fn.qual)
        if hit is None:
            hit = self._ctor_cache[fn.qual] = self._ctor_classes(fn)
        return hit

    def _nested_defs(self, fn: FuncInfo) -> Dict[str, ast.AST]:
        """Nested function defs inside ``fn``, by name."""
        hit = self._nested_cache.get(fn.qual)
        if hit is None:
            hit = {n.name: n for n in ast.walk(fn.node)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))
                   and n is not fn.node}
            self._nested_cache[fn.qual] = hit
        return hit

    # a method name defined by at most this many classes program-wide
    # resolves (to ALL of them) even with no type information — the
    # duck-typed seams (pg.osd "host daemon", the 4-implementor store
    # protocol) stay modeled without guessing on generic names like
    # get/send/run (those have many more owners and stay unresolved)
    _FALLBACK_OWNERS = 4

    # names shared with stdlib containers / sync primitives: an
    # untyped `x.append(...)` is a deque, not whatever program class
    # happens to define `append` — the fallback never fires on these
    _STDLIB_NAMES: Set[str] = (
        set(dir(list)) | set(dir(dict)) | set(dir(set)) | set(dir(str))
        | set(dir(bytes)) | {"appendleft", "popleft", "rotate",
                             "extendleft", "maxlen",  # deque
                             "acquire", "release", "locked", "wait",
                             "wait_for", "notify", "notify_all",
                             "is_set", "put", "put_nowait",
                             "get_nowait", "task_done", "join",
                             "submit", "result", "set_result",
                             "add_done_callback", "cancel", "close"})

    def _methodish_targets(self, fn: FuncInfo, owner: List[str],
                           mname: str) -> List[FuncInfo]:
        """``<owner chain>.<mname>`` -> candidate methods: typed chain
        first, bounded program-wide name fallback second."""
        out: List[FuncInfo] = []
        if owner:
            for m, c in sorted(self._owner_types(fn, owner)):
                hit = self.program.resolve_method(
                    self.program.mods[m], c, mname)
                if hit is not None:
                    out.append(hit)
            if out:
                return out
        if mname in self._STDLIB_NAMES:
            return out
        owners = self._method_owners.get(mname, set())
        if 0 < len(owners) <= self._FALLBACK_OWNERS:
            for m, c in sorted(owners):
                hit = self.program.resolve_method(
                    self.program.mods[m], c, mname)
                if hit is not None:
                    out.append(hit)
        return out

    def _call_targets(self, fn: FuncInfo,
                      call: ast.Call) -> List[FuncInfo]:
        """Every function a call might reach: Program resolution,
        typed cross-object chains, then the bounded name fallback.
        ``getattr(obj, "meth")`` with a constant name counts as a
        reference about to be invoked on this stack (the pipelined
        write engine's duck-typed ``note_write_inflight`` hook)."""
        cn = call_name(call)
        if cn == "getattr" and len(call.args) >= 2 \
                and isinstance(call.args[1], ast.Constant) \
                and isinstance(call.args[1].value, str):
            owner = dotted(call.args[0])
            return self._methodish_targets(
                fn, owner.split(".") if owner else [],
                call.args[1].value)
        t = self.program.resolve_call(fn, cn)
        if t is not None:
            return [t]
        if not cn:
            return []
        parts = cn.split(".")
        if len(parts) >= 2:
            return self._methodish_targets(fn, parts[:-1], parts[-1])
        return []

    # -- region resolution -------------------------------------------------
    def _attr_pattern(self, mod: Module, cls: Optional[str],
                      attr: str) -> Optional[str]:
        """self.<attr> lookup through the class and its resolvable
        bases (same module or imported)."""
        seen: Set[Tuple[str, str]] = set()
        stack: List[Tuple[Module, Optional[str]]] = [(mod, cls)]
        while stack:
            m, c = stack.pop()
            if c is None:
                continue
            if (m.modname, c) in seen:
                continue
            seen.add((m.modname, c))
            hit = self._attr.get((m.modname, c, attr))
            if hit:
                return hit
            ci = m.classes.get(c)
            if ci is None:
                continue
            for base in ci.bases:
                bname = base.split(".")[-1]
                if bname in m.classes:
                    stack.append((m, bname))
                fi = m.from_imports.get(bname)
                if fi and fi[0] in self.program.mods:
                    stack.append((self.program.mods[fi[0]], fi[1]))
        return None

    def _ctor_classes(self, fn: FuncInfo) -> Dict[str, Tuple[Module, str]]:
        """Function-local vars constructed from known classes:
        ``op = InFlightOp(...)`` lets ``with op.lock:`` resolve."""
        out: Dict[str, Tuple[Module, str]] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                cn = call_name(node.value).split(".")[-1]
                if cn in fn.mod.classes:
                    out[node.targets[0].id] = (fn.mod, cn)
                else:
                    fi = fn.mod.from_imports.get(cn)
                    if fi and fi[0] in self.program.mods:
                        src = self.program.mods[fi[0]]
                        if fi[1] in src.classes:
                            out[node.targets[0].id] = (src, fi[1])
        return out

    def resolve_lock_expr(self, fn: FuncInfo, expr: ast.AST
                          ) -> Optional[str]:
        name = dotted(expr)
        if not name:
            return None
        parts = name.split(".")
        attr = parts[-1]
        if len(parts) == 1:
            # bare local or module-level var
            hit = self._locals.get(fn.qual, {}).get(attr)
            if hit:
                return hit
            return self._attr.get((fn.mod.modname, None, attr))
        owner = parts[-2]
        if owner == "self" and len(parts) == 2 and fn.cls:
            hit = self._attr_pattern(fn.mod, fn.cls, attr)
            if hit:
                return hit
        ctor = self._ctor_classes(fn).get(owner)
        if ctor is not None:
            hit = self._attr_pattern(ctor[0], ctor[1], attr)
            if hit:
                return hit
        # an attr the class assigns a PLAIN primitive is a known
        # unnamed lock: resolving it to a named class would be wrong
        if owner == "self" and fn.cls and \
                (fn.mod.modname, fn.cls, attr) in self._plain_lock_attrs:
            return None
        # last resort: an attribute name used by exactly one lock
        # class anywhere in the program is unambiguous
        cands = self._attr_by_name.get(attr, set())
        if len(cands) == 1:
            return next(iter(cands))
        return None

    def _collect_regions(self) -> None:
        for fn in self.program.index.values():
            regions: List[Tuple[str, ast.With]] = []
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    expr = item.context_expr
                    pat = self.resolve_lock_expr(fn, expr)
                    if pat:
                        regions.append((pat, node))
                    else:
                        name = dotted(expr)
                        if name and _LOCKISH.search(name.split(".")[-1]):
                            self.unresolved.append(
                                (fn.mod.file.rel, node.lineno, name))
            if regions:
                self._regions[fn.qual] = regions

    # -- edges -------------------------------------------------------------
    def _nested_acquired(self, fn: FuncInfo,
                         dnode: ast.AST) -> Set[str]:
        """Lock classes acquired lexically inside a nested def —
        charged to whoever CALLS the closure (or passes it onward as
        a callback), not to its lexical position."""
        out: Set[str] = set()
        for node in ast.walk(dnode):
            if isinstance(node, ast.With):
                for item in node.items:
                    pat = self.resolve_lock_expr(fn, item.context_expr)
                    if pat:
                        out.add(pat)
        return out

    def _may_acquire(self, fn: FuncInfo, call: ast.Call,
                     closure: Dict[str, Set[str]]) -> Set[str]:
        """Every lock class a call might acquire transitively: the
        targets' closures, plus the acquisitions of any nested def
        passed as a callback argument (the callee will invoke it on
        this call stack — ``reply_once`` handed to the commit path)."""
        out: Set[str] = set()
        nested = self._nested_defs(fn)
        cn = call_name(call)
        if cn in nested:
            out |= self._nested_acquired(fn, nested[cn])
        for tgt in self._call_targets(fn, call):
            out |= closure.get(tgt.qual, set())
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in nested:
                out |= self._nested_acquired(fn, nested[arg.id])
        return out

    def _build_edges(self) -> None:
        prog = self.program
        # per-function direct acquisitions
        local: Dict[str, Set[str]] = {
            q: {pat for pat, _ in regs}
            for q, regs in self._regions.items()}
        # callee quals per function (full body including nested defs
        # — their regions are charged to the encloser too)
        callees: Dict[str, Set[str]] = {}
        for q, fn in prog.index.items():
            outs: Set[str] = set()
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    for t in self._call_targets(fn, node):
                        outs.add(t.qual)
            callees[q] = outs
        # fixpoint: closure[f] = local[f] U closure[callees]
        closure: Dict[str, Set[str]] = {
            q: set(local.get(q, ())) for q in prog.index}
        changed = True
        while changed:
            changed = False
            for q, cs in callees.items():
                mine = closure[q]
                before = len(mine)
                for c in cs:
                    mine |= closure.get(c, set())
                if len(mine) != before:
                    changed = True

        def add_edge(a: str, b: str, site: str) -> None:
            if a == b:
                return  # re-entrancy is not an order edge
            self.edges.setdefault(a, {}).setdefault(b, site)

        for q, regions in self._regions.items():
            fn = prog.index[q]
            rel = fn.mod.file.rel
            for pat, wnode in regions:
                # everything lexically inside the region body
                for node in ast.walk(wnode):
                    if node is wnode:
                        continue
                    if isinstance(node, ast.With):
                        for item in node.items:
                            inner = self.resolve_lock_expr(
                                fn, item.context_expr)
                            if inner:
                                add_edge(pat, inner,
                                         f"{rel}:{node.lineno} "
                                         f"({fn.local})")
                    elif isinstance(node, ast.Call):
                        for inner in self._may_acquire(fn, node,
                                                       closure):
                            add_edge(pat, inner,
                                     f"{rel}:{node.lineno} "
                                     f"({fn.local})")

    # -- queries -----------------------------------------------------------
    def cycles(self) -> List[List[str]]:
        """Elementary cycles via SCC decomposition: one representative
        cycle per non-trivial SCC (deterministic order)."""
        graph = {a: sorted(bs) for a, bs in self.edges.items()}
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(graph.get(v, ())))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(graph.get(w, ()))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    low[work[-1][0]] = min(low[work[-1][0]], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        out: List[List[str]] = []
        for comp in sccs:
            cyc = self._example_cycle(comp)
            if cyc:
                out.append(cyc)
        return out

    def _example_cycle(self, comp: List[str]) -> Optional[List[str]]:
        """A concrete edge walk a -> ... -> a within one SCC."""
        start = comp[0]
        compset = set(comp)
        path = [start]
        seen = {start}
        node = start
        while True:
            nxts = [b for b in sorted(self.edges.get(node, ()))
                    if b in compset]
            if not nxts:
                return None
            nxt = nxts[0]
            for b in nxts:
                if b == start and len(path) > 1:
                    return path + [start]
                if b not in seen:
                    nxt = b
                    break
            else:
                if nxts[0] == start:
                    return path + [start]
                return None
            path.append(nxt)
            seen.add(nxt)
            node = nxt

    def classify(self, runtime_name: str) -> Optional[str]:
        """Map a runtime lock instance name to its static class."""
        if runtime_name in self.classes:
            return runtime_name
        best: Optional[str] = None
        best_lit = -1
        for pat in self.classes:
            if "{}" not in pat:
                continue
            if _pattern_regex(pat).match(runtime_name):
                lit = len(pat.replace("{}", ""))
                if lit > best_lit:
                    best, best_lit = pat, lit
        return best

    # -- dumps -------------------------------------------------------------
    def to_json(self) -> dict:
        return {
            "classes": dict(sorted(self.classes.items())),
            "edges": {a: {b: site for b, site in sorted(bs.items())}
                      for a, bs in sorted(self.edges.items())},
            "cycles": self.cycles(),
            "unresolved": [f"{p}:{ln}: {expr}"
                           for p, ln, expr in sorted(self.unresolved)],
        }

    def to_dot(self) -> str:
        cyc_edges: Set[Tuple[str, str]] = set()
        for cyc in self.cycles():
            for a, b in zip(cyc, cyc[1:]):
                cyc_edges.add((a, b))
        lines = ["digraph lockorder {"]
        for a in sorted(self.edges):
            for b in sorted(self.edges[a]):
                attr = " [color=red]" if (a, b) in cyc_edges else ""
                lines.append(f'  "{a}" -> "{b}"{attr};')
        lines.append("}")
        return "\n".join(lines)


class LockOrderCycle(Check):
    name = "lock-order-cycle"
    description = ("static lock acquisition graph over make_lock "
                   "names must be acyclic (ABBA deadlock freedom)")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        model = LockModel.of(files)
        out: List[Violation] = []
        for cyc in model.cycles():
            edge_sites = []
            for a, b in zip(cyc, cyc[1:]):
                edge_sites.append(
                    f"{a} -> {b} at {self_edge_site(model, a, b)}")
            first_site = self_edge_site(model, cyc[0], cyc[1])
            path, _, line = first_site.partition(":")
            lineno = int(line.split(" ")[0]) if line else 1
            out.append(Violation(
                check=self.name, path=path, line=lineno,
                scope="<lock-graph>",
                detail="cycle:" + "->".join(cyc),
                message=("static lock-order cycle (potential ABBA "
                         "deadlock): " + "; ".join(edge_sites) +
                         " — break the cycle or hand one side off to "
                         "another lane"),
            ))
        return out


def self_edge_site(model: LockModel, a: str, b: str) -> str:
    return model.edges.get(a, {}).get(b, "?:1")


# deadlock freedom is structural: never accepted as debt
NEVER_BASELINE_PREFIXES.append((LockOrderCycle.name, "ceph_tpu/"))
