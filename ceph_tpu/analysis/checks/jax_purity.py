"""jax-purity: functions traced by jax.jit / pallas_call must be pure.

A traced function runs ONCE at trace time; anything outside the jax
ops — np.* math, time.* reads, Python RNG — is baked into the
compiled artifact as a constant and silently stops varying at run
time (the classic "my kernel ignores its input" bug).  float64
mentions break under the default x32 mode on TPU.

Traced roots are found syntactically: ``@jax.jit``/``@jit``/
``@partial(jax.jit, ...)`` decorations, first arguments to
``jax.jit(...)`` / ``pallas_call(...)`` / ``pl.pallas_call(...)``
calls, and same-module helpers those roots call.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name, dotted, qualname_index,
)

_TRACE_ENTRY = {"jax.jit", "jit", "pallas_call", "pl.pallas_call",
                "jax.pmap", "pmap", "jax.vmap", "checkify.checkify",
                # the devwatch wrappers (the ONLY sanctioned jit/pallas
                # spellings per no-unwatched-jit) trace their first
                # argument exactly like the raw entry points
                "instrumented_jit", "devwatch.instrumented_jit",
                "instrumented_pallas_call",
                "devwatch.instrumented_pallas_call"}
_IMPURE_ROOTS = {"np", "numpy", "time", "random"}
_F64 = {"np.float64", "numpy.float64", "jnp.float64"}


def _decorator_traced(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        name = dotted(dec) if not isinstance(dec, ast.Call) else (
            call_name(dec))
        if name in _TRACE_ENTRY:
            return True
        if isinstance(dec, ast.Call) and call_name(dec) in (
                "partial", "functools.partial") and dec.args:
            if dotted(dec.args[0]) in _TRACE_ENTRY:
                return True
    return False


class JaxPurity(Check):
    name = "jax-purity"
    description = ("jit/pallas-traced functions must not call np.*, "
                   "time.*, Python RNG, or mention float64")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            qn = qualname_index(f.tree)
            funcs: Dict[str, ast.AST] = {
                name: node for node, name in qn.items()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
            # roots: decorated, or passed by (last-component) name into
            # a trace entry point
            roots: Set[str] = set()
            for name, node in funcs.items():
                if _decorator_traced(node):
                    roots.add(name)
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call) and (
                        call_name(node) in _TRACE_ENTRY) and node.args:
                    target = dotted(node.args[0])
                    if target:
                        for name in funcs:
                            if name.split(".")[-1] == target.split(".")[-1]:
                                roots.add(name)
            if not roots:
                continue
            # reach same-module helpers by bare-name calls
            reach = set(roots)
            frontier = list(roots)
            while frontier:
                body = funcs[frontier.pop()]
                for node in ast.walk(body):
                    if not isinstance(node, ast.Call):
                        continue
                    cn = call_name(node)
                    for name in funcs:
                        if name.split(".")[-1] == cn and name not in reach:
                            reach.add(name)
                            frontier.append(name)
            for name in sorted(reach):
                body = funcs[name]
                for node in ast.walk(body):
                    bad = None
                    if isinstance(node, ast.Call):
                        cn = call_name(node)
                        root = cn.split(".")[0]
                        if "." in cn and root in _IMPURE_ROOTS:
                            bad = cn
                    elif isinstance(node, ast.Attribute):
                        dn = dotted(node)
                        if dn in _F64:
                            bad = dn
                    if bad is None:
                        continue
                    out.append(Violation(
                        check=self.name, path=f.rel, line=node.lineno,
                        scope=name, detail=bad,
                        message=(f"{bad} inside jit/pallas-traced "
                                 f"{name}: traces to a baked-in constant "
                                 "(or breaks x32 mode); use jnp/lax/"
                                 "jax.random equivalents"),
                    ))
        return out
