"""encode/decode symmetry: wire/disk codecs must round-trip.

Three rules, each a shipped bug class:

1. pairing — a class defining ``encode``/``encode_payload`` defines the
   matching ``decode``/``decode_payload`` (an encode-only type persists
   bytes nothing can read back);
2. field order — the ordered attribute sequence the encoder writes is
   the sequence the decoder reads.  A transposed pair round-trips its
   OWN tests (both sides transposed) and corrupts against every other
   writer;
3. version tolerance — a codec whose encoder writes struct version
   >= 2 must gate its tail on the decoded version or on
   ``remaining_in_frame()`` (the MECSubWrite v2 / PGInfo v2
   discipline: a v1 blob from the golden corpus or a not-yet-upgraded
   peer decodes with the tail defaulted).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name,
)

_PAIRS = (("encode_payload", "decode_payload"), ("encode", "decode"))
_CODEC_PARAMS = {"e", "enc", "encoder", "d", "dec", "decoder", "buf"}


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _is_wire_codec(fn: ast.FunctionDef) -> bool:
    """Distinguish wire codecs (encode(self, e: Encoder)) from
    compute methods that happen to be named encode (an erasure codec's
    shard math, a compressor): exactly one non-self/cls param, named
    like an Encoder/Decoder cursor."""
    args = [a.arg for a in fn.args.args if a.arg not in ("self", "cls")]
    return len(args) == 1 and args[0] in _CODEC_PARAMS


def _in_source_order(hits: List[Tuple[int, int, str]]) -> List[str]:
    """Dedup to first occurrence, ordered by source position — codecs
    execute strictly left-to-right/top-to-bottom, so token position IS
    execution order (ast.walk is BFS and must not be trusted here)."""
    seen: List[str] = []
    for _, _, name in sorted(hits):
        if name not in seen:
            seen.append(name)
    return seen


def _enc_attr_seq(fn: ast.FunctionDef) -> List[str]:
    """Distinct self.<attr> loads in an encoder body, source order."""
    hits = [(node.lineno, node.col_offset, node.attr)
            for node in ast.walk(fn)
            if isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"]
    return _in_source_order(hits)


def _dec_attr_seq(fn: ast.FunctionDef) -> List[str]:
    """Attributes a decoder populates, source order: `self.x = ` /
    `out.x = ` stores plus keyword names of cls(...) construction."""
    hits: List[Tuple[int, int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Store):
            hits.append((node.lineno, node.col_offset, node.attr))
        elif isinstance(node, ast.Call) and call_name(node) in (
                "cls", fn.name):  # cls(kw=...) in a classmethod decode
            for kw in node.keywords:
                if kw.arg:
                    hits.append((kw.value.lineno, kw.value.col_offset,
                                 kw.arg))
    return _in_source_order(hits)


def _order_mismatch(enc: List[str], dec: List[str]
                    ) -> Optional[Tuple[str, str]]:
    """First adjacent common-attribute pair whose relative order flips."""
    common = [a for a in enc if a in dec]
    dec_pos = {a: i for i, a in enumerate(dec)}
    for i in range(len(common) - 1):
        a, b = common[i], common[i + 1]
        if dec_pos[a] > dec_pos[b]:
            return a, b
    return None


def _encoded_version(fn: ast.FunctionDef) -> int:
    """Highest literal version passed to Encoder.start() in this body
    (0 when the encoder writes no versioned frame itself)."""
    best = 0
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and call_name(node).endswith(".start")
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, int)):
            best = max(best, node.args[0].value)
    return best


def _class_version(cls: ast.ClassDef) -> int:
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Name) and t.id == "VERSION"
                        and isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, int)):
                    return node.value.value
    return 0


def _tolerates_old_versions(fn: ast.FunctionDef) -> bool:
    """Decoder gates a tail: calls remaining_in_frame(), compares a
    variable assigned from .start(), or compares a struct_v attribute
    (Message.struct_v — the decode harness stores the SENDER's
    d.start() result there, the sanctioned gate when a message carries
    both a versioned tail and the bare trace tail)."""
    version_vars = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if call_name(node).endswith("remaining_in_frame"):
                return True
            continue
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and call_name(node.value).endswith(".start")):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    version_vars.add(t.id)
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id in version_vars:
                    return True
                if (isinstance(sub, ast.Attribute)
                        and sub.attr == "struct_v"):
                    return True
    return False


class CodecSymmetry(Check):
    name = "codec-symmetry"
    description = ("encode/decode pairing, matching field order, and "
                   "old-version tolerance for versioned codecs")
    scopes = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            for cls in ast.walk(f.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                meths = _methods(cls)
                for enc_name, dec_name in _PAIRS:
                    enc = meths.get(enc_name)
                    if enc is None:
                        continue
                    if enc_name == "encode" and not _is_wire_codec(enc):
                        continue
                    dec = meths.get(dec_name)
                    if dec is None:
                        out.append(Violation(
                            check=self.name, path=f.rel, line=enc.lineno,
                            scope=f"{cls.name}.{enc_name}",
                            detail="missing-decode",
                            message=(f"{cls.name} defines {enc_name} but "
                                     f"no {dec_name}: encoded bytes nothing "
                                     "can read back"),
                        ))
                        continue
                    mism = _order_mismatch(_enc_attr_seq(enc),
                                           _dec_attr_seq(dec))
                    if mism is not None:
                        out.append(Violation(
                            check=self.name, path=f.rel, line=dec.lineno,
                            scope=f"{cls.name}.{dec_name}",
                            detail=f"order:{mism[0]}/{mism[1]}",
                            message=(f"{cls.name}: encoder writes "
                                     f"{mism[0]} before {mism[1]} but the "
                                     "decoder reads them in the other "
                                     "order"),
                        ))
                    version = max(_class_version(cls) if enc_name ==
                                  "encode_payload" else 0,
                                  _encoded_version(enc))
                    if version >= 2 and not _tolerates_old_versions(dec):
                        out.append(Violation(
                            check=self.name, path=f.rel, line=dec.lineno,
                            scope=f"{cls.name}.{dec_name}",
                            detail="no-old-version-tolerance",
                            message=(f"{cls.name} encodes struct v{version} "
                                     "but its decoder never gates on the "
                                     "decoded version or "
                                     "remaining_in_frame(): a v1 blob "
                                     "(golden corpus, older peer) would "
                                     "misdecode"),
                        ))
        return out
