"""no-sleep-poll: `while ...: time.sleep(small)` polling is forbidden.

Poll loops burn a core tick for latency: every condition change waits
out the residual sleep (PR 2 killed the 20 ms poll loops in
Objecter.wait_for_map / wait_pgs_settled for exactly this).  The
conversion target is an Event/Condition the state-changer notifies —
the waiter wakes immediately and shutdown can interrupt it.

Only literal sleeps below the threshold inside a loop are flagged:
long back-offs (30 s ticket refresh) and computed intervals
(configurable periods) are deliberate pacing, not polling.
"""

from __future__ import annotations

import ast
from typing import List, Sequence

from ceph_tpu.analysis.framework import (
    Check, SourceFile, Violation, call_name, enclosing_scope,
)

POLL_THRESHOLD_S = 1.0


class NoSleepPoll(Check):
    name = "no-sleep-poll"
    description = ("time.sleep(<1s literal) inside a loop — use an "
                   "Event/Condition wait the state-changer notifies")
    scopes = ("ceph_tpu", "tools")

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        out: List[Violation] = []
        for f in files:
            seen = set()  # nested loops would re-visit the same call
            for loop in ast.walk(f.tree):
                if not isinstance(loop, (ast.While, ast.For)):
                    continue
                for node in ast.walk(loop):
                    if (node.__class__ is ast.Call
                            and (node.lineno, node.col_offset) in seen):
                        continue
                    if not isinstance(node, ast.Call):
                        continue
                    if call_name(node) not in ("time.sleep", "sleep",
                                               "_time.sleep"):
                        continue
                    if not node.args:
                        continue
                    arg = node.args[0]
                    if not (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, (int, float))):
                        continue  # computed interval: deliberate pacing
                    if arg.value >= POLL_THRESHOLD_S:
                        continue
                    seen.add((node.lineno, node.col_offset))
                    out.append(Violation(
                        check=self.name, path=f.rel, line=node.lineno,
                        scope=enclosing_scope(f.tree, node.lineno),
                        detail=f"sleep({arg.value})",
                        message=(f"time.sleep({arg.value}) in a loop is a "
                                 "poll; wait on an Event/Condition that the "
                                 "state change notifies"),
                    ))
        return out
