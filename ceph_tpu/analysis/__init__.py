"""cephlint — repo-native AST static analysis.

The rules PR 1 and PR 2 shipped as comments ("fast-dispatch handlers
never block", "versioned codecs decode older structs", "no sleep-poll
loops") become machine-checked here, the way the reference tree's
lockdep/mutex_debug make lock discipline a runtime invariant rather
than tribal knowledge.

Entry points:
  - ``tools/cephlint.py`` CLI (``--json``, ``--write-baseline``)
  - ``tests/test_lint.py`` runs the full suite in tier-1: any
    violation not recorded in the committed suppressions baseline
    (``tools/cephlint_baseline.json``) fails the build.

Existing debt is *recorded*, not ignored: the baseline pins today's
violation counts per (check, file, scope); new code cannot add to
them.  Intentional exceptions annotate the offending line with
``# cephlint: disable=<check-name>`` and say why.
"""

from ceph_tpu.analysis.framework import (  # noqa: F401
    Check,
    SourceFile,
    Violation,
    discover_files,
    load_baseline,
    new_violations,
    run_checks,
    violations_to_baseline,
)
from ceph_tpu.analysis.checks import ALL_CHECKS  # noqa: F401
