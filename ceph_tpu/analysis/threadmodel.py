"""threadmodel — whole-program thread-role propagation engine.

Every concurrency check in this repo used to grow its own call graph
(the PR-3 fast-dispatch graph, the PR-6 device-worker graph) and its
own root discovery.  This module is the shared engine: it discovers
the REAL concurrency roots of the program — the spawn sites where a
thread lane begins — assigns each a role, and propagates role sets
through the call graph, including callback-registration edges
(``call_soon``, ``add_done_callback``, ``on_commit=``) whose targets
run on a lane the registering code does not own.

Roles (one per lane the runtime actually spawns):

  loop           asyncio messenger event loop: every ``async def``,
                 ``ms_dispatch`` of fast-dispatching classes, and
                 callbacks scheduled via ``call_soon``/``call_later``/
                 ``_loop_call``
  device_worker  ``StripeBatchQueue._worker`` — the one thread that
                 talks to the device, plus ``add_done_callback``
                 closures (stripe futures resolve ON this thread)
  shard_worker   ``ShardedWorkQueue`` shard threads and the
                 ``process=`` callbacks handed to them
  fanout         the backend's ``ThreadPoolExecutor`` fan-out lane
                 (``...executor().submit(fn)``)
  commit         the store ``CommitPipeline`` group-commit thread:
                 its ``_run`` loop, the ``sync_fn`` ctor arg, and
                 every ``on_commit=`` completion it fires
  timer          tick/sweep/watchdog/heartbeat/scrub threads
  thread         any other ``threading.Thread(target=...)`` target
  main           not a spawned lane: functions reachable from no root

Spawn sites CUT propagation: ``threading.Thread(target=f)`` makes f a
fresh root of its own role — the caller's role does not leak into it
(that handoff is exactly the PR-5 fix: decode completions run on fresh
threads so neither the device worker nor the network lanes take pg
locks).  Callback registrations PROPAGATE instead: the callback runs
on the lane that invokes it, not the lane that registered it.

On top of the role map sits a per-role capability lattice (DENIED_CAPS)
the lane-shaped checks share: may-block, may-take-pg-lock, may-d2h,
may-compile.  ``no-blocking-on-loop`` is (loop, may-block),
``no-d2h-on-hot-path`` is (loop|device, may-d2h), ``lane-capability``
enforces the rest.

Known limits (deliberate, conservative): nested function defs and
lambdas are not call-graph nodes — a closure handed to a spawn site is
followed only when it resolves to an indexed function, so an
unresolvable target is silently not analyzed rather than guessed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ceph_tpu.analysis.framework import SourceFile, call_name, dotted

# -- roles -------------------------------------------------------------------

ROLE_LOOP = "loop"
ROLE_DEVICE = "device_worker"
ROLE_SHARD = "shard_worker"
ROLE_FANOUT = "fanout"
ROLE_COMMIT = "commit"
ROLE_TIMER = "timer"
ROLE_THREAD = "thread"
ROLE_MAIN = "main"

ALL_ROLES = (ROLE_LOOP, ROLE_DEVICE, ROLE_SHARD, ROLE_FANOUT,
             ROLE_COMMIT, ROLE_TIMER, ROLE_THREAD)

# -- capabilities ------------------------------------------------------------

CAP_BLOCK = "may-block"
CAP_PG_LOCK = "may-take-pg-lock"
CAP_D2H = "may-d2h"
CAP_COMPILE = "may-compile"

# Capabilities each role LACKS.  A role absent here may do anything.
# loop: the messenger event loop reads every peer's frames — blocking
#   it is a cluster-wide liveness hang (PR 1/2/3), d2h on it is the
#   tunnel tax (PR 6), a pg lock on it is the PR-5 deadlock lane, and
#   an XLA compile on it is a multi-second stall (PR 10 measured 89%
#   of a workload's wall inside compiles).
# device_worker: must get straight back to coalescing — pg locks on it
#   deadlock against lanes that hold the pg lock while waiting on a
#   stripe future (PR 5); payload d2h re-introduces the tunnel tax.
#   It MAY compile (dispatch is where compiles happen) and MAY block
#   (its whole job is draining a queue).
DENIED_CAPS: Dict[str, Tuple[str, ...]] = {
    ROLE_LOOP: (CAP_BLOCK, CAP_PG_LOCK, CAP_D2H, CAP_COMPILE),
    ROLE_DEVICE: (CAP_PG_LOCK, CAP_D2H),
}

_SCHED_ARG0 = {"call_soon", "call_soon_threadsafe", "_loop_call"}
_SCHED_ARG1 = {"call_later", "call_at"}
_TIMER_NAME_RE = re.compile(
    r"tick|sweep|watchdog|timer|heartbeat|\bhb\b|hb_loop|scrub|renew|"
    r"ticker|deadline", re.IGNORECASE)

# well-known lane entry points that exist whether or not any spawn
# site resolves statically (module-qualified so test fixtures written
# AS these modules get the same roots the real tree does)
_FIXED_ROOTS: Tuple[Tuple[str, str], ...] = (
    (ROLE_DEVICE, "ceph_tpu.tpu.queue:StripeBatchQueue._worker"),
    (ROLE_SHARD, "ceph_tpu.core.workqueue:ShardedWorkQueue._worker"),
    (ROLE_COMMIT, "ceph_tpu.store.objectstore:CommitPipeline._run"),
)


def body_walk(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs or
    lambdas — those only run if somebody calls them, and then the call
    site is the finding."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def awaited_calls(fn: ast.AST) -> Set[int]:
    return {id(n.value) for n in body_walk(fn)
            if isinstance(n, ast.Await) and isinstance(n.value, ast.Call)}


def returns_false_only(fn: ast.FunctionDef) -> bool:
    body = [st for st in fn.body
            if not (isinstance(st, ast.Expr)
                    and isinstance(st.value, ast.Constant)
                    and isinstance(st.value.value, str))]
    return (len(body) == 1 and isinstance(body[0], ast.Return)
            and isinstance(body[0].value, ast.Constant)
            and body[0].value.value is False)


# -- program index -----------------------------------------------------------

class ClassInfo:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.bases = [dotted(b) for b in node.bases]
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


class Module:
    def __init__(self, f: SourceFile) -> None:
        self.file = f
        self.modname = f.rel[:-3].replace("/", ".")
        self.funcs: Dict[str, ast.AST] = {}       # module-level defs
        self.classes: Dict[str, ClassInfo] = {}
        self.imports: Dict[str, str] = {}          # local -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        for node in f.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassInfo(node)
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname
                                 or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        node.module, alias.name)


class FuncInfo:
    """One analyzable function with its lexical context."""

    def __init__(self, mod: Module, cls: Optional[str],
                 name: str, node: ast.AST) -> None:
        self.mod = mod
        self.cls = cls
        self.name = name
        self.node = node

    @property
    def qual(self) -> str:
        return f"{self.mod.modname}:{self.local}"

    @property
    def local(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


# built Programs are cached by the identity of their parse trees: the
# trees live forever in the framework's AST cache, so ids are stable,
# and five lane-shaped checks per run would otherwise re-walk every
# module five times
_PROGRAM_CACHE: Dict[Tuple[int, ...], "Program"] = {}


class Program:
    """Whole-program index: modules, classes, functions, and the
    conservative call resolution every lane-shaped check shares."""

    def __init__(self, files: Sequence[SourceFile]) -> None:
        self.mods: Dict[str, Module] = {
            m.modname: m for m in (Module(f) for f in files)}
        self.index: Dict[str, FuncInfo] = {}
        for mod in self.mods.values():
            for name, node in mod.funcs.items():
                fn = FuncInfo(mod, None, name, node)
                self.index[fn.qual] = fn
            for cname, cls in mod.classes.items():
                for mname, node in cls.methods.items():
                    fn = FuncInfo(mod, cname, mname, node)
                    self.index[fn.qual] = fn

    @classmethod
    def of(cls, files: Sequence[SourceFile]) -> "Program":
        key = tuple(id(f.tree) for f in files)
        hit = _PROGRAM_CACHE.get(key)
        if hit is None:
            hit = _PROGRAM_CACHE[key] = cls(files)
        return hit

    # -- resolution (deliberately conservative: unresolvable targets
    # are not followed rather than guessed) ------------------------------
    def resolve_call(self, fn: FuncInfo, cn: str) -> Optional[FuncInfo]:
        if not cn:
            return None
        parts = cn.split(".")
        mod = fn.mod
        if parts[0] == "self" and len(parts) == 2 and fn.cls:
            return self.resolve_method(mod, fn.cls, parts[1])
        if len(parts) == 1:
            if parts[0] in mod.funcs:
                return FuncInfo(mod, None, parts[0], mod.funcs[parts[0]])
            fi = mod.from_imports.get(parts[0])
            if fi:
                src = self.mods.get(fi[0])
                if src and fi[1] in src.funcs:
                    return FuncInfo(src, None, fi[1], src.funcs[fi[1]])
            return None
        if len(parts) == 2:
            target_mod = self.mods.get(mod.imports.get(parts[0], ""))
            if target_mod is None:
                # module alias: `from pkg import mod as alias`
                fi = mod.from_imports.get(parts[0])
                if fi:
                    target_mod = self.mods.get(f"{fi[0]}.{fi[1]}")
            if target_mod and parts[1] in target_mod.funcs:
                return FuncInfo(target_mod, None, parts[1],
                                target_mod.funcs[parts[1]])
        return None

    def resolve_method(self, mod: Module, cname: str, mname: str,
                       depth: int = 0) -> Optional[FuncInfo]:
        if depth > 8:
            return None
        cls = mod.classes.get(cname)
        if cls is None:
            return None
        if mname in cls.methods:
            return FuncInfo(mod, cname, mname, cls.methods[mname])
        for base in cls.bases:
            bname = base.split(".")[-1]
            if bname in mod.classes and bname != cname:
                hit = self.resolve_method(mod, bname, mname, depth + 1)
                if hit is not None:
                    return hit
            fi = mod.from_imports.get(bname)
            if fi:
                src = self.mods.get(fi[0])
                if src and fi[1] in src.classes:
                    hit = self.resolve_method(src, fi[1], mname,
                                              depth + 1)
                    if hit is not None:
                        return hit
        return None

    def edges(self, fn: FuncInfo) -> List[FuncInfo]:
        out: List[FuncInfo] = []
        for node in body_walk(fn.node):
            if isinstance(node, ast.Call):
                target = self.resolve_call(fn, call_name(node))
                if target is not None:
                    out.append(target)
        return out


# -- the role engine ---------------------------------------------------------

_MODEL_CACHE: Dict[Tuple[int, ...], "ThreadModel"] = {}


class ThreadModel:
    """Role roots + per-role reachability with parent pointers (for
    example chains in violation messages)."""

    def __init__(self, program: Program) -> None:
        self.program = program
        # role -> root qual -> why (spawn-site description)
        self.roots: Dict[str, Dict[str, str]] = {r: {} for r in ALL_ROLES}
        self._find_roots()
        # role -> {qual: parent qual or None for roots}
        self.reach: Dict[str, Dict[str, Optional[str]]] = {}
        for role in ALL_ROLES:
            self.reach[role] = self._propagate(self.roots[role])

    @classmethod
    def of(cls, files: Sequence[SourceFile]) -> "ThreadModel":
        key = tuple(id(f.tree) for f in files)
        hit = _MODEL_CACHE.get(key)
        if hit is None:
            hit = _MODEL_CACHE[key] = cls(Program.of(files))
        return hit

    # -- queries ----------------------------------------------------------
    def roles_of(self, qual: str) -> Set[str]:
        out = {r for r in ALL_ROLES if qual in self.reach[r]}
        return out or {ROLE_MAIN}

    def chain(self, role: str, qual: str) -> List[str]:
        """Example call chain root..qual as local names."""
        parent = self.reach[role]
        names: List[str] = []
        cur: Optional[str] = qual
        while cur is not None:
            fn = self.program.index.get(cur)
            names.append(fn.local if fn is not None else cur)
            cur = parent.get(cur)
        names.reverse()
        return names

    # -- roots ------------------------------------------------------------
    def _add_root(self, role: str, qual: str, why: str) -> None:
        if qual in self.program.index:
            self.roots[role].setdefault(qual, why)

    def _find_roots(self) -> None:
        prog = self.program
        for role, qual in _FIXED_ROOTS:
            self._add_root(role, qual, "lane entry point")
        for fn in prog.index.values():
            if isinstance(fn.node, ast.AsyncFunctionDef):
                self._add_root(ROLE_LOOP, fn.qual, "async def")
        # fast-dispatching classes: their ms_dispatch runs inline on
        # the messenger event loop
        for mod in prog.mods.values():
            for cname, cls in mod.classes.items():
                can = cls.methods.get("ms_can_fast_dispatch")
                if can is None or returns_false_only(can):
                    continue
                disp = prog.resolve_method(mod, cname, "ms_dispatch")
                if disp is not None:
                    self._add_root(ROLE_LOOP, disp.qual,
                                   f"{cname}.ms_can_fast_dispatch")
        # registration sites: walk FULL bodies (lambdas and nested
        # defs included — a registration inside a closure is still a
        # registration once the closure runs)
        for fn in list(prog.index.values()):
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Call):
                    self._scan_registration(fn, node)

    def _scan_registration(self, fn: FuncInfo, node: ast.Call) -> None:
        cn = call_name(node)
        base = cn.split(".")[-1]
        site = f"{fn.local}:{node.lineno}"

        def resolve(arg: Optional[ast.AST]) -> Optional[FuncInfo]:
            if arg is None:
                return None
            return self.program.resolve_call(fn, dotted(arg))

        # loop-scheduled callbacks
        arg = None
        if base in _SCHED_ARG0 and node.args:
            arg = node.args[0]
        elif base in _SCHED_ARG1 and len(node.args) > 1:
            arg = node.args[1]
        t = resolve(arg)
        if t is not None:
            self._add_root(ROLE_LOOP, t.qual, f"scheduled at {site}")
            return

        # ad-hoc threads: target= names the lane's entry
        if base == "Thread":
            target = None
            tname = ""
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
                elif kw.arg == "name" and isinstance(
                        kw.value, (ast.Constant, ast.JoinedStr)):
                    tname = ast.unparse(kw.value)
            t = resolve(target)
            if t is not None:
                role = (ROLE_TIMER
                        if (_TIMER_NAME_RE.search(t.name)
                            or _TIMER_NAME_RE.search(tname))
                        else ROLE_THREAD)
                self._add_root(role, t.qual, f"Thread() at {site}")
            return

        # sharded work queue: the process callback runs on shard
        # workers; so do items enqueued via wq.queue(token, item)
        if base == "ShardedWorkQueue":
            target = None
            if len(node.args) > 2:
                target = node.args[2]
            for kw in node.keywords:
                if kw.arg == "process":
                    target = kw.value
            t = resolve(target)
            if t is not None:
                self._add_root(ROLE_SHARD, t.qual, f"process= at {site}")
            return
        if base == "queue" and len(node.args) > 1:
            owner = cn.split(".")[-2] if "." in cn else ""
            if "wq" in owner:
                t = resolve(node.args[1])
                if t is not None:
                    self._add_root(ROLE_SHARD, t.qual,
                                   f"wq.queue at {site}")
            return

        # commit pipeline: ctor sync_fn + every on_commit completion
        if base == "CommitPipeline" and node.args:
            t = resolve(node.args[0])
            if t is not None:
                self._add_root(ROLE_COMMIT, t.qual, f"sync_fn at {site}")
            return
        for kw in node.keywords:
            if kw.arg == "on_commit":
                t = resolve(kw.value)
                if t is not None:
                    self._add_root(ROLE_COMMIT, t.qual,
                                   f"on_commit= at {site}")

        # executor fan-out vs pipeline.submit(seq, cb)
        if base == "submit" and node.args:
            owner = cn.split(".")[-2] if "." in cn else ""
            if "pipeline" in owner:
                if len(node.args) > 1:
                    t = resolve(node.args[1])
                    if t is not None:
                        self._add_root(ROLE_COMMIT, t.qual,
                                       f"pipeline.submit at {site}")
            else:
                t = resolve(node.args[0])
                if t is not None:
                    self._add_root(ROLE_FANOUT, t.qual,
                                   f"submit at {site}")
            return

        # future callbacks: stripe futures resolve on the device
        # worker (set_result runs registered callbacks inline)
        if base == "add_done_callback" and node.args:
            t = resolve(node.args[0])
            if t is not None:
                self._add_root(ROLE_DEVICE, t.qual,
                               f"add_done_callback at {site}")

    # -- propagation ------------------------------------------------------
    def _propagate(self, roots: Dict[str, str]
                   ) -> Dict[str, Optional[str]]:
        prog = self.program
        parent: Dict[str, Optional[str]] = {q: None for q in roots}
        frontier = list(roots)
        while frontier:
            q = frontier.pop()
            fn = prog.index.get(q)
            if fn is None:
                continue
            for callee in prog.edges(fn):
                if callee.qual not in parent:
                    parent[callee.qual] = q
                    frontier.append(callee.qual)
        return parent
