"""cephlint framework: file discovery, AST cache, violations, baseline.

Checks are whole-program: each receives the full list of parsed
``SourceFile``s so cross-module analyses (the fast-dispatch call
graph, codec pairing) see everything at once.  Files are parsed once
per process and shared across every check — the CLI and the tier-1
test both lint ~120 files with six checks in well under the 30 s
budget because the parse happens once, not once per check.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# (abspath, content-sha1) -> (tree, text, parse_error); the test and
# the CLI each run in one process, so an in-proc cache is the whole
# caching story — but it also makes repeated programmatic runs (unit
# tests exercising individual checks) free.  Keyed by CONTENT, not
# (mtime, size): a same-size rewrite inside the kernel's mtime
# granularity (test fixtures do exactly this) must never serve the
# stale tree, and reading+hashing ~140 files costs milliseconds.
_AST_CACHE: Dict[Tuple[str, str],
                 Tuple[ast.AST, str, Optional[Tuple[int, str]]]] = {}

_SUPPRESS_RE = re.compile(r"#\s*cephlint:\s*disable=([\w,-]+)")


@dataclass(frozen=True)
class Violation:
    check: str      # check name, e.g. "named-locks"
    path: str       # repo-relative posix path
    line: int       # 1-based
    scope: str      # enclosing qualname ("Class.method") or "<module>"
    detail: str     # stable discriminator within the scope
    message: str    # human-readable description

    @property
    def key(self) -> str:
        """Baseline key: line-number-free so unrelated edits above a
        baselined violation don't un-suppress it."""
        return f"{self.check}::{self.path}::{self.scope}::{self.detail}"

    def to_dict(self) -> dict:
        return {
            "check": self.check, "path": self.path, "line": self.line,
            "scope": self.scope, "detail": self.detail,
            "message": self.message, "key": self.key,
        }


class SourceFile:
    """One parsed module plus the bookkeeping checks need."""

    def __init__(self, abspath: str, rel: str) -> None:
        import hashlib

        self.abspath = abspath
        self.rel = rel  # repo-relative, posix separators
        with open(abspath, "rb") as f:
            raw = f.read()
        cache_key = (abspath, hashlib.sha1(raw).hexdigest())
        hit = _AST_CACHE.get(cache_key)
        if hit is None:
            text = raw.decode("utf-8")
            # a file THIS interpreter cannot parse cannot run on it
            # either (the repo once shipped a tool in 3.12-only
            # syntax): surface as a finding, not a linter crash
            err = None
            try:
                tree = ast.parse(text, filename=rel)
            except SyntaxError as e:
                tree = ast.parse("", filename=rel)
                err = (e.lineno or 1, e.msg or "syntax error")
            _AST_CACHE[cache_key] = (tree, text, err)
            hit = _AST_CACHE[cache_key]
        self.tree, self.text, self.parse_error = hit
        self.lines = self.text.splitlines()
        # line -> set of check names disabled on that line
        self._suppress: Dict[int, set] = {}
        for i, ln in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(ln)
            if m:
                self._suppress[i] = {c.strip() for c in m.group(1).split(",")}

    def suppressed(self, check: str, line: int) -> bool:
        """True if `# cephlint: disable=<check>` annotates the line or
        the contiguous comment block directly above it (rationales are
        encouraged to span lines)."""
        def hit(ln: int) -> bool:
            names = self._suppress.get(ln)
            return bool(names and (check in names or "all" in names))

        if hit(line):
            return True
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].strip().startswith("#"):
            if hit(ln):
                return True
            ln -= 1
        return False

    def __repr__(self) -> str:
        return f"SourceFile({self.rel})"


class Check:
    """Base class.  `scopes` limits which top-level dirs a check sees
    ("ceph_tpu", "tools"); `run` gets every file in scope at once."""

    name = ""
    description = ""
    scopes: Tuple[str, ...] = ("ceph_tpu",)

    def run(self, files: Sequence[SourceFile]) -> List[Violation]:
        raise NotImplementedError


# -- discovery ---------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", "scratch", "csrc", "tests"}


def repo_root(start: Optional[str] = None) -> str:
    """The directory holding ceph_tpu/ — walk up from this module."""
    d = start or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return d


def discover_files(root: Optional[str] = None,
                   subdirs: Iterable[str] = ("ceph_tpu", "tools"),
                   ) -> List[SourceFile]:
    root = repo_root(root)
    out: List[SourceFile] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                abspath = os.path.join(dirpath, fn)
                rel = os.path.relpath(abspath, root).replace(os.sep, "/")
                out.append(SourceFile(abspath, rel))
    return out


def run_checks(files: Sequence[SourceFile],
               checks: Sequence[Check]) -> List[Violation]:
    """Run every check, drop inline-suppressed hits, sort stably."""
    by_rel = {f.rel: f for f in files}
    out: List[Violation] = []
    for f in files:
        if f.parse_error is not None:
            line, msg = f.parse_error
            out.append(Violation(
                check="parse-error", path=f.rel, line=line,
                scope="<module>", detail="syntax",
                message=(f"not parseable by this interpreter: {msg} — "
                         "the file cannot run here either"),
            ))
    for chk in checks:
        in_scope = [f for f in files
                    if f.rel.split("/", 1)[0] in chk.scopes]
        for v in chk.run(in_scope):
            src = by_rel.get(v.path)
            if src is not None and src.suppressed(v.check, v.line):
                continue
            out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.check, v.detail))
    return out


# -- baseline ----------------------------------------------------------------

# (check-name, repo-relative path prefix) pairs whose violations are
# NEVER baselineable: --write-baseline refuses to record them, so they
# always surface as new (checks register their hard-error scopes here
# at import — e.g. no-d2h-on-hot-path over the device-path modules)
NEVER_BASELINE_PREFIXES: List[Tuple[str, str]] = []


def baseline_eligible(v: "Violation") -> bool:
    return not any(v.check == c and v.path.startswith(p)
                   for c, p in NEVER_BASELINE_PREFIXES)


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in data.get("entries", {}).items()}


def violations_to_baseline(violations: Sequence[Violation]) -> dict:
    counts: Dict[str, int] = {}
    for v in violations:
        if not baseline_eligible(v):
            continue  # hard-error scope: never accepted as debt
        counts[v.key] = counts.get(v.key, 0) + 1
    return {
        "comment": (
            "cephlint suppressions baseline — existing debt, recorded. "
            "New violations (any key whose live count exceeds its entry "
            "here) fail tier-1 via tests/test_lint.py. Regenerate with "
            "`python tools/cephlint.py --write-baseline` ONLY when "
            "intentionally accepting new debt; shrink it by fixing "
            "violations and regenerating."
        ),
        "entries": {k: counts[k] for k in sorted(counts)},
    }


def new_violations(violations: Sequence[Violation],
                   baseline: Dict[str, int]) -> List[Violation]:
    """Violations beyond the baselined count for their key.

    Within one key the newest-looking instances (highest line) are
    reported first-as-new; the baselined allowance covers the rest."""
    by_key: Dict[str, List[Violation]] = {}
    for v in violations:
        by_key.setdefault(v.key, []).append(v)
    out: List[Violation] = []
    for key, group in by_key.items():
        allowed = baseline.get(key, 0)
        if len(group) <= allowed:
            continue
        group.sort(key=lambda v: v.line)
        out.extend(group[allowed:])
    out.sort(key=lambda v: (v.path, v.line, v.check, v.detail))
    return out


# -- shared AST helpers ------------------------------------------------------

_QUAL_CACHE: Dict[int, Dict[ast.AST, str]] = {}


def qualname_index(tree: ast.AST) -> Dict[ast.AST, str]:
    """Map every function/class node to its dotted qualname.  Cached
    per tree: enclosing_scope() is called once per violation and the
    re-index dominated the suite's runtime before caching."""
    hit = _QUAL_CACHE.get(id(tree))
    if hit is not None:
        return hit
    out: Dict[ast.AST, str] = {}

    def walk(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qn = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = qn
                walk(child, qn)
            else:
                walk(child, prefix)

    walk(tree, "")
    # safe to key by id(): trees live forever in _AST_CACHE, so ids
    # are never recycled within a process
    _QUAL_CACHE[id(tree)] = out
    return out


def enclosing_scope(tree: ast.AST, line: int) -> str:
    """Qualname of the innermost def/class containing `line`."""
    best = "<module>"
    best_span = None
    for node, qn in qualname_index(tree).items():
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end:
            span = end - node.lineno
            if best_span is None or span <= best_span:
                best, best_span = qn, span
    return best


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target, best-effort ("self.foo", "time.sleep",
    "open"); empty for computed targets."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        inner = dotted(node.func)
        parts.append(f"{inner}()" if inner else "()")
    elif parts:
        parts.append("?")
    else:
        return ""
    return ".".join(reversed(parts))
