"""ObjectStore — the abstract transactional object API.

Reference: src/os/ObjectStore.h + src/os/Transaction.cc. The contract
the OSD's PG engine is written against: named collections (one per PG)
holding objects with byte extents, xattrs, and an omap; all mutations
batched into atomic, ordered Transactions; reads are unordered.

A Transaction is an encodable op list (the reference's op codes at
src/os/ObjectStore.h Transaction::OP_*) so the same bytes can be
carried inside replication messages (the EC sub-write payload) and
replayed from the journal — exactly how the reference ships
transactions to replica shards.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ceph_tpu.core.crc import crc32c
from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.core.failpoint import failpoint


class StoreError(Exception):
    pass


class NoSuchObject(StoreError):
    pass


class NoSuchCollection(StoreError):
    pass


class ChecksumError(StoreError):
    """Bytes a read would serve failed at-rest checksum verification.

    Raised by the base-class read gate (per-extent seals, any backend)
    and by BlockStore's per-block device crc.  Consumers must treat the
    local copy as LOST — reconstruct/repair, never serve or EIO the
    flipped bytes upward."""


@dataclass(frozen=True, order=True)
class GHObject:
    """Object id within a collection (hobject_t/ghobject_t analog:
    reference src/common/hobject.h — name + key hash + snap + shard)."""

    name: str
    snap: int = -2  # -2 = head (CEPH_NOSNAP analog)
    shard: int = -1  # -1 = no shard (replicated); >=0 = EC shard id

    def encode(self, e: Encoder) -> None:
        e.string(self.name).s64(self.snap).s32(self.shard)

    @classmethod
    def decode(cls, d: Decoder) -> "GHObject":
        return cls(d.string(), d.s64(), d.s32())


@dataclass(frozen=True, order=True)
class Collection:
    """Collection id — one per PG (+ metadata col), e.g. '2.1f_head'."""

    name: str

    def encode(self, e: Encoder) -> None:
        e.string(self.name)

    @classmethod
    def decode(cls, d: Decoder) -> "Collection":
        return cls(d.string())


META_COLL = Collection("meta")

# Transaction op codes (subset of reference OP_* that the PG engine uses)
OP_NOP = 0
OP_TOUCH = 1
OP_WRITE = 2
OP_ZERO = 3
OP_TRUNCATE = 4
OP_REMOVE = 5
OP_SETATTRS = 6
OP_RMATTR = 7
OP_CLONE = 8
OP_MKCOLL = 9
OP_RMCOLL = 10
OP_OMAP_SETKEYS = 11
OP_OMAP_RMKEYS = 12
OP_OMAP_CLEAR = 13
OP_COLL_MOVE_RENAME = 14
OP_TRY_REMOVE = 15  # remove tolerating absence (for replica-shipped txns)


@dataclass
class Op:
    op: int
    cid: Collection
    oid: Optional[GHObject] = None
    off: int = 0
    length: int = 0
    data: bytes = b""
    attrs: Dict[str, bytes] = field(default_factory=dict)
    keys: List[str] = field(default_factory=list)
    dest_cid: Optional[Collection] = None
    dest_oid: Optional[GHObject] = None

    def encode(self, e: Encoder) -> None:
        e.start(1, 1)
        e.u8(self.op)
        self.cid.encode(e)
        e.optional(self.oid, lambda enc, o: o.encode(enc))
        # blob() materializes DeviceBuf payloads via their sanctioned
        # (accounted) wire view
        e.u64(self.off).u64(self.length).blob(self.data)
        e.mapping(self.attrs, lambda enc, k: enc.string(k),
                  lambda enc, v: enc.blob(v))
        e.seq(self.keys, lambda enc, k: enc.string(k))
        e.optional(self.dest_cid, lambda enc, c: c.encode(enc))
        e.optional(self.dest_oid, lambda enc, o: o.encode(enc))
        e.finish()

    @classmethod
    def decode(cls, d: Decoder) -> "Op":
        d.start(1)
        out = cls(
            op=d.u8(),
            cid=Collection.decode(d),
            oid=d.optional(GHObject.decode),
            off=d.u64(),
            length=d.u64(),
            data=d.blob(),
            attrs=d.mapping(lambda dd: dd.string(), lambda dd: dd.blob()),
            keys=d.seq(lambda dd: dd.string()),
            dest_cid=d.optional(Collection.decode),
            dest_oid=d.optional(GHObject.decode),
        )
        d.end()
        return out


class Transaction:
    """Atomic batch of mutations; encodable for journal + replication."""

    def __init__(self) -> None:
        self.ops: List[Op] = []

    def __len__(self) -> int:
        return len(self.ops)

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)

    # -- builders ---------------------------------------------------------
    def touch(self, cid: Collection, oid: GHObject) -> None:
        self.ops.append(Op(OP_TOUCH, cid, oid))

    def write(self, cid: Collection, oid: GHObject, off: int, data) -> None:
        """`data` may be bytes-like OR a DeviceBuf payload handle: the
        handle rides the op list un-materialized (bufferlist role) and
        becomes host bytes only at a sanctioned sink — store apply
        (`op_payload`) or wire serialization (`Op.encode`)."""
        if hasattr(data, "wire_view"):  # DeviceBuf: keep the handle
            self.ops.append(Op(OP_WRITE, cid, oid, off=off,
                               length=len(data), data=data))
            return
        self.ops.append(Op(OP_WRITE, cid, oid, off=off, length=len(data),
                           data=bytes(data)))

    def zero(self, cid: Collection, oid: GHObject, off: int, length: int) -> None:
        self.ops.append(Op(OP_ZERO, cid, oid, off=off, length=length))

    def truncate(self, cid: Collection, oid: GHObject, size: int) -> None:
        self.ops.append(Op(OP_TRUNCATE, cid, oid, off=size))

    def remove(self, cid: Collection, oid: GHObject) -> None:
        self.ops.append(Op(OP_REMOVE, cid, oid))

    def try_remove(self, cid: Collection, oid: GHObject) -> None:
        """Remove if present; no-op otherwise.  Replication ships
        primary-built transactions to replicas whose local existence may
        lag, so deletes must tolerate absence."""
        self.ops.append(Op(OP_TRY_REMOVE, cid, oid))

    def setattrs(self, cid: Collection, oid: GHObject, attrs: Dict[str, bytes]) -> None:
        self.ops.append(Op(OP_SETATTRS, cid, oid, attrs=dict(attrs)))

    def rmattr(self, cid: Collection, oid: GHObject, name: str) -> None:
        self.ops.append(Op(OP_RMATTR, cid, oid, keys=[name]))

    def clone(self, cid: Collection, src: GHObject, dst: GHObject) -> None:
        self.ops.append(Op(OP_CLONE, cid, src, dest_oid=dst))

    def create_collection(self, cid: Collection) -> None:
        self.ops.append(Op(OP_MKCOLL, cid))

    def remove_collection(self, cid: Collection) -> None:
        self.ops.append(Op(OP_RMCOLL, cid))

    def omap_setkeys(self, cid: Collection, oid: GHObject,
                     kv: Dict[str, bytes]) -> None:
        self.ops.append(Op(OP_OMAP_SETKEYS, cid, oid, attrs=dict(kv)))

    def omap_rmkeys(self, cid: Collection, oid: GHObject, keys: List[str]) -> None:
        self.ops.append(Op(OP_OMAP_RMKEYS, cid, oid, keys=list(keys)))

    def omap_clear(self, cid: Collection, oid: GHObject) -> None:
        self.ops.append(Op(OP_OMAP_CLEAR, cid, oid))

    def coll_move_rename(self, src_cid: Collection, src: GHObject,
                         dst_cid: Collection, dst: GHObject) -> None:
        self.ops.append(Op(OP_COLL_MOVE_RENAME, src_cid, src,
                           dest_cid=dst_cid, dest_oid=dst))

    # -- wire -------------------------------------------------------------
    def encode(self, e: Encoder) -> None:
        e.start(1, 1)
        e.seq(self.ops, lambda enc, op: op.encode(enc))
        e.finish()

    @classmethod
    def decode(cls, d: Decoder) -> "Transaction":
        d.start(1)
        t = cls()
        t.ops = d.seq(Op.decode)
        d.end()
        return t

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Transaction":
        return cls.decode(Decoder(data))


def op_payload(op: Op, copy: bool = False):
    """A write op's payload as a host buffer for the store's apply —
    THE sanctioned materialization point of a device-resident payload
    (accounted by the DeviceBuf itself; see ceph_tpu/tpu/staging.py
    ownership rules).  ``copy=True`` for backends that RETAIN the
    buffer (blob stores): a view into a staging slot must never
    outlive the slot's release."""
    d = op.data
    if hasattr(d, "wire_view"):
        v = d.wire_view()
        return bytes(v) if copy else v
    return d


class ValidationOverlay:
    """Lazy existence overlay for validate-then-apply transactions.

    Subclasses provide base-state lookups (`_base_coll`, `_base_obj`,
    `_base_count`); the overlay layers this transaction's pending
    effects on top WITHOUT materializing the store (each op validates in
    O(1); only RMCOLL's emptiness check pays a per-collection count, and
    only when an RMCOLL actually appears in the transaction)."""

    def __init__(self) -> None:
        self._colls: Dict[str, bool] = {}
        self._objs: Dict[Tuple[str, GHObject], bool] = {}
        self._count_delta: Dict[str, int] = {}
        self._fresh: Dict[str, bool] = {}  # created in this txn => base 0

    # -- base state hooks --------------------------------------------------
    def _base_coll(self, name: str) -> bool:
        raise NotImplementedError

    def _base_obj(self, name: str, oid: GHObject) -> bool:
        raise NotImplementedError

    def _base_count(self, name: str) -> int:
        raise NotImplementedError

    # -- overlay queries ---------------------------------------------------
    def coll_exists(self, name: str) -> bool:
        if name in self._colls:
            return self._colls[name]
        return self._base_coll(name)

    def obj_exists(self, name: str, oid: GHObject) -> bool:
        key = (name, oid)
        if key in self._objs:
            return self._objs[key]
        return self._base_obj(name, oid)

    def coll_empty(self, name: str) -> bool:
        base = 0 if self._fresh.get(name) else self._base_count(name)
        return base + self._count_delta.get(name, 0) <= 0

    # -- overlay mutations -------------------------------------------------
    def add_coll(self, name: str) -> None:
        self._colls[name] = True
        self._fresh[name] = True
        self._count_delta[name] = 0

    def rm_coll(self, name: str) -> None:
        self._colls[name] = False

    def create_obj(self, name: str, oid: GHObject) -> None:
        if not self.obj_exists(name, oid):
            self._objs[(name, oid)] = True
            self._count_delta[name] = self._count_delta.get(name, 0) + 1

    def rm_obj(self, name: str, oid: GHObject) -> None:
        if self.obj_exists(name, oid):
            self._objs[(name, oid)] = False
            self._count_delta[name] = self._count_delta.get(name, 0) - 1


def validate_op(op: Op, ov: ValidationOverlay) -> None:
    """Shared validation pass giving queue_transaction all-or-nothing
    semantics: raise exactly the errors apply would, before any backend
    mutates."""
    code = op.op
    cname = op.cid.name

    def need_coll():
        if not ov.coll_exists(cname):
            raise NoSuchCollection(cname)

    def need_obj():
        need_coll()
        if not ov.obj_exists(cname, op.oid):
            raise NoSuchObject(f"{cname}/{op.oid.name}")

    if code == OP_NOP:
        return
    if code == OP_MKCOLL:
        if ov.coll_exists(cname):
            raise StoreError(f"collection exists: {cname}")
        ov.add_coll(cname)
        return
    if code == OP_RMCOLL:
        need_coll()
        if not ov.coll_empty(cname):
            raise StoreError(f"collection not empty: {cname}")
        ov.rm_coll(cname)
        return
    if code in (OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE, OP_SETATTRS,
                OP_OMAP_SETKEYS):
        need_coll()
        ov.create_obj(cname, op.oid)
        return
    if code in (OP_REMOVE,):
        need_obj()
        ov.rm_obj(cname, op.oid)
        return
    if code == OP_TRY_REMOVE:
        need_coll()
        ov.rm_obj(cname, op.oid)
        return
    if code in (OP_RMATTR, OP_OMAP_RMKEYS, OP_OMAP_CLEAR):
        need_obj()
        return
    if code == OP_CLONE:
        need_obj()
        ov.create_obj(cname, op.dest_oid)
        return
    if code == OP_COLL_MOVE_RENAME:
        need_obj()
        if not ov.coll_exists(op.dest_cid.name):
            raise NoSuchCollection(op.dest_cid.name)
        ov.rm_obj(cname, op.oid)
        ov.create_obj(op.dest_cid.name, op.dest_oid)
        return
    raise StoreError(f"unknown op {code}")


class CommitPipeline:
    """Group-commit thread shared by the durable backends — the
    FileJournal group-commit / BlueStore `_kv_sync_thread` role.

    Submitters append their completion to the in-memory pending batch
    and return; the commit thread swaps the whole batch out (double
    buffering: batch N+1 collects while batch N syncs), runs the
    store's `sync_fn` ONCE for everything in it, then fires the
    completions in submission (WAL-seq) order.  A 16-deep writer queue
    therefore pays one fsync per BATCH, not one per transaction, and
    callers with no callback block on an event submitted through the
    same pipeline — so concurrent synchronous writers share fsyncs too.

    `freeze()`/`thaw()` hold the commit thread between WAL append and
    the batched sync: the crash-safety tests use the window to model a
    kill mid-batch (records appended, nothing fsynced, no completion
    fired).
    """

    def __init__(self, sync_fn: Callable[[], None],
                 perf=None, log: Optional[Callable[[str], None]] = None
                 ) -> None:
        self._sync_fn = sync_fn
        self._perf = perf  # PerfCounters with commit_batch/commit_lat
        self._log = log or (lambda s: print(f"store-commit: {s}",
                                            file=sys.stderr))
        self._cond = threading.Condition()
        self._pending: List[Tuple[int, Callable[[], None]]] = []
        self._frozen = False
        self._stopping = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="store-commit", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        """Drain every pending completion (final sync included), then
        join the thread — the umount path."""
        with self._cond:
            if self._thread is None:
                return
            self._frozen = False
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
        self._thread = None

    def in_commit_thread(self) -> bool:
        return threading.current_thread() is self._thread

    # -- crash-window test hook -------------------------------------------
    def freeze(self) -> None:
        with self._cond:
            self._frozen = True

    def thaw(self) -> None:
        with self._cond:
            self._frozen = False
            self._cond.notify_all()

    # -- submission -------------------------------------------------------
    def submit(self, seq: int, on_commit: Callable[[], None]) -> None:
        """Stage a completion.  Callers submit while still holding the
        store lock that ordered their WAL append, so the pending list
        order IS WAL order.  A submit racing stop() (writer vs umount)
        commits inline rather than stranding the completion forever."""
        with self._cond:
            if self._thread is not None and not self._stopping:
                self._pending.append((seq, on_commit))
                self._cond.notify_all()
                return
        try:
            self._sync_fn()
        except Exception as e:
            self._log(f"inline sync during stop failed: {e!r}")
        on_commit()

    def flush(self) -> None:
        """Block until everything submitted so far has committed."""
        done = threading.Event()
        self.submit(-1, done.set)
        done.wait()

    # -- the commit thread ------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: (self._pending and not self._frozen)
                    or self._stopping)
                if self._stopping and (not self._pending or self._frozen):
                    return
                batch, self._pending = self._pending, []
            # the WAL-appended-nothing-synced kill window: a schedule
            # can hold/kill here to model a crash mid-batch
            failpoint("store.commit_batch.sync", n=len(batch))
            t0 = time.perf_counter()
            try:
                self._sync_fn()
            except Exception as e:
                # a failing sync must not strand submitters (there is
                # no error channel on on_commit); the store's state is
                # applied, durability degrades to wal_sync=False level
                # — but degraded durability must be LOUD
                self._log(f"batch sync failed: {e!r} (completions "
                          "fire; durability degraded this batch)")
            for _seq, cb in batch:
                try:
                    cb()
                except Exception as e:
                    # one completion's bug must not starve the rest
                    self._log(f"on_commit callback raised: {e!r}")
            if self._perf is not None:
                self._perf.hinc("commit_batch", len(batch))
                self._perf.tinc("commit_lat", time.perf_counter() - t0)


# extent-seal granularity (conf store_csum_extent_kib): the BlueStore
# csum_order analog — one crc32c per DEFAULT_EXTENT_SIZE bytes of
# logical object space, sealed at write time, verified at read time
DEFAULT_EXTENT_SIZE = 64 * 1024


class ExtentSeals:
    """Per-extent at-rest checksum record for one object.

    Extent i covers logical bytes [i*E, min((i+1)*E, size)) — the tail
    extent seals only the bytes that exist, so the record pins the
    object's extent count (and thereby its size class) as well as its
    content.  Versioned encoding per the dencoder discipline: a v2 may
    append fields; v1 decoders skip the unknown tail."""

    __slots__ = ("extent_size", "crcs")

    def __init__(self, extent_size: int = DEFAULT_EXTENT_SIZE,
                 crcs: Optional[List[int]] = None) -> None:
        self.extent_size = extent_size
        self.crcs: List[int] = list(crcs) if crcs else []

    def encode(self, e: Encoder) -> None:
        e.start(1, 1)
        e.u32(self.extent_size)
        e.seq(self.crcs, lambda enc, c: enc.u32(c))
        e.finish()

    @classmethod
    def decode(cls, d: Decoder) -> "ExtentSeals":
        d.start(1)
        s = cls(d.u32(), d.seq(lambda dd: dd.u32()))
        d.end()
        return s

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "ExtentSeals":
        return cls.decode(Decoder(data))


class _SealMark:
    """Seal work one Transaction implies for one object: the union of
    dirtied logical byte ranges, or a whole-record verdict (full
    recompute / record drop)."""

    __slots__ = ("lo", "hi", "full", "drop", "fresh")

    def __init__(self) -> None:
        self.lo: Optional[int] = None
        self.hi = 0
        self.full = False   # recompute every extent from current bytes
        self.drop = False   # object removed: delete the seal record
        self.fresh = False  # pre-txn record is dead (remove+recreate)

    def dirty(self, lo: int, hi: int) -> None:
        if self.drop:
            # removed then recreated within the txn: the old record
            # describes a dead object — recompute from scratch
            self.drop = False
            self.fresh = True
            self.full = True
        self.lo = lo if self.lo is None else min(self.lo, lo)
        self.hi = max(self.hi, hi)

    def wipe(self) -> None:
        self.lo = None
        self.hi = 0
        self.full = False
        self.fresh = False
        self.drop = True


class ObjectStore:
    """Abstract backend. Writes go through queue_transaction; reads are
    direct.  `queue_transaction(t, on_commit)` validates and applies
    synchronously (read-your-writes holds on return) but DEFERS
    durability: `on_commit` fires from the backend's commit thread once
    the transaction is on stable storage, and many transactions ride
    one sync (group commit).  With no callback the call blocks until
    commit — the pre-async semantics — while still sharing the batched
    sync with concurrent writers.  Returns the transaction's WAL/commit
    sequence number."""

    # True on backends that ADDITIONALLY verify stored pages against
    # device-level checksums inside _read_span (BlockStore: crc32c per
    # 4KiB block — the disk-ECC analog).  Every backend now verifies
    # the bytes it SERVES against per-extent seals in the base read()
    # gate below, so this flag only records the extra device layer.
    checksums_at_rest = False

    # -- per-extent at-rest checksums (the BlueStore csum discipline) ----
    # Writes seal crc32c per csum_extent_size bytes of logical object
    # space into object metadata WITHIN the writing transaction
    # (partial overwrites re-seal only touched extents); every read
    # verifies exactly the extents it serves and raises ChecksumError
    # on mismatch.  Both knobs are daemon-wired from conf
    # (store_csum_extent_kib / store_verify_read).
    csum_extent_size = DEFAULT_EXTENT_SIZE
    verify_reads = True

    # -- silent-corruption injection (the scrub/repair test seam) ---------
    # Two routes corrupt the bytes a read SERVES without touching what
    # is stored (silent at-rest rot, invisible to everything but a
    # byte-reading deep scrub):
    #   - the store.corrupt_chunk / store.corrupt_xattr failpoints
    #     (seeded, match-scoped — the chaos-schedule route), and
    #   - debug_inject_data_err marks (conf store_debug_inject_data_err
    #     enables the mechanism, like the PR 7 read-err hook) — the
    #     deterministic single-object route.  A REWRITE of a marked
    #     object clears its mark (the bad media got overwritten), so
    #     corrupt -> deep-scrub detect -> auto-repair -> clean re-scrub
    #     is a closed deterministic loop.
    debug_data_err_enabled = False

    def debug_inject_data_err(self, cid: Collection, oid: GHObject) -> None:
        if not hasattr(self, "_data_err_objs"):
            self._data_err_objs: set = set()
        self._data_err_objs.add((cid.name, oid.name, oid.shard))

    def debug_clear_data_err(self) -> None:
        if hasattr(self, "_data_err_objs"):
            self._data_err_objs.clear()

    def _note_data_write(self, cid: Collection, oid: GHObject) -> None:
        """Called by backends when an object's DATA is rewritten or the
        object removed: overwriting the media drops its data-err mark."""
        marks = getattr(self, "_data_err_objs", None)
        if marks:
            marks.discard((cid.name, oid.name, oid.shard))

    def _read_filter(self, data, cid: Collection, oid: GHObject):
        """The read-boundary corruption seam: every backend routes its
        read() return through here.  Disarmed cost is one enabled()
        check + one class-attr load."""
        from ceph_tpu.core import failpoint as fp

        if fp.enabled("store.corrupt_chunk") and fp.failpoint(
                "store.corrupt_chunk", oid=oid.name, coll=cid.name,
                shard=str(oid.shard)) is fp.CORRUPT:
            data = fp.corrupt_bytes(
                data, f"{cid.name}/{oid.name}/{oid.shard}")
        if self.debug_data_err_enabled:
            marks = getattr(self, "_data_err_objs", None)
            if marks and (cid.name, oid.name, oid.shard) in marks:
                data = fp.corrupt_bytes(
                    data, f"err/{cid.name}/{oid.name}/{oid.shard}")
        return data

    def _attr_filter(self, val, cid: Collection, oid: GHObject,
                     name: str):
        """getattr() twin of _read_filter (store.corrupt_xattr)."""
        from ceph_tpu.core import failpoint as fp

        if fp.enabled("store.corrupt_xattr") and fp.failpoint(
                "store.corrupt_xattr", oid=oid.name, coll=cid.name,
                shard=str(oid.shard), attr=name) is fp.CORRUPT:
            val = fp.corrupt_bytes(
                val, f"{cid.name}/{oid.name}/{oid.shard}/{name}")
        return val

    # -- lifecycle --------------------------------------------------------
    def mkfs(self) -> None:
        raise NotImplementedError

    def mount(self) -> None:
        raise NotImplementedError

    def umount(self) -> None:
        raise NotImplementedError

    # -- writes -----------------------------------------------------------
    def queue_transaction(self, t: Transaction,
                          on_commit: Optional[Callable[[], None]] = None
                          ) -> int:
        raise NotImplementedError

    def statfs(self) -> Tuple[int, int]:
        """(used_bytes, total_bytes) — the reference ObjectStore::statfs.
        Backends without a fixed device report a nominal capacity."""
        raise NotImplementedError

    # -- reads ------------------------------------------------------------
    def exists(self, cid: Collection, oid: GHObject) -> bool:
        raise NotImplementedError

    def read(self, cid: Collection, oid: GHObject, off: int = 0,
             length: int = 0) -> bytes:
        """length==0 → read to end.

        Concrete: THE verified-read gate.  Backends implement
        `_read_span` (one atomic snapshot of bytes + size + seal
        record); this method widens the request to extent-aligned
        coverage, routes the covering bytes through `_read_filter`
        (the injection seam sits BEFORE verification, so injected rot
        is caught here, at read time), verifies each covered extent
        against its seal, and only then slices out the requested
        range.  A mismatch bumps the store's `read_verify_fail`
        counter and raises ChecksumError — flipped bytes never leave
        the store."""
        E = self.csum_extent_size
        if not self.verify_reads:
            data, _size, _blob = self._read_span(cid, oid, off, length)
            return bytes(self._read_filter(data, cid, oid))
        cov_lo = (off // E) * E
        cov_len = (0 if length == 0
                   else ((off + length + E - 1) // E) * E - cov_lo)
        data, size, blob = self._read_span(cid, oid, cov_lo, cov_len)
        data = self._read_filter(data, cid, oid)
        if blob is not None:
            try:
                seals = ExtentSeals.from_bytes(blob)
            except Exception:
                self._verify_fail(cid, oid, "undecodable extent seals")
            if seals.extent_size != E:
                # sealed at a different granularity (extent-size conf
                # changed since the last write): verify whole-object at
                # the sealed granularity — rare, O(object) once
                data, size, _ = self._read_span(cid, oid, 0, 0)
                data = self._read_filter(data, cid, oid)
                self._verify_extents(data, 0, size, seals, cid, oid)
                end = size if length == 0 else min(size, off + length)
                return bytes(data[off:end])
            self._verify_extents(data, cov_lo, size, seals, cid, oid)
        lo = off - cov_lo
        if lo >= len(data):
            return b""
        return bytes(data[lo:] if length == 0 else data[lo:lo + length])

    def _read_span(self, cid: Collection, oid: GHObject, off: int,
                   length: int) -> Tuple[bytes, int, Optional[bytes]]:
        """One atomic snapshot serving the read gate: (bytes of
        [off, off+length) clipped to EOF — length==0 reads to end —,
        object size, encoded seal record or None).  Unfiltered and
        unverified; backends take their lock ONCE here so the bytes,
        the size, and the seals can never be torn against each other."""
        raise NotImplementedError

    def _verify_extents(self, data, base: int, size: int,
                        seals: ExtentSeals, cid: Collection,
                        oid: GHObject) -> None:
        """Verify the extents `data` (object bytes starting at logical
        offset `base`, extent-aligned) covers against their seals."""
        E = seals.extent_size
        n = len(seals.crcs)
        expect = (size + E - 1) // E
        if n != expect:
            self._verify_fail(
                cid, oid, f"seal count {n} != {expect} for size {size}")
        mv = memoryview(data) if not isinstance(data, memoryview) else data
        i0 = base // E
        covered = (len(data) + E - 1) // E
        for j in range(covered):
            i = i0 + j
            if i >= n:
                break
            if crc32c(mv[j * E:(j + 1) * E]) != seals.crcs[i]:
                self._verify_fail(cid, oid, f"extent {i} crc mismatch")

    def _verify_fail(self, cid: Collection, oid: GHObject,
                     why: str) -> None:
        pc = getattr(self, "perf", None)
        if pc is not None:
            pc.inc("read_verify_fail")
        raise ChecksumError(
            f"{cid.name}/{oid.name} shard {oid.shard}: {why}")

    # -- seal maintenance (called by backends inside txn apply) ----------
    def _seal_plan(self, t: Transaction, size_fn
                   ) -> Dict[Tuple[Collection, GHObject], _SealMark]:
        """Scan a validated Transaction for the seal work it implies.
        `size_fn(cid, oid) -> Optional[int]` reports PRE-apply sizes
        (None = absent); op-by-op size simulation keeps each dirty
        range tight — a partial overwrite re-seals only the extents it
        touches.  Backends call this BEFORE applying ops, apply, then
        feed each mark to `_seal_rebuild` with post-apply bytes —
        inside the same atomic scope as the data mutation."""
        marks: Dict[Tuple[Collection, GHObject], _SealMark] = {}
        sizes: Dict[Tuple[Collection, GHObject], int] = {}

        def size_of(cid, oid):
            k = (cid, oid)
            if k not in sizes:
                s = size_fn(cid, oid)
                sizes[k] = 0 if s is None else s
            return sizes[k]

        def mk(cid, oid):
            return marks.setdefault((cid, oid), _SealMark())

        for op in t.ops:
            code = op.op
            if code in (OP_WRITE, OP_ZERO):
                s = size_of(op.cid, op.oid)
                end = op.off + op.length
                # a write past EOF zero-fills the gap from old EOF
                mk(op.cid, op.oid).dirty(min(op.off, s), end)
                sizes[(op.cid, op.oid)] = max(s, end)
            elif code == OP_TRUNCATE:
                s = size_of(op.cid, op.oid)
                mk(op.cid, op.oid).dirty(min(op.off, s), max(op.off, s))
                sizes[(op.cid, op.oid)] = op.off
            elif code in (OP_REMOVE, OP_TRY_REMOVE):
                mk(op.cid, op.oid).wipe()
                sizes[(op.cid, op.oid)] = 0
            elif code == OP_CLONE:
                m = mk(op.cid, op.dest_oid)
                m.drop = False
                m.fresh = True
                m.full = True
                sizes[(op.cid, op.dest_oid)] = size_of(op.cid, op.oid)
            elif code == OP_COLL_MOVE_RENAME:
                mk(op.cid, op.oid).wipe()
                m = mk(op.dest_cid, op.dest_oid)
                m.drop = False
                m.fresh = True
                m.full = True
                sizes[(op.dest_cid, op.dest_oid)] = size_of(op.cid, op.oid)
                sizes[(op.cid, op.oid)] = 0
        return marks

    def _seal_rebuild(self, mark: _SealMark, size: Optional[int],
                      read_fn, old_blob: Optional[bytes]
                      ) -> Optional[bytes]:
        """New encoded seal record for one planned object, reading
        post-apply bytes via `read_fn(off, length)`.  None => the
        object is gone; delete its record.  Only extents intersecting
        the dirty range (plus coverage-change casualties: the tail
        extent when the size class moved, everything on a granularity
        change) are recomputed."""
        if mark.drop or size is None:
            return None
        E = self.csum_extent_size
        old = None
        if old_blob is not None and not mark.fresh and not mark.full:
            try:
                old = ExtentSeals.from_bytes(old_blob)
            except Exception:
                old = None
            if old is not None and old.extent_size != E:
                old = None  # granularity changed: full reseal
        n = (size + E - 1) // E
        old_n = len(old.crcs) if old is not None else 0
        crcs = list(old.crcs[:n]) if old is not None else []
        while len(crcs) < n:
            crcs.append(0)
        if old is None or mark.full or mark.lo is None:
            redo = list(range(n))
        else:
            lo = min(mark.lo, size)
            hi = min(mark.hi, size)
            todo = set(range(lo // E, min(n, (hi + E - 1) // E)))
            # the tail extent's coverage follows the object size: any
            # size-class change re-seals it, and extent indexes the old
            # record lacked are always computed fresh
            if n and old_n != n:
                todo.add(n - 1)
            todo.update(range(old_n, n))
            redo = sorted(todo)
        for i in redo:
            s = i * E
            crcs[i] = crc32c(read_fn(s, min(size, s + E) - s))
        return ExtentSeals(E, crcs).to_bytes()

    def stat(self, cid: Collection, oid: GHObject) -> int:
        """Returns size; raises NoSuchObject."""
        raise NotImplementedError

    def getattr(self, cid: Collection, oid: GHObject, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: Collection, oid: GHObject) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: Collection, oid: GHObject) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get_values(self, cid: Collection, oid: GHObject,
                        keys: List[str]) -> Dict[str, bytes]:
        omap = self.omap_get(cid, oid)
        return {k: omap[k] for k in keys if k in omap}

    def list_collections(self) -> List[Collection]:
        raise NotImplementedError

    def collection_exists(self, cid: Collection) -> bool:
        raise NotImplementedError

    def collection_list(self, cid: Collection) -> List[GHObject]:
        raise NotImplementedError
