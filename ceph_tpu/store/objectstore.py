"""ObjectStore — the abstract transactional object API.

Reference: src/os/ObjectStore.h + src/os/Transaction.cc. The contract
the OSD's PG engine is written against: named collections (one per PG)
holding objects with byte extents, xattrs, and an omap; all mutations
batched into atomic, ordered Transactions; reads are unordered.

A Transaction is an encodable op list (the reference's op codes at
src/os/ObjectStore.h Transaction::OP_*) so the same bytes can be
carried inside replication messages (the EC sub-write payload) and
replayed from the journal — exactly how the reference ships
transactions to replica shards.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ceph_tpu.core.encoding import Decoder, Encoder
from ceph_tpu.core.failpoint import failpoint


class StoreError(Exception):
    pass


class NoSuchObject(StoreError):
    pass


class NoSuchCollection(StoreError):
    pass


@dataclass(frozen=True, order=True)
class GHObject:
    """Object id within a collection (hobject_t/ghobject_t analog:
    reference src/common/hobject.h — name + key hash + snap + shard)."""

    name: str
    snap: int = -2  # -2 = head (CEPH_NOSNAP analog)
    shard: int = -1  # -1 = no shard (replicated); >=0 = EC shard id

    def encode(self, e: Encoder) -> None:
        e.string(self.name).s64(self.snap).s32(self.shard)

    @classmethod
    def decode(cls, d: Decoder) -> "GHObject":
        return cls(d.string(), d.s64(), d.s32())


@dataclass(frozen=True, order=True)
class Collection:
    """Collection id — one per PG (+ metadata col), e.g. '2.1f_head'."""

    name: str

    def encode(self, e: Encoder) -> None:
        e.string(self.name)

    @classmethod
    def decode(cls, d: Decoder) -> "Collection":
        return cls(d.string())


META_COLL = Collection("meta")

# Transaction op codes (subset of reference OP_* that the PG engine uses)
OP_NOP = 0
OP_TOUCH = 1
OP_WRITE = 2
OP_ZERO = 3
OP_TRUNCATE = 4
OP_REMOVE = 5
OP_SETATTRS = 6
OP_RMATTR = 7
OP_CLONE = 8
OP_MKCOLL = 9
OP_RMCOLL = 10
OP_OMAP_SETKEYS = 11
OP_OMAP_RMKEYS = 12
OP_OMAP_CLEAR = 13
OP_COLL_MOVE_RENAME = 14
OP_TRY_REMOVE = 15  # remove tolerating absence (for replica-shipped txns)


@dataclass
class Op:
    op: int
    cid: Collection
    oid: Optional[GHObject] = None
    off: int = 0
    length: int = 0
    data: bytes = b""
    attrs: Dict[str, bytes] = field(default_factory=dict)
    keys: List[str] = field(default_factory=list)
    dest_cid: Optional[Collection] = None
    dest_oid: Optional[GHObject] = None

    def encode(self, e: Encoder) -> None:
        e.start(1, 1)
        e.u8(self.op)
        self.cid.encode(e)
        e.optional(self.oid, lambda enc, o: o.encode(enc))
        # blob() materializes DeviceBuf payloads via their sanctioned
        # (accounted) wire view
        e.u64(self.off).u64(self.length).blob(self.data)
        e.mapping(self.attrs, lambda enc, k: enc.string(k),
                  lambda enc, v: enc.blob(v))
        e.seq(self.keys, lambda enc, k: enc.string(k))
        e.optional(self.dest_cid, lambda enc, c: c.encode(enc))
        e.optional(self.dest_oid, lambda enc, o: o.encode(enc))
        e.finish()

    @classmethod
    def decode(cls, d: Decoder) -> "Op":
        d.start(1)
        out = cls(
            op=d.u8(),
            cid=Collection.decode(d),
            oid=d.optional(GHObject.decode),
            off=d.u64(),
            length=d.u64(),
            data=d.blob(),
            attrs=d.mapping(lambda dd: dd.string(), lambda dd: dd.blob()),
            keys=d.seq(lambda dd: dd.string()),
            dest_cid=d.optional(Collection.decode),
            dest_oid=d.optional(GHObject.decode),
        )
        d.end()
        return out


class Transaction:
    """Atomic batch of mutations; encodable for journal + replication."""

    def __init__(self) -> None:
        self.ops: List[Op] = []

    def __len__(self) -> int:
        return len(self.ops)

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)

    # -- builders ---------------------------------------------------------
    def touch(self, cid: Collection, oid: GHObject) -> None:
        self.ops.append(Op(OP_TOUCH, cid, oid))

    def write(self, cid: Collection, oid: GHObject, off: int, data) -> None:
        """`data` may be bytes-like OR a DeviceBuf payload handle: the
        handle rides the op list un-materialized (bufferlist role) and
        becomes host bytes only at a sanctioned sink — store apply
        (`op_payload`) or wire serialization (`Op.encode`)."""
        if hasattr(data, "wire_view"):  # DeviceBuf: keep the handle
            self.ops.append(Op(OP_WRITE, cid, oid, off=off,
                               length=len(data), data=data))
            return
        self.ops.append(Op(OP_WRITE, cid, oid, off=off, length=len(data),
                           data=bytes(data)))

    def zero(self, cid: Collection, oid: GHObject, off: int, length: int) -> None:
        self.ops.append(Op(OP_ZERO, cid, oid, off=off, length=length))

    def truncate(self, cid: Collection, oid: GHObject, size: int) -> None:
        self.ops.append(Op(OP_TRUNCATE, cid, oid, off=size))

    def remove(self, cid: Collection, oid: GHObject) -> None:
        self.ops.append(Op(OP_REMOVE, cid, oid))

    def try_remove(self, cid: Collection, oid: GHObject) -> None:
        """Remove if present; no-op otherwise.  Replication ships
        primary-built transactions to replicas whose local existence may
        lag, so deletes must tolerate absence."""
        self.ops.append(Op(OP_TRY_REMOVE, cid, oid))

    def setattrs(self, cid: Collection, oid: GHObject, attrs: Dict[str, bytes]) -> None:
        self.ops.append(Op(OP_SETATTRS, cid, oid, attrs=dict(attrs)))

    def rmattr(self, cid: Collection, oid: GHObject, name: str) -> None:
        self.ops.append(Op(OP_RMATTR, cid, oid, keys=[name]))

    def clone(self, cid: Collection, src: GHObject, dst: GHObject) -> None:
        self.ops.append(Op(OP_CLONE, cid, src, dest_oid=dst))

    def create_collection(self, cid: Collection) -> None:
        self.ops.append(Op(OP_MKCOLL, cid))

    def remove_collection(self, cid: Collection) -> None:
        self.ops.append(Op(OP_RMCOLL, cid))

    def omap_setkeys(self, cid: Collection, oid: GHObject,
                     kv: Dict[str, bytes]) -> None:
        self.ops.append(Op(OP_OMAP_SETKEYS, cid, oid, attrs=dict(kv)))

    def omap_rmkeys(self, cid: Collection, oid: GHObject, keys: List[str]) -> None:
        self.ops.append(Op(OP_OMAP_RMKEYS, cid, oid, keys=list(keys)))

    def omap_clear(self, cid: Collection, oid: GHObject) -> None:
        self.ops.append(Op(OP_OMAP_CLEAR, cid, oid))

    def coll_move_rename(self, src_cid: Collection, src: GHObject,
                         dst_cid: Collection, dst: GHObject) -> None:
        self.ops.append(Op(OP_COLL_MOVE_RENAME, src_cid, src,
                           dest_cid=dst_cid, dest_oid=dst))

    # -- wire -------------------------------------------------------------
    def encode(self, e: Encoder) -> None:
        e.start(1, 1)
        e.seq(self.ops, lambda enc, op: op.encode(enc))
        e.finish()

    @classmethod
    def decode(cls, d: Decoder) -> "Transaction":
        d.start(1)
        t = cls()
        t.ops = d.seq(Op.decode)
        d.end()
        return t

    def to_bytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "Transaction":
        return cls.decode(Decoder(data))


def op_payload(op: Op, copy: bool = False):
    """A write op's payload as a host buffer for the store's apply —
    THE sanctioned materialization point of a device-resident payload
    (accounted by the DeviceBuf itself; see ceph_tpu/tpu/staging.py
    ownership rules).  ``copy=True`` for backends that RETAIN the
    buffer (blob stores): a view into a staging slot must never
    outlive the slot's release."""
    d = op.data
    if hasattr(d, "wire_view"):
        v = d.wire_view()
        return bytes(v) if copy else v
    return d


class ValidationOverlay:
    """Lazy existence overlay for validate-then-apply transactions.

    Subclasses provide base-state lookups (`_base_coll`, `_base_obj`,
    `_base_count`); the overlay layers this transaction's pending
    effects on top WITHOUT materializing the store (each op validates in
    O(1); only RMCOLL's emptiness check pays a per-collection count, and
    only when an RMCOLL actually appears in the transaction)."""

    def __init__(self) -> None:
        self._colls: Dict[str, bool] = {}
        self._objs: Dict[Tuple[str, GHObject], bool] = {}
        self._count_delta: Dict[str, int] = {}
        self._fresh: Dict[str, bool] = {}  # created in this txn => base 0

    # -- base state hooks --------------------------------------------------
    def _base_coll(self, name: str) -> bool:
        raise NotImplementedError

    def _base_obj(self, name: str, oid: GHObject) -> bool:
        raise NotImplementedError

    def _base_count(self, name: str) -> int:
        raise NotImplementedError

    # -- overlay queries ---------------------------------------------------
    def coll_exists(self, name: str) -> bool:
        if name in self._colls:
            return self._colls[name]
        return self._base_coll(name)

    def obj_exists(self, name: str, oid: GHObject) -> bool:
        key = (name, oid)
        if key in self._objs:
            return self._objs[key]
        return self._base_obj(name, oid)

    def coll_empty(self, name: str) -> bool:
        base = 0 if self._fresh.get(name) else self._base_count(name)
        return base + self._count_delta.get(name, 0) <= 0

    # -- overlay mutations -------------------------------------------------
    def add_coll(self, name: str) -> None:
        self._colls[name] = True
        self._fresh[name] = True
        self._count_delta[name] = 0

    def rm_coll(self, name: str) -> None:
        self._colls[name] = False

    def create_obj(self, name: str, oid: GHObject) -> None:
        if not self.obj_exists(name, oid):
            self._objs[(name, oid)] = True
            self._count_delta[name] = self._count_delta.get(name, 0) + 1

    def rm_obj(self, name: str, oid: GHObject) -> None:
        if self.obj_exists(name, oid):
            self._objs[(name, oid)] = False
            self._count_delta[name] = self._count_delta.get(name, 0) - 1


def validate_op(op: Op, ov: ValidationOverlay) -> None:
    """Shared validation pass giving queue_transaction all-or-nothing
    semantics: raise exactly the errors apply would, before any backend
    mutates."""
    code = op.op
    cname = op.cid.name

    def need_coll():
        if not ov.coll_exists(cname):
            raise NoSuchCollection(cname)

    def need_obj():
        need_coll()
        if not ov.obj_exists(cname, op.oid):
            raise NoSuchObject(f"{cname}/{op.oid.name}")

    if code == OP_NOP:
        return
    if code == OP_MKCOLL:
        if ov.coll_exists(cname):
            raise StoreError(f"collection exists: {cname}")
        ov.add_coll(cname)
        return
    if code == OP_RMCOLL:
        need_coll()
        if not ov.coll_empty(cname):
            raise StoreError(f"collection not empty: {cname}")
        ov.rm_coll(cname)
        return
    if code in (OP_TOUCH, OP_WRITE, OP_ZERO, OP_TRUNCATE, OP_SETATTRS,
                OP_OMAP_SETKEYS):
        need_coll()
        ov.create_obj(cname, op.oid)
        return
    if code in (OP_REMOVE,):
        need_obj()
        ov.rm_obj(cname, op.oid)
        return
    if code == OP_TRY_REMOVE:
        need_coll()
        ov.rm_obj(cname, op.oid)
        return
    if code in (OP_RMATTR, OP_OMAP_RMKEYS, OP_OMAP_CLEAR):
        need_obj()
        return
    if code == OP_CLONE:
        need_obj()
        ov.create_obj(cname, op.dest_oid)
        return
    if code == OP_COLL_MOVE_RENAME:
        need_obj()
        if not ov.coll_exists(op.dest_cid.name):
            raise NoSuchCollection(op.dest_cid.name)
        ov.rm_obj(cname, op.oid)
        ov.create_obj(op.dest_cid.name, op.dest_oid)
        return
    raise StoreError(f"unknown op {code}")


class CommitPipeline:
    """Group-commit thread shared by the durable backends — the
    FileJournal group-commit / BlueStore `_kv_sync_thread` role.

    Submitters append their completion to the in-memory pending batch
    and return; the commit thread swaps the whole batch out (double
    buffering: batch N+1 collects while batch N syncs), runs the
    store's `sync_fn` ONCE for everything in it, then fires the
    completions in submission (WAL-seq) order.  A 16-deep writer queue
    therefore pays one fsync per BATCH, not one per transaction, and
    callers with no callback block on an event submitted through the
    same pipeline — so concurrent synchronous writers share fsyncs too.

    `freeze()`/`thaw()` hold the commit thread between WAL append and
    the batched sync: the crash-safety tests use the window to model a
    kill mid-batch (records appended, nothing fsynced, no completion
    fired).
    """

    def __init__(self, sync_fn: Callable[[], None],
                 perf=None, log: Optional[Callable[[str], None]] = None
                 ) -> None:
        self._sync_fn = sync_fn
        self._perf = perf  # PerfCounters with commit_batch/commit_lat
        self._log = log or (lambda s: print(f"store-commit: {s}",
                                            file=sys.stderr))
        self._cond = threading.Condition()
        self._pending: List[Tuple[int, Callable[[], None]]] = []
        self._frozen = False
        self._stopping = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stopping = False
            self._thread = threading.Thread(
                target=self._run, name="store-commit", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        """Drain every pending completion (final sync included), then
        join the thread — the umount path."""
        with self._cond:
            if self._thread is None:
                return
            self._frozen = False
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
        self._thread = None

    def in_commit_thread(self) -> bool:
        return threading.current_thread() is self._thread

    # -- crash-window test hook -------------------------------------------
    def freeze(self) -> None:
        with self._cond:
            self._frozen = True

    def thaw(self) -> None:
        with self._cond:
            self._frozen = False
            self._cond.notify_all()

    # -- submission -------------------------------------------------------
    def submit(self, seq: int, on_commit: Callable[[], None]) -> None:
        """Stage a completion.  Callers submit while still holding the
        store lock that ordered their WAL append, so the pending list
        order IS WAL order.  A submit racing stop() (writer vs umount)
        commits inline rather than stranding the completion forever."""
        with self._cond:
            if self._thread is not None and not self._stopping:
                self._pending.append((seq, on_commit))
                self._cond.notify_all()
                return
        try:
            self._sync_fn()
        except Exception as e:
            self._log(f"inline sync during stop failed: {e!r}")
        on_commit()

    def flush(self) -> None:
        """Block until everything submitted so far has committed."""
        done = threading.Event()
        self.submit(-1, done.set)
        done.wait()

    # -- the commit thread ------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: (self._pending and not self._frozen)
                    or self._stopping)
                if self._stopping and (not self._pending or self._frozen):
                    return
                batch, self._pending = self._pending, []
            # the WAL-appended-nothing-synced kill window: a schedule
            # can hold/kill here to model a crash mid-batch
            failpoint("store.commit_batch.sync", n=len(batch))
            t0 = time.perf_counter()
            try:
                self._sync_fn()
            except Exception as e:
                # a failing sync must not strand submitters (there is
                # no error channel on on_commit); the store's state is
                # applied, durability degrades to wal_sync=False level
                # — but degraded durability must be LOUD
                self._log(f"batch sync failed: {e!r} (completions "
                          "fire; durability degraded this batch)")
            for _seq, cb in batch:
                try:
                    cb()
                except Exception as e:
                    # one completion's bug must not starve the rest
                    self._log(f"on_commit callback raised: {e!r}")
            if self._perf is not None:
                self._perf.hinc("commit_batch", len(batch))
                self._perf.tinc("commit_lat", time.perf_counter() - t0)


class ObjectStore:
    """Abstract backend. Writes go through queue_transaction; reads are
    direct.  `queue_transaction(t, on_commit)` validates and applies
    synchronously (read-your-writes holds on return) but DEFERS
    durability: `on_commit` fires from the backend's commit thread once
    the transaction is on stable storage, and many transactions ride
    one sync (group commit).  With no callback the call blocks until
    commit — the pre-async semantics — while still sharing the batched
    sync with concurrent writers.  Returns the transaction's WAL/commit
    sequence number."""

    # True on backends whose read path verifies data against at-rest
    # checksums itself (BlockStore: crc32c per stored block, raises on
    # mismatch).  Lets consumers serve ranged reads without a
    # whole-object copy purely to re-verify an application-level crc.
    checksums_at_rest = False

    # -- silent-corruption injection (the scrub/repair test seam) ---------
    # Two routes corrupt the bytes a read SERVES without touching what
    # is stored (silent at-rest rot, invisible to everything but a
    # byte-reading deep scrub):
    #   - the store.corrupt_chunk / store.corrupt_xattr failpoints
    #     (seeded, match-scoped — the chaos-schedule route), and
    #   - debug_inject_data_err marks (conf store_debug_inject_data_err
    #     enables the mechanism, like the PR 7 read-err hook) — the
    #     deterministic single-object route.  A REWRITE of a marked
    #     object clears its mark (the bad media got overwritten), so
    #     corrupt -> deep-scrub detect -> auto-repair -> clean re-scrub
    #     is a closed deterministic loop.
    debug_data_err_enabled = False

    def debug_inject_data_err(self, cid: Collection, oid: GHObject) -> None:
        if not hasattr(self, "_data_err_objs"):
            self._data_err_objs: set = set()
        self._data_err_objs.add((cid.name, oid.name, oid.shard))

    def debug_clear_data_err(self) -> None:
        if hasattr(self, "_data_err_objs"):
            self._data_err_objs.clear()

    def _note_data_write(self, cid: Collection, oid: GHObject) -> None:
        """Called by backends when an object's DATA is rewritten or the
        object removed: overwriting the media drops its data-err mark."""
        marks = getattr(self, "_data_err_objs", None)
        if marks:
            marks.discard((cid.name, oid.name, oid.shard))

    def _read_filter(self, data, cid: Collection, oid: GHObject):
        """The read-boundary corruption seam: every backend routes its
        read() return through here.  Disarmed cost is one enabled()
        check + one class-attr load."""
        from ceph_tpu.core import failpoint as fp

        if fp.enabled("store.corrupt_chunk") and fp.failpoint(
                "store.corrupt_chunk", oid=oid.name, coll=cid.name,
                shard=str(oid.shard)) is fp.CORRUPT:
            data = fp.corrupt_bytes(
                data, f"{cid.name}/{oid.name}/{oid.shard}")
        if self.debug_data_err_enabled:
            marks = getattr(self, "_data_err_objs", None)
            if marks and (cid.name, oid.name, oid.shard) in marks:
                data = fp.corrupt_bytes(
                    data, f"err/{cid.name}/{oid.name}/{oid.shard}")
        return data

    def _attr_filter(self, val, cid: Collection, oid: GHObject,
                     name: str):
        """getattr() twin of _read_filter (store.corrupt_xattr)."""
        from ceph_tpu.core import failpoint as fp

        if fp.enabled("store.corrupt_xattr") and fp.failpoint(
                "store.corrupt_xattr", oid=oid.name, coll=cid.name,
                shard=str(oid.shard), attr=name) is fp.CORRUPT:
            val = fp.corrupt_bytes(
                val, f"{cid.name}/{oid.name}/{oid.shard}/{name}")
        return val

    # -- lifecycle --------------------------------------------------------
    def mkfs(self) -> None:
        raise NotImplementedError

    def mount(self) -> None:
        raise NotImplementedError

    def umount(self) -> None:
        raise NotImplementedError

    # -- writes -----------------------------------------------------------
    def queue_transaction(self, t: Transaction,
                          on_commit: Optional[Callable[[], None]] = None
                          ) -> int:
        raise NotImplementedError

    def statfs(self) -> Tuple[int, int]:
        """(used_bytes, total_bytes) — the reference ObjectStore::statfs.
        Backends without a fixed device report a nominal capacity."""
        raise NotImplementedError

    # -- reads ------------------------------------------------------------
    def exists(self, cid: Collection, oid: GHObject) -> bool:
        raise NotImplementedError

    def read(self, cid: Collection, oid: GHObject, off: int = 0,
             length: int = 0) -> bytes:
        """length==0 → read to end."""
        raise NotImplementedError

    def stat(self, cid: Collection, oid: GHObject) -> int:
        """Returns size; raises NoSuchObject."""
        raise NotImplementedError

    def getattr(self, cid: Collection, oid: GHObject, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: Collection, oid: GHObject) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: Collection, oid: GHObject) -> Dict[str, bytes]:
        raise NotImplementedError

    def omap_get_values(self, cid: Collection, oid: GHObject,
                        keys: List[str]) -> Dict[str, bytes]:
        omap = self.omap_get(cid, oid)
        return {k: omap[k] for k in keys if k in omap}

    def list_collections(self) -> List[Collection]:
        raise NotImplementedError

    def collection_exists(self, cid: Collection) -> bool:
        raise NotImplementedError

    def collection_list(self, cid: Collection) -> List[GHObject]:
        raise NotImplementedError
